"""Join routing + union: host N:1 / vectorized N:M / device kernel /
fused in-fragment lookup joins.

Reference parity: ``src/carnot/exec/equijoin_node.cc`` (build+probe hash
join) and ``union_node.cc`` (k-way ordered merge). The TPU redesign
routes by shape, backend and *ingest sketches* instead of always
hash-joining (see docs/JOINS.md for the full strategy matrix):

- small unique-key inner/left joins run a host dict join,
- large N:M joins run a device kernel — single-shot sort-based, or the
  windowed drivers (sorted-probe / radix-partitioned) that stage the
  build side once and stream probe windows through the prefetch
  pipeline — or a native/numpy hash join on the CPU backend (where XLA
  sorts are the wrong tool),
- N:1 joins against a dense-domain build side fuse INTO the probe
  stream's fragment as device gathers (``try_fused_join``) so output
  rows never materialize host-side.

Sketch-guided routing (``choose_join_strategy``): the table store's
ingest sketches (``table_store/sketches.py`` — row counts, HLL NDV,
zone maps) pick the build side, estimate the join's output cardinality
to size the initial output capacity (instead of climbing the
overflow-doubling ladder, one jit compile per rung), choose single-shot
vs windowed vs radix, and skip probe windows whose key range cannot
intersect the build side. Final capacities persist per plan hash on
the engine (``Engine._join_capacity_cache``) so repeated queries start
at the right rung; ``pixie_join_capacity_retries_total`` counts the
residual retries.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass

import numpy as np

from ..types.batch import HostBatch, bucket_capacity
from ..types.dtypes import DataType
from ..types.strings import NULL_ID, StringDictionary
from .fragment import compile_fragment_cached as compile_fragment
from .plan import AggOp, JoinOp, LimitOp, LookupJoinOp, MapOp
from .stream import (
    QueryError,
    _chain_out_relation,
    _col,
    _Stream,
    _stream_col_stats,
)


def _key_tuples(hb: HostBatch, on, remaps):
    keys = []
    for c in on:
        ids = hb.cols[c][0]
        if c in remaps:
            # Null string ids (-1) must stay null, not wrap to the last entry.
            ids = np.where(
                ids >= 0, remaps[c][np.clip(ids, 0, None)], NULL_ID
            ).astype(ids.dtype)
        keys.append(ids)
    extra = [hb.cols[c][1] for c in on if len(hb.cols[c]) > 1]
    return list(zip(*(list(k) for k in (keys + extra)))) if keys else []


# Inputs smaller than this run the host dict join (when N:1 applies);
# larger inputs and right/outer/N:M joins go to the device kernel.
DEVICE_JOIN_MIN_ROWS = 1 << 15

# The windowed driver prefers the radix-partitioned probe over the full
# searchsorted once the build side clears this many rows (below it the
# partition bookkeeping costs more than the shorter binary search saves).
RADIX_MIN_BUILD_ROWS = 1 << 16


# -- sketch-backed side statistics -------------------------------------------
@dataclass
class JoinSideStats:
    """What routing knows about one join input without touching data.

    ``lo``/``hi``/``ndv`` describe the SINGLE key column when the join
    key is one single-plane INT64/STRING column (the packed-id fast
    path); multi-key joins carry rows only. All fields are conservative
    estimates: rows is an upper bound when the stream filters, NDV is
    HLL (~3% error), zone bounds never shrink under expiry.
    """

    rows: int
    lo: int | None = None
    hi: int | None = None
    ndv: int | None = None
    origin: str = "none"  # 'sketch' | 'scan' | 'none'


def _chain_key_sources(chain, on_cols):
    """Trace join key columns back through a stream's op chain to source
    table columns, or None when any op rewrites/aggregates them (then
    ingest sketches no longer describe the key values).

    The chain is in APPLICATION order; tracing an output name back to
    its source walks it in reverse (the last Map renamed it most
    recently)."""
    from .plan import FilterOp, LimitOp, MapOp, trace_map_renames

    mapping = {c: c for c in on_cols}
    for op in reversed(chain):
        if isinstance(op, (FilterOp, LimitOp)):
            continue  # values survive, rows only shrink
        if isinstance(op, MapOp):
            mapping = trace_map_renames(op, mapping)
            if mapping is None:
                return None
        else:
            return None
    return mapping


def stream_join_stats(res, on_cols) -> JoinSideStats | None:
    """Ingest-sketch stats for one join input (``results[nid]`` BEFORE
    materialization), or None when the input is not a table-backed
    stream with the key columns passing through unmodified."""
    if not isinstance(res, _Stream) or not isinstance(res.source, list):
        return None
    mapping = _chain_key_sources(res.chain, on_cols)
    if mapping is None:
        return None
    tablets = [t for t in res.source if getattr(t, "sketches", None)]
    if not tablets or len(tablets) != len(res.source):
        return None
    rows = sum(t.sketches.rows for t in tablets)
    stats = JoinSideStats(rows=rows, origin="sketch")
    if len(on_cols) == 1:
        src = mapping[on_cols[0]]
        sks = [t.sketches.col(src) for t in tablets]
        if all(s is not None and s.rows for s in sks):
            stats.lo = min(s.lo for s in sks)
            stats.hi = max(s.hi for s in sks)
            if len(sks) == 1:
                stats.ndv = sks[0].ndv
            else:
                # Cross-tablet NDV: HLL registers merge exactly
                # (elementwise max) — never sum per-tablet estimates.
                from ..ops.hll import hll_estimate_np

                reg = sks[0].registers.copy()
                for s in sks[1:]:
                    np.maximum(reg, s.registers, out=reg)
                stats.ndv = max(1, min(hll_estimate_np(reg), rows))
    return stats


def _scan_side_stats(keys: np.ndarray) -> JoinSideStats:
    """Fallback stats computed from packed key ids: exact zone bounds
    (one vectorized pass) + HLL NDV (one hash pass). Used when ingest
    sketches don't cover an input; ~10ms per 4M keys, amortized against
    the device join it steers."""
    from ..ops.hll import hll_estimate_np, hll_init_np, hll_update_np

    n = len(keys)
    if n == 0:
        return JoinSideStats(rows=0, origin="scan")
    reg = hll_init_np()
    hll_update_np(reg, keys)
    return JoinSideStats(
        rows=n, lo=int(keys.min()), hi=int(keys.max()),
        ndv=max(1, min(hll_estimate_np(reg), n)), origin="scan",
    )


# -- learned capacity + retry accounting -------------------------------------
# (mode, plan-hash, node) -> final (post-overflow) output capacity of a
# join node, stored on ``Engine._join_capacity_cache``: a repeated query
# starts at the rung its last run finished on instead of re-climbing the
# doubling ladder (one jit compile per rung, paid MID-query in the
# synchronous dispatch regime). Engine-scoped because the plan
# fingerprint hashes operators, not data — two engines running the same
# script over different tables must not seed each other's rungs.
# Engine-less driver calls pass cap_key=None and learn nothing.
#
# Eviction is LRU (python dicts are insertion-ordered; a hit re-inserts
# its key at the back) with a hard size cap: pxbound's plan-time
# pre-sizing makes retention past the cap pure memory loss — under many
# distinct plan hashes (dashboard fleets, ephemeral test engines) an
# unbounded dict is a slow leak. Evictions are counted
# (pixie_join_capacity_evictions_total): a hot cache churning entries
# means the cap is too small for the plan population, worth seeing.
_CAPACITY_LOCK = threading.Lock()
_CAPACITY_CACHE_MAX = 4096


def _eviction_counter():
    from ..services.observability import default_counter

    return default_counter(
        "pixie_join_capacity_evictions_total",
        "Learned join-capacity entries evicted by the per-engine LRU "
        "size cap",
    )


def learned_capacity(engine, cap_key) -> int | None:
    cache = getattr(engine, "_join_capacity_cache", None)
    if cap_key is None or cache is None:
        return None
    with _CAPACITY_LOCK:
        cap = cache.get(cap_key)
        if cap is not None:
            # Refresh recency: re-insert at the back of the order.
            del cache[cap_key]
            cache[cap_key] = cap
        return cap


def remember_capacity(engine, cap_key, capacity: int) -> None:
    cache = getattr(engine, "_join_capacity_cache", None)
    if cap_key is None or cache is None:
        return
    evicted = 0
    with _CAPACITY_LOCK:
        cache.pop(cap_key, None)
        while len(cache) >= _CAPACITY_CACHE_MAX:
            cache.pop(next(iter(cache)))  # LRU: oldest-inserted first
            evicted += 1
        cache[cap_key] = capacity
    if evicted:
        _eviction_counter().inc(evicted)


def _retry_counter(engine):
    """pixie_join_capacity_retries_total: overflow-retry kernel re-runs
    (each costs a fresh jit compile mid-query). The bench gate asserts
    this stays 0 on the standard shapes — the sketch estimate plus the
    learned-capacity cache should make retries exceptional."""
    tracer = getattr(engine, "tracer", None)
    if tracer is not None:
        reg = tracer.registry
    else:  # engine stand-ins (tests) and direct driver calls
        from ..services.observability import default_registry as reg
    return reg.counter(
        "pixie_join_capacity_retries_total",
        "Device-join output-capacity overflow retries (kernel re-runs "
        "at a doubled capacity, one fresh jit compile each)",
    )


def estimate_join_capacity(probe_rows: int, build: JoinSideStats | None,
                           probe: JoinSideStats | None, how: str,
                           overlap: float | None = None) -> int:
    """Estimated output rows for ``probe_rows`` probe rows against the
    build side: fan-out = build_rows / NDV(build), scaled by the zone
    overlap fraction of the probe range (keys outside the build side's
    [lo, hi] cannot match). ``overlap`` overrides the fraction when the
    caller knows it more precisely (the windowed driver passes the WORST
    surviving window's overlap — the whole-probe fraction would shrink
    the per-window estimate for clustered probes whose surviving windows
    each overlap fully). Conservative where sketches are missing."""
    from ..config import get_flag

    safety = float(get_flag("join_capacity_safety"))
    if build is None or not build.ndv:
        # No build stats: the historical default (2x probe rows).
        return bucket_capacity(max(2 * probe_rows, 1))
    fanout = build.rows / max(build.ndv, 1)
    if overlap is None:
        overlap = 1.0
        if (
            probe is not None and probe.lo is not None
            and probe.hi is not None
            and build.lo is not None and build.hi is not None
            and probe.hi > probe.lo
        ):
            inter = min(probe.hi, build.hi) - max(probe.lo, build.lo) + 1
            overlap = max(0.0, min(1.0, inter / (probe.hi - probe.lo + 1)))
    est = probe_rows * fanout * overlap
    if how in ("left", "outer"):
        est = max(est, probe_rows)  # unmatched rows still emit
    return bucket_capacity(max(int(est * safety) + 1, 1 << 10))


# -- strategy choice ---------------------------------------------------------
@dataclass
class JoinDecision:
    """Routing outcome, recorded on ``engine.last_join_decision`` so
    bench and tests can see which strategy served a query."""

    strategy: str  # degenerate|host_dict|host_hash|single|sorted|radix
    swap: bool = False  # probe the RIGHT side (inner only)
    capacity: int | None = None  # initial output capacity (per window)
    window_rows: int = 0  # probe rows per dispatch (windowed paths)
    zone_skip: bool = False
    retries: int = 0  # overflow retries actually paid
    skipped_windows: int = 0
    reason: str = ""


def choose_join_strategy(left: HostBatch, right: HostBatch, op: JoinOp,
                         engine=None, left_stats=None, right_stats=None,
                         device_only: bool = False) -> JoinDecision:
    """Pick the N:M execution strategy from shape, backend and sketches.

    The host-dict (small unique-key) and degenerate (empty-side) routes
    are resolved by the dispatcher before this is called; this chooses
    among the bulk N:M paths. ``device_only`` skips the CPU-backend
    host-hash route — direct ``_join_device`` callers (tests, forced
    device runs) always get a device kernel. See docs/JOINS.md for the
    matrix.
    """
    import jax

    from ..config import get_flag

    forced = str(get_flag("join_strategy"))
    window_rows = int(get_flag("join_probe_window_rows"))
    radix_bits = int(get_flag("join_radix_bits"))
    zone_skip = bool(get_flag("join_zone_skip"))
    tpu = jax.default_backend() == "tpu"

    if not device_only and op.how in ("inner", "left") and (
        forced == "host" or (forced == "auto" and not tpu)
    ):
        # XLA CPU sorts make the device kernels a regression there; the
        # native build+probe hash join is the CPU-backend fast path.
        return JoinDecision(
            strategy="host_hash", zone_skip=zone_skip,
            reason="cpu backend" if forced == "auto" else "forced",
        )

    # Build-side swap (inner only: left/right/outer pin the null side).
    # Cost model: the build side is sorted/partitioned and resident for
    # the whole query, the probe side streams — so build = the side with
    # the LOWER rows x log2(NDV) sort cost, with hysteresis (4x) so
    # near-balanced inputs keep the stable left-probe order.
    swap = False
    if op.how == "inner" and right.length > 4 * left.length:
        import math

        def score(n_rows, st):
            ndv = st.ndv if st is not None and st.ndv else max(n_rows, 2)
            return n_rows * math.log2(max(ndv, 2))

        swap = score(left.length, left_stats) < score(right.length,
                                                      right_stats) / 4
    probe_rows = right.length if swap else left.length

    windowable = (
        op.how in ("inner", "left") and window_rows > 0
        and probe_rows > window_rows
    )
    if forced in ("sorted", "radix", "single"):
        strategy = forced
        if forced != "single" and op.how not in ("inner", "left"):
            strategy = "single"  # right/outer need the global kernel
    elif not windowable:
        strategy = "single"
    else:
        build_rows = left.length if swap else right.length
        strategy = (
            "radix"
            if radix_bits > 0 and build_rows >= RADIX_MIN_BUILD_ROWS
            else "sorted"
        )
    return JoinDecision(
        strategy=strategy, swap=swap and strategy != "single",
        window_rows=window_rows, zone_skip=zone_skip,
        reason="forced" if forced != "auto" else "auto",
    )


def _join_dispatch(left: HostBatch, right: HostBatch, op: JoinOp,
                   engine=None, left_stats=None, right_stats=None,
                   cap_key=None, planned_capacity=None) -> HostBatch:
    """Route a join: host N:1 dict, native host hash, or a device
    kernel strategy chosen by ``choose_join_strategy``.

    Reference: ``equijoin_node.cc`` always hash-joins; here small unique-
    key inner/left joins (the post-agg common case) stay on host, and
    everything else routes by shape/backend/sketches. ``engine`` (when
    the call comes from a query) carries the pipeline depth and the
    per-query cancel handle into the windowed device drivers;
    ``left_stats``/``right_stats`` are ingest-sketch
    :class:`JoinSideStats`; ``cap_key`` keys the learned-capacity cache.
    """
    if len(op.left_on) != len(op.right_on):
        raise QueryError("join key arity mismatch")
    small = left.length + right.length < DEVICE_JOIN_MIN_ROWS
    if op.how in ("inner", "left") and small:
        try:
            out = _join_host(left, right, op)
            if engine is not None:
                engine.last_join_decision = JoinDecision(
                    strategy="host_dict", reason="small unique-key build"
                )
            return out
        except _BuildNotUnique:
            pass  # N:M fan-out -> bulk strategies
    if left.length == 0 or right.length == 0:
        if engine is not None:
            engine.last_join_decision = JoinDecision(
                strategy="degenerate", reason="empty side"
            )
        return _join_degenerate(left, right, op)

    decision = choose_join_strategy(
        left, right, op, engine, left_stats, right_stats
    )
    if engine is not None:
        engine.last_join_decision = decision
    if decision.strategy == "host_hash":
        return _join_host_nm(left, right, op, right_stats, decision)
    return _join_device(left, right, op, engine, decision,
                        left_stats, right_stats, cap_key,
                        planned_capacity=planned_capacity)


class _BuildNotUnique(Exception):
    pass


def _align_join_dicts(left, right, op):
    """String-dictionary id remaps so key ids compare across sides.

    Returns (l_remap, r_remap, key_dicts): key_dicts maps a left key
    column to the merged dictionary (union preserves left ids, so pair
    rows stay valid and coalesced build-side ids land past them).
    """
    l_remap: dict = {}
    r_remap: dict = {}
    key_dicts: dict = {}
    for lc, rc in zip(op.left_on, op.right_on):
        ld, rd = left.dicts.get(lc), right.dicts.get(rc)
        if ld is not None and rd is not None and ld is not rd:
            merged, rl, rr = ld.union(rd)
            l_remap[lc], r_remap[rc] = rl, rr
            key_dicts[lc] = merged
    return l_remap, r_remap, key_dicts


def _join_out_schema(left, right, op):
    """(out_rel, ordered (side, src_col) pairs) for join output columns."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    src = [("l", c) for c in left.relation.column_names] + [
        ("r", c) for c in right.relation.column_names if c not in op.right_on
    ]
    return out_rel, src


def _join_degenerate(left, right, op: JoinOp) -> HostBatch:
    """Joins where one side is empty (device kernel needs real rows)."""
    out_rel, src = _join_out_schema(left, right, op)
    if op.how == "inner" or (op.how == "left" and left.length == 0) or (
        op.how == "right" and right.length == 0
    ):
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    elif op.how in ("left", "outer") and right.length == 0:
        keep_l, keep_r = np.arange(left.length), np.full(left.length, -1)
    elif op.how in ("right", "outer") and left.length == 0:
        keep_l, keep_r = np.full(right.length, -1), np.arange(right.length)
    else:  # outer with one side non-empty handled above; both empty:
        keep_l = keep_r = np.zeros(0, dtype=np.int64)
    _, r_remap, key_dicts = _align_join_dicts(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        keep_l, keep_l >= 0, keep_r, keep_r >= 0,
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _assemble_join(left, right, op, out_rel, src, l_idx, l_take, r_idx, r_take,
                   r_remap=None, key_dicts=None):
    """Gather output columns from per-row indices + take masks.

    Join key columns coalesce (SQL USING semantics): a right/outer extra
    row — whose probe side is null — takes its key from the build side,
    remapped into the merged dictionary for strings.
    """
    r_remap = r_remap or {}
    key_dicts = key_dicts or {}
    key_map = dict(zip(op.left_on, op.right_on))
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for side, c in src:
        n = next(names)
        hb = left if side == "l" else right
        idx = l_idx if side == "l" else r_idx
        take = l_take if side == "l" else r_take
        rc = key_map.get(c) if side == "l" else None
        nullv = NULL_ID if hb.relation.col_type(c) == DataType.STRING else 0
        planes = []
        for pi, p in enumerate(hb.cols[c]):
            if len(p) == 0:
                taken = np.full(len(idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(idx, 0, len(p) - 1)]
            if not take.all():
                if rc is not None:
                    q = right.cols[rc][pi]
                    if pi == 0 and rc in r_remap:
                        q = np.where(
                            q >= 0, r_remap[rc][np.clip(q, 0, None)], NULL_ID
                        ).astype(q.dtype)
                    alt = (
                        np.full(len(r_idx), nullv, dtype=p.dtype)
                        if len(q) == 0
                        else q[np.clip(r_idx, 0, len(q) - 1)]
                    )
                    taken = np.where(
                        take, taken, np.where(r_take, alt, nullv)
                    ).astype(p.dtype)
                else:
                    taken = np.where(take, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in hb.dicts:
            out_dicts[n] = (
                key_dicts.get(c, hb.dicts[c]) if side == "l" else hb.dicts[c]
            )
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _join_key_planes(hb, cols, remaps):
    planes = []
    for c in cols:
        for i, p in enumerate(hb.cols[c]):
            if i == 0 and c in remaps:
                p = np.where(
                    p >= 0, remaps[c][np.clip(p, 0, None)], NULL_ID
                ).astype(p.dtype)
            planes.append(p)
    return planes


@functools.lru_cache(maxsize=64)
def _device_join_cache(n_build, n_probe, dtypes, capacity, how):
    """One jitted kernel per (bucketed shapes, key dtypes, capacity, how).
    Tracked in the program registry (exec/programs.py): the lru key
    params fully determine the traced program, so they ARE the program
    key — compile wall-time, XLA cost/memory analysis and hit counts
    land in /debug/programz and ``__programs__``."""
    import jax

    from ..ops.join import device_join
    from .programs import default_program_registry

    fn = jax.jit(
        lambda bk, bv, pk, pv: device_join(bk, bv, pk, pv, capacity, how)
    )
    return default_program_registry().wrap(
        fn, "join_single_shot",
        ("join", "single", n_build, n_probe, dtypes, capacity, how),
        f"single nb={n_build} np={n_probe} cap={capacity} {how}",
    )


@functools.lru_cache(maxsize=64)
def _probe_sorted_cache(n_build_cap, n_probe_cap, capacity, how):
    """One jitted presorted-probe kernel per (bucketed shapes, capacity,
    how); the sorted build side and its row count are runtime args, so
    every probe window of a query (and across queries of the same
    shapes) reuses one program. Registry-tracked (see
    ``_device_join_cache``)."""
    import jax

    from ..ops.join import probe_sorted_join
    from .programs import default_program_registry

    fn = jax.jit(
        lambda sbk, rb, pk, pv: probe_sorted_join(sbk, rb, pk, pv, capacity, how)
    )
    return default_program_registry().wrap(
        fn, "join_probe_sorted",
        ("join", "sorted", n_build_cap, n_probe_cap, capacity, how),
        f"sorted nb={n_build_cap} w={n_probe_cap} cap={capacity} {how}",
    )


@functools.lru_cache(maxsize=64)
def _radix_probe_cache(n_build_cap, n_probe_cap, capacity, how, radix_bits,
                       steps):
    """One jitted radix-partitioned probe kernel per (bucketed shapes,
    capacity, how, partition count, search depth); the partitioned build
    keys and offsets are runtime args — see ``_probe_sorted_cache``.
    Registry-tracked (see ``_device_join_cache``)."""
    import jax

    from ..ops.join import radix_probe_join
    from .programs import default_program_registry

    fn = jax.jit(
        lambda sbk, starts, pk, pv: radix_probe_join(
            sbk, starts, pk, pv, capacity, how, radix_bits, steps
        )
    )
    return default_program_registry().wrap(
        fn, "join_probe_radix",
        ("join", "radix", n_build_cap, n_probe_cap, capacity, how,
         radix_bits, steps),
        f"radix nb={n_build_cap} w={n_probe_cap} cap={capacity} {how} "
        f"bits={radix_bits}",
    )


def _window_zones(keys: np.ndarray, window_rows: int):
    """(lo[W], hi[W]) per probe window — one vectorized pass (the
    windowed drivers' exact zone maps; ingest sketches only gate whether
    this pass is worth running)."""
    n = len(keys)
    offs = np.arange(0, n, window_rows)
    return (
        np.minimum.reduceat(keys, offs),
        np.maximum.reduceat(keys, offs),
    )


def _join_device_windowed(left: HostBatch, right: HostBatch, op: JoinOp,
                          window_rows: int, engine=None, decision=None,
                          left_stats=None, right_stats=None,
                          cap_key=None) -> HostBatch:
    """Multi-window device join driver (inner/left N:M).

    The build side is packed to comparable int64 key ids, then sorted
    (``strategy="sorted"``) or radix-partitioned by splitmix64 hash
    (``strategy="radix"``) and staged on device ONCE per query (the
    fused-join ``__side__`` discipline: a query-constant table rides as
    a reused runtime arg, never re-``device_put`` per window). Probe
    windows then stream through the window-prefetch pipeline, so staging
    window N+1 overlaps the join kernel on window N. Without a build-
    side swap, output rows are bit-identical to the single-shot
    kernel's: windows emit in probe order, and matches within a probe
    row follow build order on every path (both partitionings are stable
    on equal keys). A swap emits the same row multiset in build-major
    order instead — joins carry no row-order contract.

    Sketch guidance: the initial output capacity comes from the learned
    per-plan cache, else the NDV-based cardinality estimate — NOT a
    fixed guess climbing the overflow-doubling ladder; windows whose key
    zone cannot intersect the build side are never staged (inner skips
    them outright, left emits their null rows host-side); ``decision``
    may swap probe/build for inner joins.
    """
    import jax

    from ..config import get_flag
    from .pipeline import WindowPipeline
    from .stream import _block_if, _timed

    if decision is None:
        decision = JoinDecision(strategy="sorted", window_rows=window_rows)
    # Under analyze, the join gets its own stage breakdown (stage /
    # compute / stall) like every other window consumer.
    qstats = getattr(engine, "_query_stats", None) if engine is not None \
        else None
    stats = qstats.new_fragment([op]) if qstats is not None else None

    l_remap, r_remap, key_dicts = _align_join_dicts(left, right, op)
    lkeys, rkeys = _packed_key_ids(left, op.left_on, l_remap,
                                   right, op.right_on, r_remap)
    swap = bool(decision.swap)
    if swap and op.how != "inner":
        raise QueryError("join build-side swap is inner-only")
    pkeys, bkeys = (rkeys, lkeys) if swap else (lkeys, rkeys)
    n_probe = len(pkeys)
    bstats = left_stats if swap else right_stats
    if (
        bstats is None or not bstats.ndv or len(op.left_on) > 1
        or l_remap or r_remap
    ):
        # Multi-plane keys were re-packed into dense ids, and divergent
        # string dictionaries were remapped into a merged id space —
        # table sketches describe RAW values, so their zone bounds no
        # longer apply; rescan the packed ids (rows/NDV survive either
        # transform, bounds do not).
        bstats = _scan_side_stats(bkeys)
    elif bstats.rows > len(bkeys):
        import dataclasses

        # Sketch rows are table-lifetime counts (expiry/filters shrink
        # the materialized batch); fan-out comes from live rows.
        bstats = dataclasses.replace(bstats, rows=len(bkeys))

    rb = len(bkeys)
    nb = bucket_capacity(rb)
    sentinel = np.iinfo(np.int64).max  # sorts past every real key
    sbk = np.full(nb, sentinel, dtype=np.int64)
    if decision.strategy == "radix":
        from ..ops.join import radix_partition_build

        radix_bits = int(get_flag("join_radix_bits"))
        order, part_starts, steps = radix_partition_build(bkeys, radix_bits)
        sbk[:rb] = bkeys[order]
        sbk_dev = jax.device_put(sbk)  # staged once; reused by every window
        starts_dev = jax.device_put(part_starts)

        def probe_fn(cap):
            fn = _radix_probe_cache(
                nb, wcap, cap, op.how, radix_bits, steps
            )
            return lambda pk_dev, pv_dev: fn(
                sbk_dev, starts_dev, pk_dev, pv_dev
            )
    else:
        order = np.argsort(bkeys, kind="stable")
        sbk[:rb] = bkeys[order]
        sbk_dev = jax.device_put(sbk)
        rb_s = np.int32(rb)

        def probe_fn(cap):
            fn = _probe_sorted_cache(nb, wcap, cap, op.how)
            return lambda pk_dev, pv_dev: fn(sbk_dev, rb_s, pk_dev, pv_dev)

    wcap = bucket_capacity(min(window_rows, n_probe))
    n_windows = (n_probe + window_rows - 1) // window_rows

    # Zone-map window skipping: a probe window whose [min, max] cannot
    # intersect the build side's key range joins nothing — inner skips
    # it outright (the prefetch thread never stages it), left emits its
    # null rows host-side with zero device work.
    skip = np.zeros(n_windows, dtype=bool)
    build_lo = int(bkeys.min()) if rb else 0
    build_hi = int(bkeys.max()) if rb else 0
    window_overlap = None  # worst surviving window's zone overlap
    if n_windows > 1:
        # Per-window zones feed BOTH decisions (one cheap pass): which
        # windows to skip (zone_skip flag), and the capacity estimate's
        # overlap fraction — which must cover the worst WINDOW, not the
        # probe-wide average (for clustered probes most windows miss
        # the build range entirely while the live ones overlap it
        # almost fully; the whole-probe fraction would understate them
        # whether or not skipping is enabled).
        wlo, whi = _window_zones(pkeys, window_rows)
        if decision.zone_skip:
            skip = (whi < build_lo) | (wlo > build_hi)
            decision.skipped_windows = int(skip.sum())
        live = ~skip
        if live.any():
            span = np.maximum(whi[live] - wlo[live] + 1, 1)
            inter = (
                np.minimum(whi[live], build_hi)
                - np.maximum(wlo[live], build_lo) + 1
            )
            window_overlap = float(
                np.clip(inter / span, 0.0, 1.0).max()
            )

    def stage_window(off):
        m = min(window_rows, n_probe - off)
        pk = np.full(wcap, sentinel, dtype=np.int64)
        pk[:m] = pkeys[off:off + m]
        pv = np.zeros(wcap, dtype=bool)
        pv[:m] = True
        return m, jax.device_put(pk), jax.device_put(pv)

    def staged_probe_windows():
        for w in range(n_windows):
            if skip[w]:
                continue
            off = w * window_rows
            m = min(window_rows, n_probe - off)
            with _timed(stats, "stage", rows=m):
                _, pk_dev, pv_dev = stage_window(off)
                _block_if(stats, (pk_dev, pv_dev))
            if stats is not None:
                stats.rows_in += m
            yield off, pk_dev, pv_dev

    # Initial capacity: learned (this plan overflowed before — start at
    # the rung it settled on), else the sketch estimate. Each overflow
    # retry costs a fresh jit compile MID-query, so getting this right
    # is worth more than the capacity estimate's few percent of error.
    probe_side = JoinSideStats(
        rows=n_probe,
        lo=int(pkeys.min()) if n_probe else None,
        hi=int(pkeys.max()) if n_probe else None,
        origin="scan",
    )
    # Namespace the learned rung by execution mode + window size: a
    # windowed rung is PER WINDOW, a single-shot rung covers the whole
    # output — cross-seeding them either overallocates every window or
    # guarantees a re-climb when the same plan flips paths.
    cap_key = None if cap_key is None else ("windowed", window_rows, cap_key)
    capacity = learned_capacity(engine, cap_key)
    if capacity is None:
        # Clamp the ESTIMATE (a skew blowup would allocate absurd
        # expansion buffers); a learned value is never clamped — it was
        # reached by real doublings and re-clamping would re-climb.
        capacity = min(
            estimate_join_capacity(
                min(window_rows, n_probe), bstats, probe_side, op.how,
                overlap=window_overlap,
            ),
            bucket_capacity(max(2 * window_rows, 1) * 64),
        )

    parts: dict = {}  # off -> (probe_idx, probe_take, build_row, build_take)
    depth = (
        engine.pipeline_depth if engine is not None
        else get_flag("pipeline_depth")
    )
    pipe = WindowPipeline(
        staged_probe_windows(), depth,
        cancel=getattr(engine, "_cancel", None), stats=stats,
    )

    def compact(off, p_idx, p_take, b_idx, b_take, out_valid):
        sel = np.nonzero(out_valid)[0]
        parts[off] = (
            p_idx[sel].astype(np.int64) + off,
            p_take[sel],
            order[np.clip(b_idx[sel], 0, max(rb - 1, 0))],
            b_take[sel],
        )

    counter = _retry_counter(engine)
    try:
        run = probe_fn(capacity)
        for off, pk_dev, pv_dev in pipe:
            with _timed(stats, "compute"):
                while True:
                    # The per-window readback is the driver's consume
                    # step: compacting each window host-side bounds
                    # memory to one window's capacity, and the overflow
                    # flag rides in the same batch (no extra sync, no
                    # per-window bool(overflow) readback).
                    p_idx, p_take, b_idx, b_take, out_valid, overflow = (
                        np.asarray(a) for a in run(pk_dev, pv_dev)  # pxlint: disable=host-sync-hot-path
                    )
                    if not bool(overflow):
                        break
                    # Estimate/learned rung was wrong: double, recompile
                    # (counted — the bench gate wants this at zero), and
                    # keep the larger capacity for every later window.
                    capacity *= 2
                    counter.inc()
                    decision.retries += 1
                    run = probe_fn(capacity)
            if stats is not None:
                stats.windows += 1
            compact(off, p_idx, p_take, b_idx, b_take, out_valid)
    finally:
        pipe.close()
        if engine is not None:
            engine._note_pipeline(pipe)

    if op.how == "left":
        # Zone-skipped windows of a left join still emit one null-right
        # row per probe row — assembled host-side, no device dispatch.
        for w in np.nonzero(skip)[0]:
            off = int(w) * window_rows
            m = min(window_rows, n_probe - off)
            parts[off] = (
                np.arange(off, off + m, dtype=np.int64),
                np.ones(m, dtype=bool),
                np.zeros(m, dtype=np.int64),
                np.zeros(m, dtype=bool),
            )
    remember_capacity(engine, cap_key, capacity)

    ordered = [parts[off] for off in sorted(parts)]

    def cat(i, dtype):
        if not ordered:
            return np.zeros(0, dtype=dtype)
        return np.concatenate([p[i] for p in ordered]).astype(
            dtype, copy=False
        )

    p_all = (cat(0, np.int64), cat(1, bool))
    b_all = (cat(2, np.int64), cat(3, bool))
    l_idx, l_take = (b_all if swap else p_all)
    r_idx, r_take = (p_all if swap else b_all)
    out_rel, src = _join_out_schema(left, right, op)
    out = _assemble_join(
        left, right, op, out_rel, src,
        l_idx, l_take, r_idx, r_take,
        r_remap=r_remap, key_dicts=key_dicts,
    )
    if stats is not None:
        stats.rows_out = out.length
    return out


def _join_device(left: HostBatch, right: HostBatch, op: JoinOp,
                 engine=None, decision=None, left_stats=None,
                 right_stats=None, cap_key=None,
                 planned_capacity=None) -> HostBatch:
    """N:M device join: pad to bucketed capacities, run the sort-based
    kernel at the sketch-estimated (or learned) capacity, re-run doubled
    on overflow (counted), gather columns host-side. Large windowable
    probes route to the windowed drivers instead."""
    from ..config import get_flag

    if decision is None or decision.strategy == "host_hash":
        decision = choose_join_strategy(
            left, right, op, engine, left_stats, right_stats,
            device_only=True,
        )
        if engine is not None:
            engine.last_join_decision = decision
    probe_window = get_flag("join_probe_window_rows")
    probe_rows = right.length if decision.swap else left.length
    if (
        decision.strategy in ("sorted", "radix")
        and op.how in ("inner", "left")
        and probe_window > 0
        and probe_rows > probe_window
        and left.length > 0
        and right.length > 0
    ):
        # Same key-dtype guard as the single-shot path below — the
        # packed-id densify would otherwise paper over a mismatch via
        # numpy promotion (int64 vs float64 collides above 2^53).
        for lc, rc in zip(op.left_on, op.right_on):
            for lp_, rp_ in zip(left.cols[lc], right.cols[rc]):
                if lp_.dtype != rp_.dtype:
                    raise QueryError(
                        f"join key dtype mismatch: {rp_.dtype} vs {lp_.dtype}"
                    )
        # Windowable joins with a big probe side: sorted/partitioned
        # build staged once, probe windows pipelined (one dispatch per
        # window).
        return _join_device_windowed(
            left, right, op, probe_window, engine, decision,
            left_stats, right_stats, cap_key,
        )
    l_remap, r_remap, key_dicts = _align_join_dicts(left, right, op)
    probe_planes = _join_key_planes(left, op.left_on, l_remap)
    build_planes = _join_key_planes(right, op.right_on, r_remap)
    for bp, pp in zip(build_planes, probe_planes):
        if bp.dtype != pp.dtype:
            raise QueryError(
                f"join key dtype mismatch: {bp.dtype} vs {pp.dtype}"
            )

    nb, np_ = bucket_capacity(right.length), bucket_capacity(left.length)

    def pad(p, cap):
        out = np.zeros(cap, dtype=p.dtype)
        out[: len(p)] = p
        return out

    bk = [pad(p, nb) for p in build_planes]
    pk = [pad(p, np_) for p in probe_planes]
    bv = np.zeros(nb, dtype=bool)
    bv[: right.length] = True
    pv = np.zeros(np_, dtype=bool)
    pv[: left.length] = True

    # Initial capacity: learned rung, else the NDV-based estimate, else
    # the historical probe+build default. right/outer append one extra
    # row per unmatched build row past the pair region. The rung is
    # namespaced: single-shot capacities cover the WHOLE output, never
    # interchangeable with the windowed drivers' per-window rungs.
    cap_key = None if cap_key is None else ("single", cap_key)
    capacity = learned_capacity(engine, cap_key)
    if capacity is None:
        if right_stats is not None and right_stats.ndv:
            import dataclasses

            # Sketch rows are table-LIFETIME counts (expiry never
            # decrements; filters shrink the batch further) — fan-out
            # must come from the rows actually materialized, or a
            # churned streaming table inflates the estimate without
            # bound. Divergent string dictionaries were remapped into a
            # merged id space, so the sketches' zone bounds are
            # raw-space and only the NDV/rows half applies there.
            remapped = bool(l_remap or r_remap)
            capacity = estimate_join_capacity(
                left.length,
                dataclasses.replace(
                    right_stats, rows=min(right_stats.rows, right.length)
                ),
                left_stats, op.how,
                overlap=1.0 if remapped else None,
            )
            if op.how in ("right", "outer"):
                capacity = bucket_capacity(capacity + right.length)
            # Clamp to the theoretical maximum output (every probe row
            # matching every build row) — stale stats must never drive
            # an allocation past what the data could produce.
            capacity = min(
                capacity, bucket_capacity(max(left.length, 1) * right.length)
            )
        elif planned_capacity:
            # pxbound's plan-time estimate (analysis/bounds.py): sized
            # from bounds run-time sketches cannot see — a post-
            # aggregate build side's group-count bound. Clamped to the
            # theoretical max like the run-time estimate.
            capacity = min(
                bucket_capacity(max(int(planned_capacity), 1)),
                bucket_capacity(max(left.length, 1) * right.length),
            )
        else:
            capacity = bucket_capacity(max(left.length + right.length, 1))
    counter = _retry_counter(engine)
    while True:
        fn = _device_join_cache(
            nb, np_, tuple(str(p.dtype) for p in bk), capacity, op.how
        )
        p_idx, p_take, b_idx, b_take, out_valid, overflow = (
            np.asarray(a) for a in fn(bk, bv, pk, pv)
        )
        if not bool(overflow):
            break
        capacity *= 2
        counter.inc()
        decision.retries += 1
    remember_capacity(engine, cap_key, capacity)

    sel = np.nonzero(out_valid)[0]
    out_rel, src = _join_out_schema(left, right, op)
    return _assemble_join(
        left, right, op, out_rel, src,
        p_idx[sel], p_take[sel], b_idx[sel], b_take[sel],
        r_remap=r_remap, key_dicts=key_dicts,
    )


def _join_host(left: HostBatch, right: HostBatch, op: JoinOp) -> HostBatch:
    """N:1 equijoin on host (post-agg inputs are small).

    Reference: ``src/carnot/exec/equijoin_node.cc`` build+probe — here the
    build side must be unique on the key (raises _BuildNotUnique for the
    dispatcher to fall through to the device kernel).
    """
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)

    lk = _key_tuples(left, op.left_on, l_remap)
    rk = _key_tuples(right, op.right_on, r_remap)
    lookup: dict = {}
    for i, k in enumerate(rk):
        if k in lookup:
            raise _BuildNotUnique(op.right_on, k)
        lookup[k] = i

    match = np.fromiter((lookup.get(k, -1) for k in lk), dtype=np.int64, count=len(lk))
    if op.how == "inner":
        l_idx = np.nonzero(match >= 0)[0]
    elif op.how == "left":
        l_idx = np.arange(left.length)
    else:
        raise QueryError(f"unsupported join how={op.how!r}")
    r_idx = match[l_idx]
    return _assemble_join_host(left, right, op, l_idx, r_idx)


def _join_host_nm(left: HostBatch, right: HostBatch, op: JoinOp,
                  right_stats=None, decision=None) -> HostBatch:
    """N:M inner/left equijoin on host — the CPU-backend analog of the
    device kernel (XLA CPU sorts are too slow to route big joins through
    the device path there). The native O(n) build+probe hash join
    (native/hash_join.cc) carries the bulk; the vectorized numpy
    sort/searchsorted form is the no-toolchain fallback.

    Zone pre-filter (the host analog of the windowed drivers' window
    skipping): rows whose key lies outside the other side's [min, max]
    cannot join — inner drops them from the probe, both hows drop them
    from the build, so a selective join hashes only the overlap."""
    l_remap, r_remap, _ = _align_join_dicts(left, right, op)
    lk = _packed_key_ids(left, op.left_on, l_remap,
                         right, op.right_on, r_remap)
    lkeys, rkeys = lk

    sel_l = sel_r = None  # compressed-row -> original-row maps
    if (
        decision is not None and decision.zone_skip
        and len(lkeys) and len(rkeys)
    ):
        llo, lhi = int(lkeys.min()), int(lkeys.max())
        rlo, rhi = int(rkeys.min()), int(rkeys.max())
        if op.how == "inner" and (llo < rlo or lhi > rhi):
            keep = (lkeys >= rlo) & (lkeys <= rhi)
            if int(keep.sum()) < int(0.9 * len(lkeys)):
                sel_l = np.nonzero(keep)[0]
                lkeys = lkeys[sel_l]
        if rlo < llo or rhi > lhi:
            keep = (rkeys >= llo) & (rkeys <= lhi)
            if int(keep.sum()) < int(0.9 * len(rkeys)):
                sel_r = np.nonzero(keep)[0]
                rkeys = rkeys[sel_r]

    def _emit(l_idx, r_idx):
        if sel_l is not None:
            l_idx = sel_l[l_idx]
        if sel_r is not None and len(sel_r):
            r_idx = np.where(r_idx >= 0, sel_r[np.clip(r_idx, 0, None)], -1)
        return _assemble_join_host(left, right, op, l_idx, r_idx)

    if op.how == "left" and len(lkeys) and not len(rkeys):
        # Pre-filter emptied the build side: every probe row is
        # unmatched (the generic path below assumes a non-empty build).
        return _emit(
            np.arange(left.length, dtype=np.int64),
            np.full(left.length, -1, dtype=np.int64),
        )
    if op.how == "inner" and (not len(lkeys) or not len(rkeys)):
        return _emit(
            np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
        )

    from ..native import hash_join_call

    if len(rkeys) and len(lkeys):
        native = hash_join_call(rkeys, lkeys, left_outer=(op.how == "left"))
        if native is not None:
            l_idx, r_idx = native
            return _emit(l_idx.astype(np.int64), r_idx.astype(np.int64))
    order = np.argsort(rkeys, kind="stable")
    span = 0
    if len(rkeys) and len(lkeys):
        kmin = min(int(rkeys.min()), int(lkeys.min()))
        kmax = max(int(rkeys.max()), int(lkeys.max()))
        span = kmax - kmin + 1
    if 0 < span <= 4 * (len(lkeys) + len(rkeys)):
        # Dense key range: bincount + cumsum offsets replace the two
        # binary searches (random-access searchsorted over millions of
        # probes is the profile's hot spot).
        kcounts = np.bincount(rkeys - kmin, minlength=span)
        key_starts = np.zeros(span + 1, dtype=np.int64)
        np.cumsum(kcounts, out=key_starts[1:])
        lo = key_starts[lkeys - kmin]
        counts = kcounts[lkeys - kmin]
        hi = lo + counts
    else:
        srk = rkeys[order]
        lo = np.searchsorted(srk, lkeys, side="left")
        hi = np.searchsorted(srk, lkeys, side="right")
        counts = hi - lo
    if op.how == "left":
        counts = np.maximum(counts, 1)  # unmatched keep one null row
        unmatched = (hi - lo) == 0
    total = int(counts.sum())
    starts = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    l_idx = np.repeat(np.arange(len(lkeys), dtype=np.int64), counts)
    within = np.arange(total, dtype=np.int64) - np.repeat(starts[:-1], counts)
    if len(rkeys):
        r_idx = order[
            np.clip(np.repeat(lo, counts) + within, 0, len(rkeys) - 1)
        ]
    else:
        r_idx = np.full(total, -1, dtype=np.int64)
    if op.how == "left" and len(rkeys):
        r_idx = np.where(np.repeat(unmatched, counts), -1, r_idx)
    return _emit(l_idx, r_idx)


def _packed_key_ids(left, left_on, l_remap, right, right_on, r_remap):
    """Dense i64 key ids comparable across both sides (np.unique over the
    stacked key planes of the concatenated inputs)."""
    def planes(b, cols, remap):
        out = []
        for c in cols:
            for i, p in enumerate(b.cols[c]):
                q = p
                if i == 0 and c in remap:
                    q = remap[c][np.clip(p, 0, None)]
                    q = np.where(p >= 0, q, NULL_ID)
                out.append(np.asarray(q))
        return out
    lp = planes(left, left_on, l_remap)
    rp = planes(right, right_on, r_remap)
    if (
        len(lp) == 1
        and np.issubdtype(lp[0].dtype, np.integer)
        and np.issubdtype(rp[0].dtype, np.integer)
    ):
        # Single-plane INTEGER keys compare directly — no densification
        # pass (the int64 cast is equality-preserving, wrapping uints
        # bijectively). Floats must densify: casting would truncate
        # 1.2 and 1.7 onto the same key.
        return (lp[0].astype(np.int64, copy=False),
                rp[0].astype(np.int64, copy=False))
    # Exact densify: per-plane np.unique codes (lossless for ANY dtype —
    # a blanket int64 cast would truncate float keys), then one unique
    # over the code tuples for multi-plane keys.
    codes = []
    for a, b in zip(lp, rp):
        _, inv = np.unique(np.concatenate([a, b]), return_inverse=True)
        codes.append(inv.astype(np.int64).reshape(-1))
    if len(codes) == 1:
        inv = codes[0]
    else:
        _, inv = np.unique(
            np.stack(codes, axis=1), axis=0, return_inverse=True
        )
        inv = inv.astype(np.int64).reshape(-1)
    return inv[: left.length], inv[left.length:]


def _assemble_join_host(left, right, op, l_idx, r_idx) -> HostBatch:
    """Row assembly for the host N:1 / N:M paths (r_idx=-1 -> null)."""
    out_rel = left.relation.merge(
        right.relation.select(
            [c for c in right.relation.column_names if c not in op.right_on]
        ),
        suffix=op.suffix,
    )
    out_cols: dict = {}
    out_dicts: dict = {}
    names = iter(out_rel.column_names)
    for c in left.relation.column_names:
        n = next(names)
        out_cols[n] = tuple(p[l_idx] for p in left.cols[c])
        if c in left.dicts:
            out_dicts[n] = left.dicts[c]
    for c in right.relation.column_names:
        if c in op.right_on:
            continue
        n = next(names)
        planes = []
        nullv = NULL_ID if right.relation.col_type(c) == DataType.STRING else 0
        for p in right.cols[c]:
            if len(p) == 0:  # empty build side: all-null fill
                taken = np.full(len(l_idx), nullv, dtype=p.dtype)
            else:
                taken = p[np.clip(r_idx, 0, None)]
                if op.how == "left":
                    taken = np.where(r_idx >= 0, taken, nullv).astype(p.dtype)
            planes.append(taken)
        out_cols[n] = tuple(planes)
        if c in right.dicts:
            out_dicts[n] = right.dicts[c]
    return HostBatch(
        relation=out_rel, cols=out_cols, length=len(l_idx), dicts=out_dicts
    )


def _union_host(mats) -> HostBatch:
    """Schema-aligned union with dictionary re-encoding.

    When the schema carries a ``time_`` column the result is merged in
    time order — the reference UnionNode's k-way ordered merge of
    cross-PEM streams (``src/carnot/exec/union_node.cc``); a stable sort
    over the concatenation is equivalent given each input is itself
    time-ordered, and stays a single vectorized pass.
    """
    first = mats[0]
    for m in mats[1:]:
        if tuple(m.relation.column_names) != tuple(first.relation.column_names):
            raise QueryError("union inputs must share a schema")
    out_cols: dict = {}
    out_dicts: dict = {}
    for c, dt in first.relation.items():
        if dt == DataType.STRING:
            merged = StringDictionary()
            planes = []
            for m in mats:
                d = m.dicts.get(c, StringDictionary())
                # union preserves existing ids (append-only), so earlier
                # planes stay valid as merged grows.
                merged, _, remap = merged.union(d)
                ids = m.cols[c][0]
                planes.append(
                    np.where(ids >= 0, remap[np.clip(ids, 0, None)], NULL_ID).astype(
                        np.int32
                    )
                )
            out_cols[c] = (np.concatenate(planes),)
            out_dicts[c] = merged
        else:
            out_cols[c] = tuple(
                np.concatenate([m.cols[c][i] for m in mats])
                for i in range(len(first.cols[c]))
            )
    if first.relation.has_column("time_"):
        order = np.argsort(out_cols["time_"][0], kind="stable")
        if not np.array_equal(order, np.arange(len(order))):
            out_cols = {
                c: tuple(p[order] for p in ps) for c, ps in out_cols.items()
            }
    return HostBatch(
        relation=first.relation,
        cols=out_cols,
        length=sum(m.length for m in mats),
        dicts=out_dicts,
    )


# -- fused lookup join --------------------------------------------------------
def try_fused_join(engine, nid, node, results, consumers):
    """N:1 join as an in-fragment device lookup, or None to fall back.

    Reference contrast: ``equijoin_node.cc`` materializes output rows
    through a host hash map; here, when the build side resolves to a
    dense-domain table, the probe stream keeps flowing — each window
    gathers the build columns on device and the downstream
    Map/Filter/Agg fuse into the same XLA program (VERDICT r03 ask
    #2: output-row assembly never leaves the device).
    """
    from ..types.dtypes import device_dtypes

    op = node.op
    if not engine.fused_lookup_join:
        return None
    if op.how not in ("inner", "left") or len(op.left_on) != 1:
        return None
    left_id, right_id = node.inputs
    left_res = results[left_id]
    if not isinstance(left_res, _Stream) or consumers.get(left_id, 0) > 1:
        return None
    if any(isinstance(o, (AggOp, LimitOp)) for o in left_res.chain):
        return None
    lc, rc = op.left_on[0], op.right_on[0]
    bound = _chain_out_relation(left_res, engine.registry)
    if bound is None:
        return None
    left_rel, left_dicts = bound
    if not left_rel.has_column(lc):
        return None
    l_dt = left_rel.col_type(lc)
    if len(device_dtypes(l_dt)) != 1:
        return None

    right_res = results[right_id]
    if (
        isinstance(right_res, _Stream)
        and consumers.get(right_id, 0) <= 1
        and any(isinstance(o, AggOp) for o in right_res.chain)
    ):
        built = _dense_agg_build(engine, right_res, op, l_dt, left_dicts, lc, rc)
        if isinstance(built, tuple) and built[0] == "fallback":
            # The aggregate already executed; keep its rows for the
            # generic join path rather than re-folding the stream.
            results[right_id] = built[1]
            built = _host_table_build(
                built[1], op, l_dt, left_dicts, lc, rc
            )
    else:
        if not isinstance(right_res, HostBatch):
            return None
        built = _host_table_build(right_res, op, l_dt, left_dicts, lc, rc)
    if built is None:
        return None
    lo, dom, found, value_tables, right_rel = built

    # Output naming: all left columns keep their names; right value
    # columns (minus the key) merge with the join suffix — the same
    # schema ``_join_out_schema`` produces for the host paths.
    try:
        out_rel = left_rel.merge(
            right_rel.select(
                [c for c in right_rel.column_names if c not in op.right_on]
            ),
            suffix=op.suffix,
        )
    except Exception:
        return None
    value_srcs = [c for c in right_rel.column_names if c not in op.right_on]
    out_names = out_rel.column_names[len(left_rel.column_names):]

    out_cols = []
    side: dict = {}
    prefix = f"__lj{nid}"
    for src, out_name in zip(value_srcs, out_names):
        dt = right_rel.col_type(src)
        if dt == DataType.STRING:
            return None  # string values need mid-chain dict plumbing
        planes = value_tables[src]
        out_cols.append((out_name, dt, len(planes)))
        for j, p in enumerate(planes):
            side[f"{prefix}:{out_name}:{j}"] = p
    side[f"{prefix}:found"] = found

    lj = LookupJoinOp(
        key_col=lc, how=op.how, prefix=prefix, lo=int(lo), dom=int(dom),
        out_cols=tuple(out_cols),
    )
    st = left_res.extend(lj)
    st.side.update(side)
    return st


def _dense_agg_build(engine, right_stream, op, l_dt, left_dicts, lc, rc):
    """Build lookup tables straight from a dense aggregate's device
    state: the slot-aligned finalize output IS the table (slot =
    key - lo), so the build side never visits the host."""
    if any(isinstance(o, LimitOp) for o in right_stream.chain):
        return None
    frag_probe = compile_fragment(
        right_stream.chain, right_stream.relation, right_stream.dicts,
        engine.registry, col_stats=_stream_col_stats(right_stream),
    )
    if (
        not frag_probe.is_agg
        or len(frag_probe.dense_domains) != 1
        or frag_probe.dense_strides not in ((), (1,))
        or frag_probe.limit is not None
    ):
        # (strided domains step-index their slots; the LookupJoinOp
        # gather arithmetic assumes stride 1.)
        return None
    # The dense slot space must be the probe key's own code space.
    agg_i = next(
        i for i, o in enumerate(right_stream.chain)
        if isinstance(o, AggOp)
    )
    agg = right_stream.chain[agg_i]
    if tuple(agg.group_cols) != (rc,):
        return None
    # Post-agg ops must leave the key column untouched — the slot
    # arithmetic pairs probe keys with SLOT indices, so a post map
    # that rewrites the key would silently mispair every row.
    for o in right_stream.chain[agg_i + 1:]:
        if isinstance(o, MapOp):
            key_expr = dict(o.exprs).get(rc)
            if key_expr != _col(rc):
                return None
    out_rel = frag_probe.relation
    if rc not in out_rel.column_names:
        return None
    if out_rel.col_type(rc) != l_dt:
        return None
    if l_dt == DataType.STRING:
        meta = next(m for m in frag_probe.out_meta if m.name == rc)
        if left_dicts.get(lc) is not meta.dict:
            return None
    if any(m.struct_fields for m in frag_probe.out_meta):
        return None
    # Execute the PROBE's fragment, not a recompile: an append racing
    # between two compiles (stats crossing the stats quantization
    # grain) would give the run a different dense domain/offset than
    # the lo/dom captured below, silently mispairing every lookup.
    # With the same fragment, a racing append past the captured
    # domain surfaces as dr._overflow and takes the reject path.
    dr = engine._run_fragment(right_stream, frag=frag_probe)
    reject = bool(np.asarray(dr._overflow))  # stats raced an append
    value_tables = {
        n: tuple(dr._cols[n])
        for n in out_rel.column_names
        if n != rc and n in dr._cols
    }
    if set(value_tables) != {c for c in out_rel.column_names if c != rc}:
        reject = True
    if reject:
        # Don't discard the executed aggregate: hand the (rebucketed
        # if needed) rows back so the generic join path reuses them
        # instead of re-folding the whole right stream.
        return ("fallback", dr.to_host())
    return (
        frag_probe.dense_offsets[0], frag_probe.dense_domains[0],
        dr._valid, value_tables, out_rel,
    )


def _host_table_build(right_hb, op, l_dt, left_dicts, lc, rc):
    """Build dense lookup tables from a materialized unique-key host
    batch (the post-agg N:1 case arriving as rows)."""
    from ..config import get_flag

    if not right_hb.relation.has_column(rc):
        return None
    if right_hb.relation.col_type(rc) != l_dt:
        return None
    if right_hb.length == 0:
        return None
    kb = np.asarray(right_hb.cols[rc][0])
    if l_dt == DataType.STRING:
        ld = left_dicts.get(lc)
        rd = right_hb.dicts.get(rc)
        if ld is None or rd is None:
            return None
        if rd is not ld:
            # Re-express build keys in the probe's id space without
            # growing it: unseen keys can never match a probe row.
            remap = np.fromiter(
                (ld.lookup(s) for s in rd.strings),
                dtype=np.int64, count=len(rd),
            )
            kb = np.where(kb >= 0, remap[np.clip(kb, 0, None)], -1)
        lo, dom = 0, len(ld) + 1
        in_dom = kb >= 0
    elif l_dt in (DataType.INT64, DataType.TIME64NS):
        lo, hi = int(kb.min()), int(kb.max())
        dom = hi - lo + 1
        if dom > get_flag("int_dense_domain_limit"):
            return None
        in_dom = np.ones(len(kb), dtype=bool)
    else:
        return None
    idx = np.where(in_dom, kb - lo, 0)
    found = np.zeros(dom, dtype=bool)
    # Uniqueness: a duplicate build key means N:M — not this path.
    found[idx[in_dom]] = True
    if int(found.sum()) != int(in_dom.sum()):
        return None
    from ..types.dtypes import device_dtypes

    value_tables = {}
    for c in right_hb.relation.column_names:
        if c == rc:
            continue
        ddts = device_dtypes(right_hb.relation.col_type(c))
        planes = []
        for p, ddt in zip(right_hb.cols[c], ddts):
            # Device dtype, not host: FLOAT64 host planes are f64 but
            # the device-plane invariant is f32 — an f64 side table
            # would re-admit f64 into fused device code.
            p = np.asarray(p)
            t = np.zeros(dom, dtype=ddt)
            if len(p):
                t[idx[in_dom]] = p[in_dom]
            planes.append(t)
        value_tables[c] = tuple(planes)
    return lo, dom, found, value_tables, right_hb.relation
