"""Live (streaming) query execution: infinite sources + incremental
results.

Reference parity: ``src/carnot/exec/memory_source_node.cc`` — a memory
source with no stop time streams forever, emitting row batches as the
table grows — and ``query_result_forwarder.go:470`` (StreamResults),
which relays incremental batches to the subscribed client until cancel.

TPU-first redesign: instead of a long-lived push graph, a **streaming
cursor** holds a per-tablet row watermark and, each round, folds only
the windows appended since the last round through the chain's compiled
fragment:

- Non-blocking chains (Map/Filter/Limit) emit each new batch once
  (``mode="append"``) — the infinite-MemorySource behavior.
- Blocking aggregates keep their group state ACROSS rounds: new windows
  fold into the persistent state and the re-finalized aggregate is
  emitted each round (``mode="replace"``) — incremental view
  maintenance, which Carnot does not do (it recomputes live views from
  scratch on every UI poll).

The distributed form (PEM partial states re-shipped per round, Kelvin
re-merging latest states) lives in ``services.agent``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..table_store.coldstore import take_decode_meter
from .engine import (
    Engine,
    QueryCancelled,
    QueryError,
    _double_agg_groups,
    _stream_col_stats,
    _Stream,
    _timed,
    _to_host_batch,
)
from .fragment import compile_fragment_cached as compile_fragment
from .plan import (
    AggOp,
    FilterOp,
    LimitOp,
    MapOp,
    MemorySourceOp,
    Plan,
    ResultSinkOp,
    TableSinkOp,
)


@dataclass
class StreamUpdate:
    """One incremental result delivery."""

    table: object  # sink name (None for bridge updates)
    batch: object  # HostBatch | AggStatePayload | RowsPayload
    seq: int
    # "append": batch holds only NEW rows; "replace": batch is the full
    # current aggregate (supersedes every earlier update); "state": a
    # partial-agg state snapshot for the merge tier (supersedes this
    # agent's earlier snapshots); "rows": a new-rows bridge payload.
    mode: str
    bridge_id: object = None


@dataclass
class _StreamChain:
    """A linear Source -> ops -> sink slice of a streamable plan."""

    source: MemorySourceOp
    ops: list
    sink_name: str
    is_agg: bool
    bridge_id: object = None  # set when the terminal is a BridgeSinkOp


def _linearize(plan: Plan) -> _StreamChain:
    """Validate + flatten a streamable plan.

    Streamable = exactly one MemorySource feeding a linear
    Map/Filter/Agg run into one result sink (or a BridgeSink — the
    distributed form's per-agent half). Joins/unions/UDTFs stay one-shot
    (QueryError) — the service layer can still poll those.
    """
    from .plan import BridgeSinkOp

    sources = [
        n for n in plan.nodes.values() if isinstance(n.op, MemorySourceOp)
    ]
    if len(sources) != 1:
        raise QueryError(
            f"streaming needs exactly one memory source, plan has "
            f"{len(sources)}"
        )
    node = sources[0]
    src = node.op
    if src.stop_time is not None:
        raise QueryError("a time-bounded source cannot stream (stop_time set)")
    consumers = {
        nid: [m.id for m in plan.nodes.values() if nid in m.inputs]
        for nid in plan.nodes
    }
    ops: list = []
    sink = None
    bridge_id = None
    cur = node.id
    while True:
        outs = consumers[cur]
        if len(outs) != 1:
            raise QueryError("streaming plans must be linear (fan-out found)")
        nxt = plan.nodes[outs[0]]
        if isinstance(nxt.op, (MapOp, FilterOp, AggOp, LimitOp)):
            ops.append(nxt.op)
            cur = nxt.id
        elif isinstance(nxt.op, (ResultSinkOp, TableSinkOp)):
            sink = nxt.op
            break
        elif isinstance(nxt.op, BridgeSinkOp):
            bridge_id = nxt.op.bridge_id
            break
        else:
            raise QueryError(
                f"operator {type(nxt.op).__name__} is not streamable"
            )
    # A LimitOp caps total rows; meaningful for append streams only.
    n_aggs = sum(isinstance(o, AggOp) for o in ops)
    if n_aggs > 1:
        raise QueryError("streaming supports at most one aggregate")
    if bridge_id is not None:
        name = None
    else:
        name = sink.name if isinstance(sink, ResultSinkOp) else sink.table
    return _StreamChain(
        source=src, ops=ops, sink_name=name, is_agg=n_aggs == 1,
        bridge_id=bridge_id,
    )


class StreamingQuery:
    """A live cursor over one plan: ``poll()`` folds everything appended
    since the last poll and emits 0..n StreamUpdates; ``run()`` loops
    until cancelled (the service-loop form)."""

    def __init__(self, engine: Engine, plan: Plan, emit, cancel=None,
                 script: str = ""):
        self.engine = engine
        self.emit = emit
        self.cancel = cancel
        self.chain = _linearize(plan)
        src = self.chain.source
        tablets = engine.table_store.tablets(src.table)
        if not tablets:
            raise QueryError(f"no table named {src.table!r}")
        self.tablets = tablets
        base = next((t for t in tablets if len(t.relation)), tablets[0])
        self.relation = base.relation
        self.dicts = dict(base.dicts)
        pre = []
        if src.columns is not None:
            from .engine import _col

            pre.append(MapOp(exprs=tuple((c, _col(c)) for c in src.columns)))
        self.ops = pre + list(self.chain.ops)
        self.seq = 0
        self.rows_emitted = 0
        self._wm: dict = {}  # id(tablet) -> row watermark
        for t in tablets:
            be = getattr(t, "_backend", None)
            start = src.start_time
            if be is None:
                self._wm[id(t)] = 0
            elif start is not None:
                self._wm[id(t)] = t.row_id_for_time(int(start), False)
            else:
                self._wm[id(t)] = t.first_row_id()
        # Where the CURRENT agg state's fold started, per tablet: ring
        # expiry crossing this mark means folded rows are gone and the
        # persistent state must refold from the live rows (otherwise a
        # replace-mode aggregate keeps counting expired rows a one-shot
        # rescan would not see).
        self._fold_lo: dict = dict(self._wm)
        self._state = None
        self._frag = None
        self._pruners: dict = {}  # id(tablet) -> zone-skip pruner | None
        # One lifecycle trace per stream (exec/trace.py): the stream
        # shows in /debug/queryz as in-flight until close()/run() ends
        # it; per-poll window work lands in its fragment stats. Begun
        # last so earlier __init__ raises can't leak an in-flight trace.
        from .trace import plan_script

        self.trace = engine.tracer.begin_query(
            script=script or plan_script(plan), kind="stream"
        )
        self._tstats = None  # current compile's fragment stats
        try:
            self._compile()
        except BaseException as e:
            self.close(status="error", error=f"{type(e).__name__}: {e}")
            raise

    def _compile(self):
        stream = _Stream(self.relation, self.dicts, list(self.ops), self.tablets)
        self._frag = compile_fragment(
            self.ops, self.relation, self.dicts, self.engine.registry,
            col_stats=_stream_col_stats(stream),
        )
        if self.trace is not None:
            # A fresh fragment per (re)compile: rebuckets show as their
            # own fragment rows, the engine one-shot convention.
            self._tstats = self.trace.stats.new_fragment(self.ops)
        if self.chain.is_agg and self._state is not None:
            # Rebucket path: state restarts from scratch at the new size.
            self._state = None
        self._pruners = {}  # fragment stats changed; rebuild lazily

    def _pruner_for(self, t):
        """Zone-map window pruner for one tablet (None = no skipping).
        Built once per compile; skips are charged to the stream's
        current fragment stats."""
        key = id(t)
        if key not in self._pruners:
            from .zoneskip import chain_pruner

            self._pruners[key] = chain_pruner(
                t, self.ops, self.dicts, stats=self._tstats
            )
        return self._pruners[key]

    def close(self, status: str = "ok", error: str = "") -> None:
        """End the stream's lifecycle trace (idempotent). ``run()`` calls
        this on exit; callers driving ``poll()`` directly should close
        explicitly so /debug/queryz stops listing the stream as
        in-flight."""
        tr, self.trace = self.trace, None
        if tr is not None:
            self.engine.tracer.end_query(tr, status=status, error=error)

    def _new_windows(self):
        """(cols, valid, (tablet_key, row_hi)) device windows appended
        since the last poll. ``cols is None`` marks a zone-map-pruned
        tail: no window survives past ``row_hi``'s predecessor, and the
        consumer should commit the watermark without folding.

        Watermarks are NOT advanced here: with the prefetch pipeline this
        generator runs up to ``pipeline_depth`` windows ahead of the
        consumer, and advancing eagerly would mark windows consumed that
        an error/cancel then drops forever. The consumer commits
        ``self._wm[tablet_key] = row_hi`` only AFTER folding/emitting a
        window (at-least-once, matching the serial executor)."""
        for t in self.tablets:
            be = getattr(t, "_backend", None)
            if be is None:
                continue
            wm = self._wm[id(t)]
            end = t.end_row_id()
            # TRUE expiry may have dropped rows under the watermark
            # (tier-merged first: demotion does NOT advance it, so
            # demoted-but-never-folded rows are still visited).
            wm = max(wm, t.first_row_id())
            self._wm[id(t)] = wm
            if end <= wm:
                continue
            last_hi = wm
            for win, lo, hi in t.device_scan(
                window_rows=self.engine.window_rows,
                start_row=wm, stop_row=end,
                prune=self._pruner_for(t),
            ):
                # Cold decode ran on this (producer) thread inside the
                # staging call — charge it via the locked fragment stats.
                dsec, dbytes = take_decode_meter()
                if self._tstats is not None and (dsec or dbytes):
                    self._tstats.add("decode", dsec, nbytes=dbytes)
                last_hi = hi
                yield win.cols, (
                    np.int32(lo - win.row0), np.int32(hi - win.row0)
                ), (id(t), hi)
            if last_hi < end:
                # Zone maps pruned the tail windows. Pruned windows in
                # the MIDDLE of a scan are covered by the next surviving
                # window's commit (the watermark is a scalar), but a
                # pruned tail would otherwise leave the watermark short:
                # the poll would emit nothing and every later poll would
                # rescan (and re-prune) the same windows. Yield a
                # column-less marker so the consumer commits ``end`` and
                # still counts the poll as progress — the pruner proved
                # the predicate matches no row in those windows, so
                # skipping the fold is exact.
                yield None, None, (id(t), end)

    def _check_cancel(self):
        if self.cancel is not None and self.cancel.is_set():
            raise QueryCancelled("stream cancelled")

    def _pipelined_windows(self):
        """``_new_windows`` behind the engine's window-prefetch pipeline:
        the next appended window stages on a background thread while the
        current one folds/emits. Callers wrap iteration in try/finally
        close() (no leaked prefetch threads on cancel/StopStream).

        Empty polls (nothing appended since the watermark) run serial —
        a 0.25s-interval idle stream must not churn a thread per poll."""
        from .pipeline import WindowPipeline

        depth = getattr(self.engine, "pipeline_depth", 1)
        if depth > 1 and not self._has_new_rows():
            depth = 1
        return WindowPipeline(
            self._new_windows(), depth, cancel=self.cancel,
            stats=self._tstats,
        )

    def _has_new_rows(self) -> bool:
        # Mirrors _new_windows' watermark arithmetic (clamp to
        # first_row_id for ring expiry, compare against end_row_id);
        # keep the two in lockstep. Disagreement is only a perf wobble
        # (thread churn or a serial poll), never a correctness issue —
        # _new_windows alone decides what is yielded.
        for t in self.tablets:
            be = getattr(t, "_backend", None)
            if be is None:
                continue
            wm = max(self._wm[id(t)], t.first_row_id())
            if t.end_row_id() > wm:
                return True
        return False

    def _fold_new(self, frag):
        """Shared agg half: fold newly appended windows into the
        persistent group state. Returns (rows, folded)."""
        rows = 0
        if self._state is not None:
            for t in self.tablets:
                be = getattr(t, "_backend", None)
                if be is not None and (
                    t.first_row_id() > self._fold_lo.get(id(t), 0)
                ):
                    # TRUE expiry dropped rows ALREADY folded into the
                    # persistent state — refold from the live rows so
                    # the replace-mode aggregate matches what a
                    # one-shot rescan would compute (materialized-view
                    # bit-identity across expiry churn). Demotion alone
                    # never triggers this: the tier-merged first row id
                    # only moves on cold eviction.
                    self._state = None
                    break
        if self._state is None:
            self._state = frag.init_state()
            # Restart folds everything from the source's start.
            for t in self.tablets:
                be = getattr(t, "_backend", None)
                if be is not None:
                    start = self.chain.source.start_time
                    pos = (
                        t.row_id_for_time(int(start), False)
                        if start is not None
                        else t.first_row_id()
                    )
                    self._wm[id(t)] = pos
                    # The effective fold start: expiry may already sit
                    # past a time-derived position.
                    self._fold_lo[id(t)] = max(pos, t.first_row_id())
        folded = False
        st = self._tstats
        pipe = self._pipelined_windows()
        try:
            for cols, valid, (wm_key, wm_hi) in pipe:
                self._check_cancel()
                if cols is not None:
                    with _timed(st, "compute"):
                        self._state = frag.update(self._state, cols, valid)
                    w_rows = int(valid[1] - valid[0])
                    rows += w_rows
                    if st is not None:
                        st.windows += 1
                        st.rows_in += w_rows
                # A column-less marker (zone-map-pruned tail) folds
                # nothing but still counts as progress: rows WERE
                # consumed, so the poll must emit (matching the serial
                # executor, which emits the unchanged aggregate).
                folded = True
                self._wm[wm_key] = wm_hi  # commit AFTER the fold
        finally:
            pipe.close()
            self.engine._note_pipeline(pipe)
        return rows, folded

    def _rebucket(self):
        """Group overflow: double capacity (recompiling against fresh
        stats) and refold history."""
        new_ops = _double_agg_groups(
            _Stream(self.relation, self.dicts, list(self.ops), self.tablets)
        ).chain
        self.ops = list(new_ops)
        self._state = None
        self._compile()

    def _note_freshness(self) -> None:
        """Stamp this poll's staleness (now minus the source table's max
        event-time watermark) on the stream's trace: the usage field
        keeps the worst round — a live view that fell behind its ingest
        shows its backlog in __queries__ like any one-shot query.
        Exactly ONE watermark sweep per poll round: the overflow-
        rebucket retry re-enters ``_poll_inner``, not ``poll``, so it
        cannot re-sweep (shared helper + call structure; regression
        test in tests/test_result_cache.py)."""
        if self.trace is None:
            return
        from ..table_store import table as _table_mod

        wm = _table_mod.max_watermark_ns(self.tablets)
        if wm is not None:
            self.trace.note_freshness_lag(
                self.chain.source.table, (time.time_ns() - wm) / 1e6
            )

    def poll(self) -> int:
        """Fold new rows; emit updates. Returns rows consumed."""
        self._note_freshness()
        return self._poll_inner()

    def _poll_inner(self) -> int:
        frag = self._frag
        rows = 0
        if self.chain.bridge_id is not None:
            return self._poll_bridge(frag)
        if self.chain.is_agg:
            rows, folded = self._fold_new(frag)
            if not folded and self.seq > 0:
                return 0
            cols, valid, overflow = frag.finalize(self._state)
            if bool(np.asarray(overflow)):
                self._rebucket()
                return self._poll_inner()
            hb = _to_host_batch(frag.out_meta, cols, np.asarray(valid))
            if frag.limit is not None and hb.length > frag.limit:
                hb = _head(hb, frag.limit)
            self.emit(StreamUpdate(
                table=self.chain.sink_name, batch=hb, seq=self.seq,
                mode="replace",
            ))
            self.seq += 1
            return rows
        # Non-blocking: each new window emits once.
        st = self._tstats
        pipe = self._pipelined_windows()
        try:
            for cols, valid, (wm_key, wm_hi) in pipe:
                self._check_cancel()
                if cols is None:
                    # Zone-map-pruned tail: no row can match, so there
                    # is nothing to emit — just advance the watermark.
                    self._wm[wm_key] = wm_hi
                    continue
                with _timed(st, "compute"):
                    out_cols, out_valid = frag.update(cols, valid)
                with _timed(st, "materialize"):
                    hb = _to_host_batch(
                        frag.out_meta, out_cols, np.asarray(out_valid)
                    )
                if st is not None:
                    st.windows += 1
                    st.rows_in += int(valid[1] - valid[0])
                    st.rows_out += hb.length
                if hb.length == 0:
                    rows += int(valid[1] - valid[0])
                    self._wm[wm_key] = wm_hi
                    continue
                if frag.limit is not None:
                    left = frag.limit - self.rows_emitted
                    if left <= 0:
                        raise StopStream()
                    if hb.length > left:
                        hb = _head(hb, left)
                self.emit(StreamUpdate(
                    table=self.chain.sink_name, batch=hb, seq=self.seq,
                    mode="append",
                ))
                self.seq += 1
                self.rows_emitted += hb.length
                rows += int(valid[1] - valid[0])
                self._wm[wm_key] = wm_hi  # commit AFTER the emit
                if frag.limit is not None and self.rows_emitted >= frag.limit:
                    raise StopStream()
        finally:
            pipe.close()
            self.engine._note_pipeline(pipe)
        return rows

    def _poll_bridge(self, frag) -> int:
        """Per-agent half of a distributed live query: fold new windows,
        ship the current partial state (agg bridges) or the new rows
        (row-gather bridges) to the merge tier."""
        import jax

        from .engine import AggStatePayload, RowsPayload

        rows = 0
        if self.chain.is_agg:
            rows, folded = self._fold_new(frag)
            # The first round ships even an empty (neutral) state: the
            # merge tier gates on hearing from EVERY data agent, and an
            # idle agent must not blank the whole live view.
            if not folded and self.seq > 0:
                return 0
            if bool(np.asarray(self._state["overflow"])):
                self._rebucket()
                return self._poll_bridge(self._frag)
            payload = AggStatePayload(
                chain=tuple(self.ops),
                input_relation=self.relation,
                input_dicts=dict(self.dicts),
                state=jax.tree_util.tree_map(np.asarray, self._state),
                dense_domains=frag.dense_domains,
                dense_offsets=frag.dense_offsets,
                dense_strides=frag.dense_strides,
            )
            self.emit(StreamUpdate(
                table=None, batch=payload, seq=self.seq, mode="state",
                bridge_id=self.chain.bridge_id,
            ))
            self.seq += 1
            return rows
        st = self._tstats
        pipe = self._pipelined_windows()
        try:
            for cols, valid, (wm_key, wm_hi) in pipe:
                self._check_cancel()
                if cols is None:
                    # Zone-map-pruned tail (see _new_windows): commit
                    # the watermark; no rows survive to ship.
                    self._wm[wm_key] = wm_hi
                    continue
                with _timed(st, "compute"):
                    out_cols, out_valid = frag.update(cols, valid)
                with _timed(st, "materialize"):
                    hb = _to_host_batch(
                        frag.out_meta, out_cols, np.asarray(out_valid)
                    )
                rows += int(valid[1] - valid[0])
                if st is not None:
                    st.windows += 1
                    st.rows_in += int(valid[1] - valid[0])
                    st.rows_out += hb.length
                if hb.length != 0:
                    self.emit(StreamUpdate(
                        table=None, batch=RowsPayload(batch=hb),
                        seq=self.seq, mode="rows",
                        bridge_id=self.chain.bridge_id,
                    ))
                    self.seq += 1
                self._wm[wm_key] = wm_hi  # commit AFTER the emit
        finally:
            pipe.close()
            self.engine._note_pipeline(pipe)
        return rows

    def run(self, poll_interval_s: float = 0.25, max_rounds=None) -> int:
        """Poll until cancelled (or the row limit / max_rounds hits).
        Returns the number of updates emitted."""
        rounds = 0
        status, error = "ok", ""
        try:
            while True:
                self._check_cancel()
                self.poll()
                rounds += 1
                if max_rounds is not None and rounds >= max_rounds:
                    break
                if self.cancel is not None:
                    if self.cancel.wait(poll_interval_s):
                        status = "cancelled"
                        break
                else:
                    time.sleep(poll_interval_s)
        except StopStream:
            pass  # row limit satisfied: a normal end
        except QueryCancelled as e:
            status, error = "cancelled", str(e)
        except BaseException as e:
            self.close(status="error", error=f"{type(e).__name__}: {e}")
            raise
        finally:
            self.close(status=status, error=error)
        return self.seq


class StopStream(Exception):
    """Row limit satisfied: the stream ends itself (LimitNode's abort
    signal to upstream sources)."""


def _head(hb, n: int):
    from ..types.batch import HostBatch

    return HostBatch(
        relation=hb.relation,
        cols={c: tuple(p[:n] for p in planes) for c, planes in hb.cols.items()},
        length=n,
        dicts=dict(hb.dicts),
    )


def stream_query(
    engine: Engine, query: str, emit, cancel=None, now_ns: int = 0,
    max_output_rows: int | None = None,
) -> StreamingQuery:
    """Compile a PxL script into a live StreamingQuery on ``engine``.

    ``max_output_rows=None`` (the default) disables the result-sink row
    cap: a live stream is unbounded by design; pass a value to cap the
    append stream like the reference's 10k default does for one-shots.
    """
    from ..planner import CompilerState, compile_pxl

    state = CompilerState(
        schemas={
            name: t.relation
            for name, t in engine.tables.items()
            if t is not None and len(t.relation)
        },
        registry=engine.registry,
        now_ns=now_ns,
        max_output_rows=max_output_rows or (1 << 62),
        table_stats=engine._compile_table_stats(),
    )
    compiled = compile_pxl(query, state)
    return StreamingQuery(engine, compiled.plan, emit, cancel=cancel,
                          script=query)
