"""Device-tier observability: compiled-program registry + device memory.

Everything above the JAX boundary is already observable (trace spans,
``QueryResourceUsage``, telemetry tables); below it the engine was
blind — nothing recorded what XLA programs exist, what each one cost to
compile, what it reads/allocates, or whether a repeated query actually
reused an executable. This module closes that gap with two pieces:

**ProgramRegistry** — the process-wide registry of tracked XLA
programs. The fragment compiler (``exec/fragment.py``) and the join
drivers (``exec/joins.py``) wrap their jit entry points in
:class:`TrackedProgram` proxies; each distinct (program key, input
shape signature) pair becomes one :class:`ProgramRecord` holding

- the executable itself, built through the AOT ``lower().compile()``
  path so the compile wall-time is measured exactly (the jit dispatch
  path hides it inside the first call),
- XLA ``cost_analysis()`` (FLOPs, bytes accessed) and
  ``memory_analysis()`` (argument/output/temp bytes) — both guarded:
  CPU/older jaxlib may return nothing or raise, in which case the
  record degrades to timing-only with ``None`` analysis fields,
- hit/compile counters (a *hit* is one tracked invocation served by a
  cached executable; windows hit once per dispatch).

Because the registry OWNS the executables (this jax version does not
share the AOT and jit dispatch caches), it is literally the
compiled-program cache the ROADMAP's concurrent-serving item wants to
promote: a fragment-cache eviction no longer implies an XLA recompile
as long as the registry still holds the record. Any failure anywhere in
the AOT path falls back to the plain jit call — tracking can degrade,
execution cannot.

Surfaces: ``pixie_program_cache_{hits,misses,evictions}_total``
counters + the ``pixie_compile_seconds`` histogram on the default
metrics registry, the ``/debug/programz`` endpoint
(``services/observability.py``), and the ``__programs__`` telemetry
table (``services/telemetry.py`` drains :meth:`ProgramRegistry.rows`
per finished trace).

**DeviceMemoryMonitor** — periodic ``device.memory_stats()`` snapshots
exported as ``pixie_device_memory_bytes{device,kind}`` gauges (real on
TPU; ``memory_stats()`` returns None on CPU and the gauges simply don't
appear), plus per-query high-water attribution: the engine brackets
every ``execute_plan`` with :meth:`query_begin`/:meth:`query_end` and
stamps the observed peak ``bytes_in_use`` into
``QueryResourceUsage.device_peak_bytes`` (0 on stat-less backends).
"""

from __future__ import annotations

import threading
import time

from ..config import get_flag
from . import threadmap

#: ``pixie_compile_seconds`` buckets: a CPU fragment compiles in
#: ~10-100ms, a big t-digest program in minutes over the TPU tunnel.
COMPILE_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 300.0,
)

#: ``memory_stats()`` keys exported as gauges / tracked for peaks.
_MEM_KINDS = (
    "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
    "largest_free_block_bytes",
)


def shape_signature(args) -> tuple:
    """Hashable signature of a call's input pytree: treedef + per-leaf
    (shape, dtype-or-type, sharding). Exactly the distinctions XLA
    compiles separate programs for — two calls with equal signatures
    may share one executable. ~7µs per call (hot-path budget: one per
    tracked dispatch, i.e. per window)."""
    from jax import tree_util

    leaves, treedef = tree_util.tree_flatten(args)
    return (treedef, tuple(
        (
            getattr(leaf, "shape", ()),
            getattr(leaf, "dtype", None) or type(leaf),
            getattr(leaf, "sharding", None),
        )
        for leaf in leaves
    ))


class ProgramRecord:
    """One tracked XLA program: a (program key, shape signature) pair
    and everything observed about it."""

    __slots__ = (
        "program_id", "kind", "label", "sig_repr", "plan_hash",
        "compiled", "fn_id", "compiles", "hits", "compile_s_total",
        "compile_s_last", "flops", "bytes_accessed", "argument_bytes",
        "output_bytes", "temp_bytes", "peak_bytes", "created_ns",
        "last_used_ns", "seq", "pins", "aot_disabled", "jit_warm",
        "fn_ref",
    )

    def __init__(self, program_id: str, kind: str, label: str,
                 sig_repr: str, plan_hash: str = ""):
        self.program_id = program_id
        self.kind = kind
        self.label = label
        self.sig_repr = sig_repr
        self.plan_hash = plan_hash
        self.compiled = None  # AOT executable (None = timing-only)
        self.fn_id = 0  # id() of the jit fn the executable came from
        self.compiles = 0
        self.hits = 0
        self.compile_s_total = 0.0
        self.compile_s_last = 0.0
        # XLA analyses; None until a compile produced them (CPU/older
        # jax may never fill them — consumers must tolerate None).
        self.flops = None
        self.bytes_accessed = None
        self.argument_bytes = None
        self.output_bytes = None
        self.temp_bytes = None
        self.peak_bytes = None
        self.created_ns = time.time_ns()
        self.last_used_ns = self.created_ns
        self.seq = 0  # registry change sequence (telemetry drain)
        # Objects whose id() participates in the program key (string
        # dictionaries, the UDF registry — the fragment cache key is
        # id-based): pinning them here keeps a key match valid even
        # after the fragment cache evicts its own pinning entry, so a
        # registry hit can NEVER serve an executable compiled against a
        # recycled address.
        self.pins = None
        # AOT gave up for this program (lower/compile raised, or a
        # compiled executable failed at dispatch): stop re-attempting
        # and run through the plain jit call instead.
        self.aot_disabled = False
        # The jit fn's own dispatch cache has compiled this signature
        # (we timed that call). False routes the next call through the
        # miss path so a silent jit recompile — e.g. right after a
        # degrade, when every prior call went through the AOT
        # executable — is COUNTED, never mislabeled as a free hit.
        self.jit_warm = False
        # The jit fn a timing-only record's jit_warm refers to: held so
        # the fn_id comparison can never match a RECYCLED address of a
        # collected fn (same discipline as ``pins``). None while an AOT
        # executable exists (the hit path doesn't consult fn_id then).
        self.fn_ref = None

    def to_dict(self) -> dict:
        """The /debug/programz row."""
        return {
            "program_id": self.program_id,
            "kind": self.kind,
            "label": self.label,
            "shape": self.sig_repr,
            "plan_hash": self.plan_hash,
            "cached": self.compiled is not None,
            "compiles": self.compiles,
            "hits": self.hits,
            "compile_ms": round(self.compile_s_total * 1e3, 3),
            "compile_ms_last": round(self.compile_s_last * 1e3, 3),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "peak_bytes": self.peak_bytes,
            "created_ns": self.created_ns,
            "last_used_ns": self.last_used_ns,
        }


def _analyses(compiled):
    """(flops, bytes_accessed, argument, output, temp, peak) from an AOT
    Compiled — every field independently guarded to None (the CPU
    backend fills cost analysis but e.g. no generated-code sizes; other
    backends may raise on either call)."""
    flops = bytes_accessed = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            v = ca.get("flops")
            flops = float(v) if v is not None else None
            v = ca.get("bytes accessed")
            bytes_accessed = float(v) if v is not None else None
    except Exception:
        pass
    arg_b = out_b = temp_b = peak = None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        def _field(attr):
            # Per-field guard: a backend missing ONE size attribute
            # must not discard the sizes it did report.
            try:
                v = getattr(ma, attr, None)
                return int(v) if v is not None else None
            except Exception:
                return None

        arg_b = _field("argument_size_in_bytes")
        out_b = _field("output_size_in_bytes")
        temp_b = _field("temp_size_in_bytes")
        if arg_b is not None and out_b is not None and temp_b is not None:
            # Static allocation high-water approximation: XLA does not
            # expose a true peak on every backend, but args + outputs +
            # temps bounds what the program pins while running.
            peak = arg_b + out_b + temp_b
    return flops, bytes_accessed, arg_b, out_b, temp_b, peak


class TrackedProgram:
    """Callable proxy over one jitted entry point: every invocation is
    keyed by input shape signature against the registry. Misses compile
    via the AOT path (exact timing + analyses) and cache the
    executable; hits dispatch the cached executable directly (same
    per-call cost as the jit fast path — measured ~32µs vs ~31µs on
    CPU). Any AOT failure falls back to the plain jit call."""

    __slots__ = ("fn", "_registry", "_key", "_kind", "_label", "_pins")

    def __init__(self, fn, registry: "ProgramRegistry", key, kind: str,
                 label: str, pins=None):
        self.fn = fn
        self._registry = registry
        self._key = key
        self._kind = kind
        self._label = label
        self._pins = pins

    def __call__(self, *args):
        reg = self._registry
        # Profiler phase bracket: samples landing while the program
        # dispatches/runs are device work (or the wait for it), not
        # host execution — set_phase is a no-op on unattributed
        # threads, so the per-window cost is one dict get.
        tm = threadmap.set_phase("device_dispatch")
        try:
            try:
                sig = shape_signature(args)
                hash(sig)
            except Exception:
                return self.fn(*args)  # unhashable input: untracked call
            rec = reg._lookup(self._key, sig, id(self.fn))
            if rec is not None:
                if rec.compiled is not None:
                    try:
                        return rec.compiled(*args)
                    except Exception:
                        # Executable/input mismatch the signature missed
                        # (e.g. an exotic sharding): drop the executable
                        # for this record and re-raise nothing — the jit
                        # path below recomputes identically (programs
                        # are pure).
                        reg._degrade(rec)
                return self.fn(*args)  # timing-only record: plain jit path
            return reg._compile_and_run(self, sig, args)
        finally:
            threadmap.restore(tm)


class ProgramRegistry:
    """Bounded LRU of :class:`ProgramRecord`. Thread-safe; compilation
    runs outside the lock (a miss must not serialize unrelated
    programs behind a multi-second XLA compile)."""

    def __init__(self, metrics_registry=None, size: int | None = None):
        self._metrics_registry = metrics_registry
        self._size = size  # None = read program_registry_size per miss
        self._lock = threading.Lock()
        self._records: dict = {}  # (key, sig) -> ProgramRecord
        self._seq = 0
        self._metrics: dict | None = None
        # Hit increments batch registry-side and flush to the shared
        # prometheus counter every _HIT_FLUSH hits (and at every
        # surface read): one global-lock round trip per window across
        # all engines was the hot path's contention point.
        self._pending_hits = 0
        # In-flight compile dedup: (key, sig) -> threading.Event. The
        # first thread to miss compiles; concurrent missers wait on the
        # event and re-lookup — a multi-second XLA compile must not run
        # twice for the same program.
        self._inflight: dict = {}
        # LRU-evicted records, keyed by program_id (executable/pins
        # dropped, counters kept). Serves two contracts: (a) rows()
        # still drains an evicted record's FINAL state (its seq is
        # bumped at eviction), so undrained hit increments are never
        # lost to __programs__; (b) a re-created record RESUMES these
        # counters, keeping the per-program_id stream monotonic.
        # Bounded FIFO at 4x the registry size — churn beyond that can
        # reset a long-gone program's counters, a documented memory
        # bound.
        self._evicted: dict = {}

    # -- wrapping ------------------------------------------------------------
    def wrap(self, fn, kind: str, key, label: str = "", pins=None):
        """Wrap a jitted entry point; returns ``fn`` unchanged when the
        registry is disabled (``program_registry_size`` <= 0) or ``fn``
        is not trackable. ``pins`` are objects whose id() participates
        in ``key`` — held by the record so a key match stays valid."""
        if fn is None or self._max_size() <= 0:
            return fn
        if isinstance(fn, TrackedProgram):
            return fn
        if not hasattr(fn, "lower"):
            return fn  # not a jit stage: nothing to AOT-compile
        return TrackedProgram(fn, self, key, kind, label, pins=pins)

    def _max_size(self) -> int:
        if self._size is not None:
            return int(self._size)
        return int(get_flag("program_registry_size"))

    # -- metrics -------------------------------------------------------------
    def _m(self) -> dict:
        if self._metrics is not None:
            return self._metrics
        with self._lock:
            if self._metrics is not None:
                return self._metrics
            if self._metrics_registry is None:
                from ..services.observability import default_registry

                self._metrics_registry = default_registry
            reg = self._metrics_registry
            # Flush batched hit increments at every /metrics render so
            # a scrape never under-reports by the batch remainder.
            # Registered under the lock: two racing first callers must
            # not install the collector twice.
            reg.register_collector(self._flush_hits_collector)
            self._metrics = {
                "hits": reg.counter(
                    "pixie_program_cache_hits_total",
                    "Tracked program invocations served by a cached "
                    "XLA executable (one per dispatch, i.e. per window)",
                ),
                "misses": reg.counter(
                    "pixie_program_cache_misses_total",
                    "Tracked program invocations that compiled a new "
                    "XLA executable (first shape, eviction, or rebuild)",
                ),
                "evictions": reg.counter(
                    "pixie_program_cache_evictions_total",
                    "Program records LRU-evicted from the registry "
                    "(their executables recompile on next use)",
                ),
                "compile": reg.histogram(
                    "pixie_compile_seconds",
                    "XLA compile wall time per tracked program "
                    "(the AOT lower().compile() span)",
                    buckets=COMPILE_BUCKETS,
                ),
            }
        return self._metrics

    #: Batched hit increments flush to the prometheus counter at this
    #: granularity (also flushed by every surface read).
    _HIT_FLUSH = 64

    # -- the dispatch paths (TrackedProgram.__call__) ------------------------
    def _lookup(self, key, sig, fn_id: int):
        """Hit path: return the record for (key, sig) and count the hit,
        or None when this call must go through the miss path. A record
        without an executable only counts hits while the jit fn's own
        dispatch cache is provably warm FOR THIS fn — after a degrade
        or a fragment rebuild the jit call would silently recompile,
        which must be counted, never mislabeled as a free hit."""
        flush = 0
        with self._lock:
            rec = self._records.get((key, sig))
            if rec is None:
                return None
            if rec.compiled is None and not (
                rec.jit_warm and rec.fn_id == fn_id
            ):
                return None
            rec.hits += 1
            rec.last_used_ns = time.time_ns()
            self._seq += 1
            rec.seq = self._seq
            self._pending_hits += 1
            if self._pending_hits >= self._HIT_FLUSH:
                flush, self._pending_hits = self._pending_hits, 0
        if flush:
            self._m()["hits"].inc(flush)
        return rec

    def _flush_hits_locked(self) -> int:
        """Caller holds self._lock; returns the count to inc OUTSIDE."""
        flush, self._pending_hits = self._pending_hits, 0
        return flush

    def _flush_hits_collector(self, _reg) -> None:
        """Metrics-render collector: drain the batched hit count."""
        m = self._metrics
        if m is None:
            return  # render raced _m()'s registration; nothing pending
        with self._lock:
            flush = self._flush_hits_locked()
        if flush:
            m["hits"].inc(flush)

    def _degrade(self, rec: ProgramRecord) -> None:
        """The cached executable failed at dispatch: drop it, stop
        re-attempting AOT for this program, and route the NEXT call
        through the miss path so the jit recompile it will trigger is
        timed and counted."""
        with self._lock:
            rec.compiled = None
            rec.aot_disabled = True
            rec.jit_warm = False

    def _compile_and_run(self, prog: TrackedProgram, sig, args):
        """Miss path: AOT-compile (timed, analyzed), record, execute.
        Every step guarded — a failure anywhere degrades the record to
        timing-only and executes through the plain jit call. Concurrent
        missers of the SAME (key, sig) wait for the first compiler and
        re-lookup instead of duplicating a multi-second XLA compile;
        different programs never serialize on each other."""
        fn = prog.fn
        key = (prog._key, sig)
        with self._lock:
            rec = self._records.get(key)
            attempt_aot = not (rec is not None and rec.aot_disabled)
            ev = self._inflight.get(key)
            if ev is None:
                self._inflight[key] = threading.Event()
        if ev is not None:
            # Another thread is compiling this exact program: wait for
            # its record, then retry the hit path (falling back to the
            # plain jit call if it degraded meanwhile). No timeout
            # fallthrough — the owner's finally ALWAYS sets the event,
            # and duplicating a genuinely wedged multi-minute compile
            # would only multiply the stall by the waiter count.
            ev.wait()
            rec = self._lookup(prog._key, sig, id(fn))
            if rec is not None and rec.compiled is not None:
                try:
                    return rec.compiled(*args)
                except Exception:
                    self._degrade(rec)
            return fn(*args)
        try:
            t0 = time.perf_counter()
            compiled = None
            analyses = (None,) * 6
            if attempt_aot:
                try:
                    compiled = fn.lower(*args).compile()
                    compile_s = time.perf_counter() - t0
                    analyses = _analyses(compiled)
                except Exception:
                    compiled = None
            out = None
            ran = False
            if compiled is not None:
                try:
                    out = compiled(*args)
                    ran = True
                except Exception:
                    compiled = None
            if not ran:
                # jit fallback: this call includes jit's own compile, so
                # the timing still approximates compile cost
                # (timing-only mode; jit_warm marks the cache hot).
                out = fn(*args)
                compile_s = time.perf_counter() - t0
            self._record_compile(
                prog, sig, compiled, compile_s, analyses,
                aot_failed=attempt_aot and compiled is None,
            )
            return out
        finally:
            with self._lock:
                done = self._inflight.pop(key, None)
            if done is not None:
                done.set()

    def _record_compile(self, prog: TrackedProgram, sig, compiled,
                        compile_s: float, analyses,
                        aot_failed: bool = False) -> None:
        key = prog._key
        with self._lock:
            rec = self._records.get((key, sig))
            if rec is None:
                pid = f"{hash((key, sig)) & (2**64 - 1):016x}"
                rec = ProgramRecord(
                    pid, prog._kind, prog._label, _sig_repr(sig),
                )
                base = self._evicted.pop(pid, None)
                if base is not None:
                    # Resume the evicted incarnation's counters so the
                    # telemetry stream stays monotonic per program_id.
                    rec.compiles = base.compiles
                    rec.hits = base.hits
                    rec.compile_s_total = base.compile_s_total
            rec.compiled = compiled
            rec.fn_id = id(prog.fn)
            rec.pins = prog._pins
            if aot_failed:
                rec.aot_disabled = True
            rec.jit_warm = compiled is None  # the jit path just ran
            # Pin the fn for timing-only records: jit_warm is only
            # meaningful for THIS fn object, and an unpinned id() could
            # be recycled by a rebuilt fragment's fn.
            rec.fn_ref = prog.fn if compiled is None else None
            rec.compiles += 1
            rec.compile_s_last = compile_s
            rec.compile_s_total += compile_s
            flops, bytes_acc, arg_b, out_b, temp_b, peak = analyses
            # Per-field: a backend reporting only SOME sizes keeps them.
            if flops is not None:
                rec.flops = flops
            if bytes_acc is not None:
                rec.bytes_accessed = bytes_acc
            if arg_b is not None:
                rec.argument_bytes = arg_b
            if out_b is not None:
                rec.output_bytes = out_b
            if temp_b is not None:
                rec.temp_bytes = temp_b
            if peak is not None:
                rec.peak_bytes = peak
            rec.last_used_ns = time.time_ns()
            self._seq += 1
            rec.seq = self._seq
            self._records[(key, sig)] = rec
            evicted = 0
            max_size = self._max_size()
            while len(self._records) > max(max_size, 1):
                # Evict least-recently-used by timestamp (insertion
                # order no longer tracks recency — hits deliberately
                # skip the pop/reinsert dict churn).
                lru = min(
                    self._records, key=lambda k: self._records[k].last_used_ns
                )
                gone = self._records.pop(lru)
                # Free the heavy state, keep the counters, and bump the
                # seq so the next drain emits the FINAL row.
                gone.compiled = None
                gone.pins = None
                gone.fn_ref = None
                gone.jit_warm = False
                self._seq += 1
                gone.seq = self._seq
                self._evicted[gone.program_id] = gone
                evicted += 1
            while len(self._evicted) > 4 * max(max_size, 1):
                self._evicted.pop(next(iter(self._evicted)))
        m = self._m()
        m["misses"].inc()
        m["compile"].observe(compile_s)
        if evicted:
            m["evictions"].inc(evicted)

    # -- surfaces ------------------------------------------------------------
    def programz(self) -> dict:
        """The /debug/programz body: every record, most recent first."""
        with self._lock:
            recs = [r.to_dict() for r in self._records.values()]
            flush = self._flush_hits_locked()
        if flush:
            self._m()["hits"].inc(flush)
        recs.sort(key=lambda r: r["last_used_ns"], reverse=True)
        hits = sum(r["hits"] for r in recs)
        compiles = sum(r["compiles"] for r in recs)
        return {
            "programs": recs,
            "count": len(recs),
            "hits": hits,
            "compiles": compiles,
            "compile_ms": round(
                sum(r["compile_ms"] for r in recs), 3
            ),
        }

    def rows(self, since_seq: int) -> tuple:
        """(new_cursor, rows) — one ``__programs__`` row per record that
        changed since ``since_seq`` (cumulative counters; the LATEST row
        per program_id is its current state). Each telemetry collector
        keeps its own cursor, so N agents in one process each fold the
        full program history into their own table."""
        import itertools

        rows = []
        with self._lock:
            flush = self._flush_hits_locked()
            cursor = self._seq
            # Evicted records drain too (their seq was bumped at
            # eviction): the final counter state always reaches the
            # table even when the program never runs again.
            for rec in itertools.chain(
                self._records.values(), self._evicted.values()
            ):
                if rec.seq > since_seq:
                    rows.append({
                        "program_id": rec.program_id,
                        "kind": rec.kind,
                        "label": rec.label,
                        "compiles": rec.compiles,
                        "hits": rec.hits,
                        "compile_ms": rec.compile_s_total * 1e3,
                        "flops": (
                            float(rec.flops) if rec.flops is not None
                            else 0.0
                        ),
                        "bytes_accessed": (
                            float(rec.bytes_accessed)
                            if rec.bytes_accessed is not None else 0.0
                        ),
                        "argument_bytes": int(rec.argument_bytes or 0),
                        "temp_bytes": int(rec.temp_bytes or 0),
                        "peak_bytes": int(rec.peak_bytes or 0),
                        "last_used_ns": rec.last_used_ns,
                    })
        if flush:
            self._m()["hits"].inc(flush)
        return cursor, rows

    def stats(self) -> dict:
        with self._lock:
            return {
                "programs": len(self._records),
                "hits": sum(r.hits for r in self._records.values()),
                "compiles": sum(
                    r.compiles for r in self._records.values()
                ),
            }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


def _sig_repr(sig) -> str:
    """Compact human form of a shape signature for programz/telemetry:
    the distinct leaf shapes with multiplicities, e.g.
    '3x[131072]float32,[scalar]int32'."""
    _treedef, leaves = sig
    counts: dict = {}
    for shape, dtype, _sharding in leaves:
        name = getattr(dtype, "name", None) or getattr(
            dtype, "__name__", None
        ) or str(dtype)
        k = (tuple(shape), name)
        counts[k] = counts.get(k, 0) + 1
    parts = []
    for (shape, dtype), n in list(counts.items())[:8]:
        s = "x".join(str(d) for d in shape) or "scalar"
        parts.append(f"{n}x[{s}]{dtype}" if n > 1 else f"[{s}]{dtype}")
    if len(counts) > 8:
        parts.append("...")
    return ",".join(parts)


class DeviceMemoryMonitor:
    """``device.memory_stats()`` snapshots: gauges + per-query peaks.

    CPU devices return None from ``memory_stats()`` — every consumer of
    this class sees zeros/absent gauges there, never an error (the
    None-guard contract the telemetry tests pin). A poll thread
    (``device_memory_poll_s`` > 0) tightens per-query peak resolution;
    without it peaks come from the query-boundary samples alone.
    """

    def __init__(self, metrics_registry=None):
        self._metrics_registry = metrics_registry
        self._lock = threading.Lock()
        self._open: list[dict] = []  # live per-query peak trackers
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._collector_installed = False

    # -- snapshots -----------------------------------------------------------
    @staticmethod
    def snapshot() -> dict:
        """{device label: {kind: bytes}} for devices that report stats
        (TPU); stat-less devices (CPU) are simply absent."""
        import jax

        out: dict = {}
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            label = f"{d.platform}:{d.id}"
            out[label] = {
                k: int(v) for k, v in stats.items()
                if isinstance(v, (int, float))
            }
        return out

    def _in_use(self) -> int:
        """Max ``bytes_in_use`` across devices (0 when unreported)."""
        snap = self.snapshot()
        return max(
            (s.get("bytes_in_use", 0) for s in snap.values()), default=0
        )

    # -- per-query peak attribution (engine execute_plan brackets) -----------
    def query_begin(self) -> dict:
        token = {"peak": self._in_use()}
        with self._lock:
            self._open.append(token)
        return token

    def query_end(self, token: dict) -> int:
        """High-water device bytes_in_use observed while the query ran
        (begin sample, any poll samples, end sample). 0 on backends
        without memory stats."""
        end = self._in_use()
        with self._lock:
            # Remove by IDENTITY: two overlapping queries whose begin
            # samples were equal hold ==-equal token dicts, and
            # list.remove would drop the OTHER query's token, cutting
            # it off from further poll updates.
            self._open = [t for t in self._open if t is not token]
            return max(token["peak"], end)

    # -- gauges + poll loop --------------------------------------------------
    def install_collector(self) -> None:
        """Refresh ``pixie_device_memory_bytes`` at every /metrics
        render (idempotent)."""
        if self._collector_installed:
            return
        if self._metrics_registry is None:
            from ..services.observability import default_registry

            self._metrics_registry = default_registry
        self._metrics_registry.register_collector(self._collect)
        self._collector_installed = True

    def _collect(self, reg) -> None:
        g = reg.gauge(
            "pixie_device_memory_bytes",
            "device.memory_stats() snapshot per local device "
            "(TPU-real; CPU devices report no stats and emit nothing)",
        )
        for dev, stats in self.snapshot().items():
            for kind in _MEM_KINDS:
                if kind in stats:
                    g.labels(device=dev, kind=kind).set(stats[kind])

    def start(self, poll_s: float | None = None) -> None:
        """Start the background poller (no-op when the period is <= 0
        or it is already running)."""
        period = (
            float(get_flag("device_memory_poll_s"))
            if poll_s is None else float(poll_s)
        )
        if period <= 0 or self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.wait(period):
                peak = self._in_use()
                with self._lock:
                    for token in self._open:
                        if peak > token["peak"]:
                            token["peak"] = peak

        self._thread = threading.Thread(
            target=run, name="device-memory-poll", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)


_DEFAULT_REGISTRY: ProgramRegistry | None = None
_DEFAULT_MONITOR: DeviceMemoryMonitor | None = None
_DEFAULT_LOCK = threading.Lock()


def default_program_registry() -> ProgramRegistry:
    """The process-wide program registry (fragments are shared process-
    wide through the fragment cache, so their programs are too)."""
    global _DEFAULT_REGISTRY
    with _DEFAULT_LOCK:
        if _DEFAULT_REGISTRY is None:
            _DEFAULT_REGISTRY = ProgramRegistry()
        return _DEFAULT_REGISTRY


def default_device_monitor() -> DeviceMemoryMonitor:
    """The process-wide device-memory monitor (one /metrics collector,
    shared per-query peak tracking across engines)."""
    global _DEFAULT_MONITOR
    with _DEFAULT_LOCK:
        if _DEFAULT_MONITOR is None:
            _DEFAULT_MONITOR = DeviceMemoryMonitor()
            _DEFAULT_MONITOR.install_collector()
        return _DEFAULT_MONITOR
