"""Expression binder: Expr tree x Relation x dictionaries -> device closure.

Reference parity: ``src/carnot/exec/expression_evaluator.{h,cc}`` — but
where Carnot walks the expression tree per RowBatch (vector- or
arrow-native, ``expression_evaluator.h:89-91``), here the whole tree is
bound ONCE into a jnp closure that XLA fuses into the fragment program.

Binding rules:
- DEVICE UDFs: recursive bind, implicit casts from the lattice, traced.
- HOST_DICT UDFs: the string argument's dictionary is transformed
  host-side at bind time; the device sees an int32 gather (lookup table
  for scalar returns, id-remap for string returns).
- STRING literals are encoded against the sibling argument's dictionary
  (equality filters on unseen literals become id==-1: always false).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..types.dtypes import DataType
from ..types.strings import NULL_ID, StringDictionary
from ..udf.registry import Registry
from ..udf.udf import Executor, apply_cast
from .plan import ColumnRef, Expr, FuncCall, Literal


class BindError(TypeError):
    pass


@dataclass
class BoundExpr:
    """fn(cols: dict[str, planes-tuple]) -> plane array (broadcastable)."""

    fn: Callable
    dtype: DataType
    # For STRING-typed results: the dictionary its int32 ids refer to.
    dict: Optional[StringDictionary] = None


def bind_expr(expr: Expr, relation, dicts, registry: Registry) -> BoundExpr:
    if isinstance(expr, ColumnRef):
        if not relation.has_column(expr.name):
            raise BindError(f"unknown column {expr.name!r} in {relation}")
        dt = relation.col_type(expr.name)
        name = expr.name
        if dt == DataType.UINT128:
            fn = lambda cols: cols[name]  # (hi, lo) tuple
        else:
            fn = lambda cols: cols[name][0]
        return BoundExpr(fn=fn, dtype=dt, dict=dicts.get(name))

    if isinstance(expr, Literal):
        if expr.dtype == DataType.STRING:
            # Encoded later, in FuncCall context (needs a sibling dict).
            raise BindError(
                f"string literal {expr.value!r} outside a function context"
            )
        val = expr.value
        return BoundExpr(fn=lambda cols: jnp.asarray(val), dtype=expr.dtype)

    if isinstance(expr, FuncCall):
        return _bind_func(expr, relation, dicts, registry)

    raise BindError(f"cannot bind expression {expr!r}")


def _bind_func(expr: FuncCall, relation, dicts, registry: Registry) -> BoundExpr:
    # Bind non-string-literal args first to learn types and dictionaries.
    bound: list = [None] * len(expr.args)
    str_literals: list = []
    for i, a in enumerate(expr.args):
        if isinstance(a, Literal) and a.dtype == DataType.STRING:
            str_literals.append(i)
        else:
            bound[i] = bind_expr(a, relation, dicts, registry)

    arg_types = [
        DataType.STRING if i in str_literals else bound[i].dtype
        for i in range(len(expr.args))
    ]
    udf = registry.get_scalar(expr.name, arg_types)

    if udf.executor == Executor.HOST_DICT:
        return _bind_host_dict(expr, udf, bound, str_literals, relation, dicts, registry)

    # DEVICE: ids from different dictionaries are not comparable — align
    # every STRING arg onto one shared dictionary (id-preserving union;
    # later args get a remap gather). The union snapshots the dictionaries
    # at bind time: queries assume no concurrent appends to the source
    # table while executing (the service shell serializes these).
    sibling_dict = None
    for i, b in enumerate(bound):
        if b is None or b.dict is None:
            continue
        if sibling_dict is None:
            sibling_dict = b.dict
        elif b.dict is not sibling_dict:
            merged, _, remap = sibling_dict.union(b.dict)
            remap_j = np.asarray(remap)
            prev_fn = b.fn
            bound[i] = BoundExpr(
                fn=(
                    lambda _f, _r: (
                        lambda cols: jnp.where(
                            (ids := _f(cols)) >= 0,
                            jnp.asarray(_r)[jnp.clip(ids, 0)],
                            NULL_ID,
                        )
                    )
                )(prev_fn, remap_j),
                dtype=DataType.STRING,
                dict=merged,
            )
            sibling_dict = merged

    # Encode string literals against the shared dictionary.
    for i in str_literals:
        lit = expr.args[i]
        if sibling_dict is None:
            raise BindError(
                f"string literal {lit.value!r} in {expr.name} has no sibling "
                "dictionary to encode against"
            )
        lit_id = sibling_dict.lookup(lit.value)
        bound[i] = BoundExpr(
            fn=(lambda _id: (lambda cols: jnp.asarray(_id, dtype=jnp.int32)))(lit_id),
            dtype=DataType.STRING,
            dict=sibling_dict,
        )

    casts = list(zip(arg_types, udf.arg_types))
    arg_fns = [b.fn for b in bound]
    fn_udf = udf.fn

    def fn(cols):
        vals = [apply_cast(f(cols), have, want) for f, (have, want) in zip(arg_fns, casts)]
        return fn_udf(*vals)

    out_dict = None
    if udf.return_type == DataType.STRING:
        out_dict = udf.out_dict if udf.out_dict is not None else sibling_dict
    return BoundExpr(fn=fn, dtype=udf.return_type, dict=out_dict)


def _bind_host_dict(expr, udf, bound, str_literals, relation, dicts, registry) -> BoundExpr:
    """Run the UDF over the dictionary host-side; device applies a gather."""
    d_i = udf.dict_arg
    if d_i in str_literals or bound[d_i] is None or bound[d_i].dict is None:
        raise BindError(
            f"{udf.name}: argument {d_i} must be a string column/expression "
            "with a dictionary"
        )
    src = bound[d_i]
    src_dict = src.dict

    # All other args must be literals (reference: these are Init() args of
    # the C++ UDFs — compile-time constants).
    literal_vals: dict[int, object] = {}
    for i, a in enumerate(expr.args):
        if i == d_i:
            continue
        if not isinstance(a, Literal):
            raise BindError(
                f"{udf.name}: argument {i} must be a literal (host-dict UDF)"
            )
        literal_vals[i] = a.value

    def call_one(s: str):
        args = [literal_vals.get(i) if i != d_i else s for i in range(len(expr.args))]
        return udf.fn(*args)

    src_fn = src.fn
    if udf.return_type == DataType.STRING:
        new_dict, remap = src_dict.transform(call_one)
        remap_j = np.asarray(remap)

        def fn(cols):
            # jnp.asarray at TRACE time: an eagerly-created jax Array
            # captured as a jit constant poisons axon-tunnel dispatch.
            ids = src_fn(cols)
            return jnp.where(
                ids >= 0, jnp.asarray(remap_j)[jnp.clip(ids, 0)], NULL_ID
            )

        return BoundExpr(fn=fn, dtype=DataType.STRING, dict=new_dict)

    null_value = {
        DataType.BOOLEAN: False,
        DataType.INT64: 0,
        DataType.FLOAT64: float("nan"),
        DataType.TIME64NS: 0,
    }[udf.return_type]
    np_dt = {
        DataType.BOOLEAN: np.bool_,
        DataType.INT64: np.int64,
        DataType.FLOAT64: np.float32,
        DataType.TIME64NS: np.int64,
    }[udf.return_type]
    table = np.asarray([call_one(s) for s in src_dict.strings] + [null_value], dtype=np_dt)
    table_j = table
    k = len(src_dict.strings)

    def fn(cols):
        ids = src_fn(cols)
        safe = jnp.where((ids >= 0) & (ids < k), ids, k)
        return jnp.asarray(table_j)[safe]

    return BoundExpr(fn=fn, dtype=udf.return_type)
