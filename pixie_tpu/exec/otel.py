"""OTel export: result batches -> OTLP-shaped payloads.

Reference parity: ``src/carnot/exec/otel_export_sink_node.{h,cc}``
(``:40``) — converts RowBatches into OpenTelemetry metrics/spans and
ships them over OTLP gRPC; the planner side is the ``px.otel`` module
(``planner/objects/otel.h:35``). Payloads here are the OTLP JSON
encoding (ResourceMetrics / ResourceSpans dicts); the transport is a
pluggable exporter callback — in-memory collection by default, an OTLP
HTTP/gRPC pusher where the deployment provides one (grpc is gated: not
part of the baked environment).

The engine's own query-lifecycle traces (``exec/trace.py``) dogfood
this path: ``QueryTrace.to_otlp()`` builds the same ResourceSpans
payload shape (via ``_attr_kvs``) and ``Tracer`` pushes it through
``OTLPHttpExporter`` when the ``trace_export_url`` flag is set.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class OTelEndpointConfig:
    url: str = ""
    headers: tuple = ()  # tuple[(k, v)]
    insecure: bool = False


@dataclass(frozen=True)
class OTelMetricGauge:
    name: str
    value_column: str
    attributes: tuple = ()  # tuple[(attr name, column name)]
    unit: str = ""
    description: str = ""


@dataclass(frozen=True)
class OTelMetricSummary:
    """Quantile summary metric: columns per quantile point."""

    name: str
    count_column: str
    quantile_columns: tuple = ()  # tuple[(q float, column name)]
    attributes: tuple = ()
    unit: str = ""
    description: str = ""


@dataclass(frozen=True)
class OTelSpan:
    name: str  # literal name or (when name_is_column) a column
    start_time_column: str = "time_"
    end_time_column: str = "end_time"
    attributes: tuple = ()
    name_is_column: bool = False


@dataclass(frozen=True)
class OTelDataSpec:
    endpoint: OTelEndpointConfig = field(default_factory=OTelEndpointConfig)
    resource: tuple = ()  # tuple[(attr, literal str or ("column", name))]
    data: tuple = ()  # tuple[Gauge | Summary | Span]

    def referenced_columns(self) -> set:
        cols = set()
        for _a, v in self.resource:
            if isinstance(v, tuple) and v[0] == "column":
                cols.add(v[1])
        for d in self.data:
            if isinstance(d, OTelMetricGauge):
                cols.add(d.value_column)
            elif isinstance(d, OTelMetricSummary):
                cols.add(d.count_column)
                cols.update(c for _q, c in d.quantile_columns)
            elif isinstance(d, OTelSpan):
                cols.update({d.start_time_column, d.end_time_column})
                if d.name_is_column:
                    cols.add(d.name)
            cols.update(c for _a, c in getattr(d, "attributes", ()))
        return cols


def _attr_kvs(pairs):
    return [
        {"key": k, "value": {"stringValue": str(v)}} for k, v in pairs
    ]


def batch_to_otlp(hb, spec: OTelDataSpec) -> dict:
    """One HostBatch -> {'resourceMetrics': [...], 'resourceSpans': [...]}.

    Rows are split by their resource-attribute values — one
    ResourceMetrics/ResourceSpans entry per distinct resource, as the
    reference sink does (otel_export_sink_node.cc groups by resource).
    """
    d = hb.to_pydict()
    n = hb.length

    res_cols = [
        v[1]
        for _a, v in spec.resource
        if isinstance(v, tuple) and v[0] == "column"
    ]
    groups: dict[tuple, list[int]] = {}
    for i in range(n):
        groups.setdefault(tuple(d[c][i] for c in res_cols), []).append(i)
    if not groups:
        groups[()] = []

    def resource_attrs(key: tuple):
        out, it = [], iter(key)
        for attr, v in spec.resource:
            if isinstance(v, tuple) and v[0] == "column":
                out.append((attr, next(it)))
            else:
                out.append((attr, v))
        return _attr_kvs(out)

    payload: dict = {}
    for key, rows in groups.items():
        gauges, summaries, spans = [], [], []
        for item in spec.data:
            if isinstance(item, OTelMetricGauge):
                pts = [
                    {
                        "timeUnixNano": int(d["time_"][i]) if "time_" in d else 0,
                        "asDouble": float(d[item.value_column][i]),
                        "attributes": _attr_kvs(
                            (a, d[c][i]) for a, c in item.attributes
                        ),
                    }
                    for i in rows
                ]
                gauges.append(
                    {
                        "name": item.name,
                        "unit": item.unit,
                        "description": item.description,
                        "gauge": {"dataPoints": pts},
                    }
                )
            elif isinstance(item, OTelMetricSummary):
                pts = [
                    {
                        "timeUnixNano": int(d["time_"][i]) if "time_" in d else 0,
                        "count": int(d[item.count_column][i]),
                        "quantileValues": [
                            {"quantile": q, "value": float(d[c][i])}
                            for q, c in item.quantile_columns
                        ],
                        "attributes": _attr_kvs(
                            (a, d[c][i]) for a, c in item.attributes
                        ),
                    }
                    for i in rows
                ]
                summaries.append(
                    {
                        "name": item.name,
                        "unit": item.unit,
                        "description": item.description,
                        "summary": {"dataPoints": pts},
                    }
                )
            elif isinstance(item, OTelSpan):
                for i in rows:
                    spans.append(
                        {
                            "name": (
                                str(d[item.name][i])
                                if item.name_is_column
                                else item.name
                            ),
                            "startTimeUnixNano": int(d[item.start_time_column][i]),
                            "endTimeUnixNano": int(d[item.end_time_column][i]),
                            "attributes": _attr_kvs(
                                (a, d[c][i]) for a, c in item.attributes
                            ),
                        }
                    )
        metrics = gauges + summaries
        if metrics:
            payload.setdefault("resourceMetrics", []).append(
                {
                    "resource": {"attributes": resource_attrs(key)},
                    "scopeMetrics": [{"metrics": metrics}],
                }
            )
        if spans:
            payload.setdefault("resourceSpans", []).append(
                {
                    "resource": {"attributes": resource_attrs(key)},
                    "scopeSpans": [{"spans": spans}],
                }
            )
    return payload


class OTLPHttpExporter:
    """Push OTLP-JSON payloads over HTTP (stdlib urllib; no grpc in env).

    Reference transport parity: ``otel_export_sink_node.cc`` ships the
    same payloads over OTLP gRPC with retries; OTLP/HTTP is the spec's
    sibling encoding (POST /v1/metrics, /v1/traces). Bind an instance as
    an engine's ``export_otel`` to turn collected exports into pushes.
    """

    def __init__(self, base_url: str, headers=(), timeout_s: float = 5.0,
                 max_retries: int = 2):
        self.base_url = base_url.rstrip("/")
        self.headers = tuple(headers)
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.pushed = 0
        self.errors = 0

    def __call__(self, payload: dict, endpoint=None) -> None:
        url = self.base_url
        if endpoint is not None and getattr(endpoint, "url", ""):
            url = endpoint.url.rstrip("/")
        jobs = []
        if payload.get("resourceMetrics"):
            jobs.append((url + "/v1/metrics",
                         {"resourceMetrics": payload["resourceMetrics"]}))
        if payload.get("resourceSpans"):
            jobs.append((url + "/v1/traces",
                         {"resourceSpans": payload["resourceSpans"]}))
        for u, body in jobs:
            self._post(u, body, endpoint)

    def _post(self, url: str, body: dict, endpoint) -> None:
        import json as _json
        import time as _time
        import urllib.error
        import urllib.request

        data = _json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        headers.update(dict(self.headers))
        if endpoint is not None:
            headers.update(dict(getattr(endpoint, "headers", ()) or ()))
        last = None
        for attempt in range(self.max_retries + 1):
            req = urllib.request.Request(url, data=data, headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s):
                    self.pushed += 1
                    return
            except urllib.error.HTTPError as e:
                last = e
                if e.code not in (429,) and e.code < 500:
                    break  # 4xx (auth, bad request): retrying cannot help
                if attempt < self.max_retries:
                    _time.sleep(min(0.2 * (2 ** attempt), 2.0))
            except (urllib.error.URLError, OSError) as e:
                last = e
                if attempt < self.max_retries:
                    _time.sleep(min(0.2 * (2 ** attempt), 2.0))
        self.errors += 1
        raise ExportError(f"OTLP push to {url} failed: {last}")


class ExportError(Exception):
    pass
