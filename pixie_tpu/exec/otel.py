"""OTel export: result batches -> OTLP-shaped payloads.

Reference parity: ``src/carnot/exec/otel_export_sink_node.{h,cc}``
(``:40``) — converts RowBatches into OpenTelemetry metrics/spans and
ships them over OTLP gRPC; the planner side is the ``px.otel`` module
(``planner/objects/otel.h:35``). Payloads here are the OTLP JSON
encoding (ResourceMetrics / ResourceSpans dicts); the transport is a
pluggable exporter callback — in-memory collection by default, an OTLP
HTTP/gRPC pusher where the deployment provides one (grpc is gated: not
part of the baked environment).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class OTelEndpointConfig:
    url: str = ""
    headers: tuple = ()  # tuple[(k, v)]
    insecure: bool = False


@dataclass(frozen=True)
class OTelMetricGauge:
    name: str
    value_column: str
    attributes: tuple = ()  # tuple[(attr name, column name)]
    unit: str = ""
    description: str = ""


@dataclass(frozen=True)
class OTelMetricSummary:
    """Quantile summary metric: columns per quantile point."""

    name: str
    count_column: str
    quantile_columns: tuple = ()  # tuple[(q float, column name)]
    attributes: tuple = ()
    unit: str = ""
    description: str = ""


@dataclass(frozen=True)
class OTelSpan:
    name: str  # literal name or (when name_is_column) a column
    start_time_column: str = "time_"
    end_time_column: str = "end_time"
    attributes: tuple = ()
    name_is_column: bool = False


@dataclass(frozen=True)
class OTelDataSpec:
    endpoint: OTelEndpointConfig = field(default_factory=OTelEndpointConfig)
    resource: tuple = ()  # tuple[(attr, literal str or ("column", name))]
    data: tuple = ()  # tuple[Gauge | Summary | Span]

    def referenced_columns(self) -> set:
        cols = set()
        for _a, v in self.resource:
            if isinstance(v, tuple) and v[0] == "column":
                cols.add(v[1])
        for d in self.data:
            if isinstance(d, OTelMetricGauge):
                cols.add(d.value_column)
            elif isinstance(d, OTelMetricSummary):
                cols.add(d.count_column)
                cols.update(c for _q, c in d.quantile_columns)
            elif isinstance(d, OTelSpan):
                cols.update({d.start_time_column, d.end_time_column})
                if d.name_is_column:
                    cols.add(d.name)
            cols.update(c for _a, c in getattr(d, "attributes", ()))
        return cols


def _attr_kvs(pairs):
    return [
        {"key": k, "value": {"stringValue": str(v)}} for k, v in pairs
    ]


def batch_to_otlp(hb, spec: OTelDataSpec) -> dict:
    """One HostBatch -> {'resourceMetrics': [...], 'resourceSpans': [...]}.

    Rows are split by their resource-attribute values — one
    ResourceMetrics/ResourceSpans entry per distinct resource, as the
    reference sink does (otel_export_sink_node.cc groups by resource).
    """
    d = hb.to_pydict()
    n = hb.length

    res_cols = [
        v[1]
        for _a, v in spec.resource
        if isinstance(v, tuple) and v[0] == "column"
    ]
    groups: dict[tuple, list[int]] = {}
    for i in range(n):
        groups.setdefault(tuple(d[c][i] for c in res_cols), []).append(i)
    if not groups:
        groups[()] = []

    def resource_attrs(key: tuple):
        out, it = [], iter(key)
        for attr, v in spec.resource:
            if isinstance(v, tuple) and v[0] == "column":
                out.append((attr, next(it)))
            else:
                out.append((attr, v))
        return _attr_kvs(out)

    payload: dict = {}
    for key, rows in groups.items():
        gauges, summaries, spans = [], [], []
        for item in spec.data:
            if isinstance(item, OTelMetricGauge):
                pts = [
                    {
                        "timeUnixNano": int(d["time_"][i]) if "time_" in d else 0,
                        "asDouble": float(d[item.value_column][i]),
                        "attributes": _attr_kvs(
                            (a, d[c][i]) for a, c in item.attributes
                        ),
                    }
                    for i in rows
                ]
                gauges.append(
                    {
                        "name": item.name,
                        "unit": item.unit,
                        "description": item.description,
                        "gauge": {"dataPoints": pts},
                    }
                )
            elif isinstance(item, OTelMetricSummary):
                pts = [
                    {
                        "timeUnixNano": int(d["time_"][i]) if "time_" in d else 0,
                        "count": int(d[item.count_column][i]),
                        "quantileValues": [
                            {"quantile": q, "value": float(d[c][i])}
                            for q, c in item.quantile_columns
                        ],
                        "attributes": _attr_kvs(
                            (a, d[c][i]) for a, c in item.attributes
                        ),
                    }
                    for i in rows
                ]
                summaries.append(
                    {
                        "name": item.name,
                        "unit": item.unit,
                        "description": item.description,
                        "summary": {"dataPoints": pts},
                    }
                )
            elif isinstance(item, OTelSpan):
                for i in rows:
                    spans.append(
                        {
                            "name": (
                                str(d[item.name][i])
                                if item.name_is_column
                                else item.name
                            ),
                            "startTimeUnixNano": int(d[item.start_time_column][i]),
                            "endTimeUnixNano": int(d[item.end_time_column][i]),
                            "attributes": _attr_kvs(
                                (a, d[c][i]) for a, c in item.attributes
                            ),
                        }
                    )
        metrics = gauges + summaries
        if metrics:
            payload.setdefault("resourceMetrics", []).append(
                {
                    "resource": {"attributes": resource_attrs(key)},
                    "scopeMetrics": [{"metrics": metrics}],
                }
            )
        if spans:
            payload.setdefault("resourceSpans", []).append(
                {
                    "resource": {"attributes": resource_attrs(key)},
                    "scopeSpans": [{"spans": spans}],
                }
            )
    return payload
