"""Stream & result primitives shared by the engine's execution modules.

A ``_Stream`` is the engine's unit of deferred work: a source (tables or
a host batch) plus the chain of fragment-fusable ops accumulated so far.
This module also owns the host-batch assembly helpers every executor
path (engine, joins, bridge merge, streaming) shares.

Reference parity: the exec-side RowBatch/Table plumbing around Carnot's
ExecNode chain (``src/carnot/exec/exec_node.h``) — here a chain becomes
one fused XLA fragment instead of a node-per-op push loop.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..types.batch import HostBatch
from ..types.dtypes import DataType, host_dtypes
from ..types.relation import Relation
from ..types.strings import StringDictionary
from .plan import AggOp, MemorySourceOp


class QueryError(Exception):
    pass


class QueryCancelled(QueryError):
    """Raised mid-stream when a query's cancel event fires (the
    ExecState::keep_running / exec_graph abort path,
    ``src/carnot/exec/exec_state.h``)."""


@dataclass
class _Stream:
    relation: Relation
    dicts: dict
    chain: list
    source: object  # list[Table] | Table | HostBatch
    source_op: Optional[MemorySourceOp] = None
    # Query-constant side-input arrays (numpy, keyed by reserved names)
    # passed to the fragment program alongside each window — the build
    # tables of fused lookup joins ride here, staged once per query.
    side: dict = field(default_factory=dict)

    def extend(self, op):
        return _Stream(
            self.relation, self.dicts, self.chain + [op], self.source,
            self.source_op, dict(self.side),
        )


def _chain_out_relation(stream: "_Stream", registry):
    """(relation, dicts) after a stream's pre-stage chain, or None if the
    chain does not bind (the caller falls back to the generic path)."""
    from .fragment import _bind_pre_stage

    try:
        _, rel, dicts = _bind_pre_stage(
            list(stream.chain), stream.relation, dict(stream.dicts), registry
        )
    except Exception:
        return None
    return rel, dicts


def _stream_col_stats(stream: "_Stream"):
    """Merged per-column (min, max) bounds across a stream's source
    tablets (None when the source is not table-backed or any tablet
    lacks stats for a column)."""
    src = stream.source
    if not isinstance(src, list) or not src:
        return None
    merged: dict | None = None
    for t in src:
        ts = getattr(t, "col_stats", None)
        if ts is None:
            return None
        if not ts:
            continue  # empty tablet (or no int columns): contributes no rows
        if merged is None:
            merged = dict(ts)
        else:
            merged = {
                c: (min(merged[c][0], ts[c][0]), max(merged[c][1], ts[c][1]))
                for c in merged.keys() & ts.keys()
            }
    return merged or None


def _col(name):
    from .plan import ColumnRef

    return ColumnRef(name)


def _double_agg_groups(stream: "_Stream") -> "_Stream":
    """Return the stream with its AggOp's max_groups doubled (rebucket)."""
    import dataclasses

    from ..config import get_flag

    limit = get_flag("max_groups_limit")
    chain = []
    doubled = False
    for op in stream.chain:
        if isinstance(op, AggOp) and not doubled:
            g2 = op.max_groups * 2
            if g2 > limit:
                raise QueryError(
                    f"group-by overflow at max_groups={op.max_groups}; "
                    f"rebucketing past the {limit} cap refused "
                    "(PIXIE_TPU_MAX_GROUPS_LIMIT)"
                )
            chain.append(dataclasses.replace(op, max_groups=g2))
            doubled = True
        else:
            chain.append(op)
    if not doubled:
        raise AssertionError("no AggOp in overflowing chain")
    return _Stream(
        stream.relation, stream.dicts, chain, stream.source,
        stream.source_op, dict(stream.side),  # keep lookup-join side tables
    )


def _window_shapes(cols) -> tuple:
    """Shape/dtype signature of a staged window (scan batching requires
    identical signatures so the stacked treedef stays one program).
    Side inputs are query-constant and never affect batchability."""
    return tuple(
        (c, tuple((p.shape, str(p.dtype)) for p in planes))
        for c, planes in sorted(cols.items())
        if c != "__side__"
    )


def _timed(stats, stage: str, rows: int = 0, nbytes: int = 0):
    """Stage timer context (no-op without stats) — keeps the analyze and
    plain execution paths one code path."""
    if stats is None:
        import contextlib

        return contextlib.nullcontext()
    return stats.timed(stage, rows, nbytes)


def _block_if(stats, x) -> None:
    """block_until_ready under analyze only (attribution needs sync).

    The always-on trace spine passes stats with ``sync=False``: stage
    timestamps still land, but the device is never fenced — pipeline
    overlap survives (the ISSUE-3 no-forced-sync contract)."""
    if stats is not None and getattr(stats, "sync", True):
        import jax

        jax.block_until_ready(x)


# -- host-batch assembly ------------------------------------------------------
def _to_host_batch(meta_list, cols, valid) -> HostBatch:
    idx = np.nonzero(valid)[0]
    out_cols: dict = {}
    dicts: dict = {}
    rel_items = []
    for m in meta_list:
        if m.struct_fields is not None:
            planes = np.asarray(cols[m.name][0])[idx]  # [rows, k] floats
            d = StringDictionary()
            ids = np.fromiter(
                (
                    d.get_or_add(
                        json.dumps(
                            {f: round(float(v), 6) for f, v in zip(m.struct_fields, row)}
                        )
                    )
                    for row in planes
                ),
                dtype=np.int32,
                count=len(planes),
            )
            out_cols[m.name] = (ids,)
            dicts[m.name] = d
            rel_items.append((m.name, DataType.STRING))
            continue
        hdts = host_dtypes(m.dtype)
        out_cols[m.name] = tuple(
            np.asarray(p)[idx].astype(h) for p, h in zip(cols[m.name], hdts)
        )
        if m.dict is not None:
            dicts[m.name] = m.dict
        rel_items.append((m.name, m.dtype))
    return HostBatch(
        relation=Relation(rel_items), cols=out_cols, length=len(idx), dicts=dicts
    )


def _empty_host_batch(relation, dicts=None) -> HostBatch:
    cols = {
        n: tuple(np.empty(0, dtype=h) for h in host_dtypes(t))
        for n, t in relation.items()
    }
    return HostBatch(relation=relation, cols=cols, length=0, dicts=dict(dicts or {}))


def _concat_host(pieces, relation) -> HostBatch:
    nonempty = [p for p in pieces if p.length > 0]
    if not nonempty:
        dicts = pieces[0].dicts if pieces else {}
        return _empty_host_batch(relation, dicts)
    pieces = nonempty
    first = pieces[0]
    if len(pieces) == 1:
        return first
    cols = {
        n: tuple(
            np.concatenate([p.cols[n][i] for p in pieces])
            for i in range(len(first.cols[n]))
        )
        for n in first.relation.column_names
    }
    return HostBatch(
        relation=first.relation,
        cols=cols,
        length=sum(p.length for p in pieces),
        dicts=first.dicts,
    )


def _apply_limit(hb: HostBatch, limit) -> HostBatch:
    if limit is None or hb.length <= limit:
        return hb
    return HostBatch(
        relation=hb.relation,
        cols={n: tuple(p[:limit] for p in ps) for n, ps in hb.cols.items()},
        length=limit,
        dicts=hb.dicts,
    )
