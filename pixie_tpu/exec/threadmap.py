"""Cross-thread attribution registry for the continuous profiler.

The perf profiler (``ingest/profiler.py``) samples every live thread's
stack at 100Hz via ``sys._current_frames``. By itself a folded stack is
anonymous — it says *what* code is running but not *for whom*. This
module is the "whom": a process-wide map of thread-id → attribution
entry, updated at the points where work changes identity:

- ``Engine._execute_plan_scoped`` binds the executing ``QueryTrace``
  (phase ``host``) around plan execution;
- ``QueryBroker.execute_script`` binds the distributed trace around
  planning + dispatch;
- ``tracectx.bound`` registers the ambient context envelope, so bus
  handler threads carry at least the trace id;
- ``WindowPipeline`` rebinds the creator's entry on its prefetch thread
  (phase ``stage``) and brackets the consumer's waits (phase ``stall``);
- ``TrackedProgram.__call__`` brackets device dispatch
  (phase ``device_dispatch``).

Concurrency contract: entries are IMMUTABLE dicts and every mutation
replaces the whole value (``_entries[tid] = new_dict``), so the sampler
can read ``_entries.get(tid)`` with **no lock** — a single GIL-atomic
dict lookup per sampled thread. That matters: the sampler runs at 100Hz
and must never synchronize (see ``PXLINT_HOT_REGIONS``); attribution
reads race benignly (a sample lands on the old or the new entry, never
a torn one).

Binding is token-based (save/restore, like ``contextvars``): nested
binds compose, and exceptional exits restore the outer entry.
"""

from __future__ import annotations

import contextlib
import threading

#: thread-id -> immutable attribution entry.  Entry keys:
#:   "trace"  QueryTrace (live reference — qid/tenant stamped after
#:            begin_query are picked up at sample time automatically)
#:   "ctx"    trace-context envelope dict (bus handlers)
#:   "phase"  "" | "host" | "device_dispatch" | "stall" | "stage"
_entries: dict[int, dict] = {}


class _Token:
    """Save/restore handle returned by :func:`bind` / :func:`set_phase`."""

    __slots__ = ("tid", "prev")

    def __init__(self, tid: int, prev):
        self.tid = tid
        self.prev = prev


def bind(trace=None, ctx=None, phase: str = "", base: dict | None = None):
    """Register the calling thread's attribution; returns a token for
    :func:`unbind`. ``base`` seeds the entry from another thread's entry
    (pipeline prefetch threads inherit their creator's identity); the
    explicit ``trace``/``ctx``/``phase`` arguments override it."""
    tid = threading.get_ident()
    prev = _entries.get(tid)
    entry = dict(base) if base else {}
    if trace is not None:
        entry["trace"] = trace
    if ctx is not None:
        entry["ctx"] = ctx
    if phase or "phase" not in entry:
        entry["phase"] = phase
    _entries[tid] = entry
    return _Token(tid, prev)


def unbind(token) -> None:
    """Restore the entry that was live before the matching :func:`bind`."""
    if token is None:
        return
    if token.prev is None:
        _entries.pop(token.tid, None)
    else:
        _entries[token.tid] = token.prev


def set_phase(phase: str):
    """Replace the calling thread's phase; returns a token for
    :func:`restore`, or ``None`` when the thread has no entry (one dict
    get on unattributed threads — the hot-path fast exit)."""
    tid = threading.get_ident()
    prev = _entries.get(tid)
    if prev is None:
        return None
    _entries[tid] = {**prev, "phase": phase}
    return _Token(tid, prev)


def restore(token) -> None:
    """Undo a :func:`set_phase` (no-op on the ``None`` fast-exit token)."""
    if token is not None:
        _entries[token.tid] = token.prev


@contextlib.contextmanager
def attributed(trace=None, ctx=None, phase: str = "", base: dict | None = None):
    """Context-manager form of :func:`bind`/:func:`unbind`."""
    token = bind(trace=trace, ctx=ctx, phase=phase, base=base)
    try:
        yield
    finally:
        unbind(token)


def current_entry() -> dict | None:
    """The calling thread's live entry (for cross-thread inheritance)."""
    return _entries.get(threading.get_ident())


def lookup(tid: int) -> dict | None:
    """Sampler-side read: the entry for ``tid``, lock-free."""
    return _entries.get(tid)


def attribution(entry) -> tuple[str, str, str, str]:
    """Resolve an entry to ``(qid, script_hash, tenant, phase)`` strings.

    Reads qid/tenant off the live ``QueryTrace`` reference so values
    stamped after ``begin_query`` (the broker assigns qid + tenant a few
    lines later) are visible to samples taken any time after."""
    if not entry:
        return ("", "", "", "")
    trace = entry.get("trace")
    qid = script_hash = tenant = ""
    if trace is not None:
        qid = getattr(trace, "qid", "") or ""
        script_hash = getattr(trace, "script_hash", "") or ""
        tenant = getattr(trace, "tenant", "") or ""
    if not qid:
        ctx = entry.get("ctx")
        if isinstance(ctx, dict):
            qid = ctx.get("trace_id", "") or ""
    return (qid, script_hash, tenant, entry.get("phase", "") or "")


def clear() -> None:
    """Drop all entries (test isolation)."""
    _entries.clear()
