from .registry import Registry, default_registry
from .udf import Executor, ScalarUDFDef, SignatureError, UDADef, apply_cast, cast_cost, resolve_overload

__all__ = [
    "Registry",
    "default_registry",
    "Executor",
    "ScalarUDFDef",
    "UDADef",
    "SignatureError",
    "apply_cast",
    "cast_cost",
    "resolve_overload",
]
