"""SQL query normalization UDFs (dictionary-side).

Reference parity: ``src/carnot/funcs/builtins/sql_ops.cc`` +
``sql_parsing/`` — NormalizeMySQLUDF / NormalizePostgresSQLUDF replace
literals with placeholders so queries group by shape. The reference uses a
real SQL tokenizer; this is a tokenizer-lite regex pipeline (string
literals, numeric literals, IN-lists) — adequate for grouping, and it runs
once per distinct query string in the dictionary.
"""

from __future__ import annotations

import re

from ..udf import STRING, Executor

_STRING_LIT = re.compile(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"")
_NUM_LIT = re.compile(r"\b\d+(?:\.\d+)?\b")
_IN_LIST = re.compile(r"(?i)(\bIN\s*\()\s*(?:\?\s*,\s*)*\?\s*(\))")
_WS = re.compile(r"\s+")


def normalize_sql(q: str) -> str:
    q = _STRING_LIT.sub("?", q)
    q = _NUM_LIT.sub("?", q)
    q = _IN_LIST.sub(r"\1?\2", q)  # collapse IN (?, ?, ?) -> IN (?)
    return _WS.sub(" ", q).strip()


def register(reg):
    for name in ("normalize_mysql", "normalize_pgsql"):
        reg.scalar(
            name, (STRING,), STRING, normalize_sql,
            executor=Executor.HOST_DICT, dict_arg=0,
            doc="Replace SQL literals with '?' placeholders so queries group by shape.",
        )
