"""Math scalar UDFs and numeric UDAs.

Reference parity: ``src/carnot/funcs/builtins/math_ops.h:34-744`` — binary
arith (add/subtract/multiply/divide/modulo), comparisons
(equal/notEqual/lessThan/greaterThan/...), logical ops, unary
(abs/ceil/floor/round/sqrt/exp/ln/log2/log10/negate/invert), ``bin``, time
conversions, and the UDAs MeanUDA(:584)/SumUDA(:630)/MaxUDA(:661)/
MinUDA(:703)/CountUDA(:744).

TPU-first: scalars are whole-column jnp expressions XLA fuses; UDAs are
segment reductions into [G] carries with associative merges.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.scan import blocked_cumsum
from ..udf import BOOLEAN, FLOAT64, INT64, STRING, TIME64NS


def _num(t):  # numeric overload families
    return [(INT64, jnp.int64), (FLOAT64, jnp.float64)][t]


_I64_MAX = jnp.iinfo(jnp.int64).max
_I64_MIN = jnp.iinfo(jnp.int64).min


def register(reg):
    # -- binary arithmetic ---------------------------------------------------
    for dt in (INT64, FLOAT64):
        reg.scalar("add", (dt, dt), dt, lambda a, b: a + b)
        reg.scalar("subtract", (dt, dt), dt, lambda a, b: a - b)
        reg.scalar("multiply", (dt, dt), dt, lambda a, b: a * b)
    # Time arithmetic keeps TIME64NS (duration treated as INT64 input).
    reg.scalar("add", (TIME64NS, TIME64NS), TIME64NS, lambda a, b: a + b)
    reg.scalar("subtract", (TIME64NS, TIME64NS), TIME64NS, lambda a, b: a - b)
    # divide always yields float (Carnot: DivideUDF -> FLOAT64).
    reg.scalar(
        "divide",
        (FLOAT64, FLOAT64),
        FLOAT64,
        lambda a, b: a / b,
        doc="Arithmetic division; inf/nan on zero divisors.",
    )
    reg.scalar("modulo", (INT64, INT64), INT64, lambda a, b: jnp.where(b != 0, a % jnp.where(b == 0, 1, b), 0))
    reg.scalar("pow", (FLOAT64, FLOAT64), FLOAT64, lambda a, b: jnp.power(a, b))

    # -- comparisons ---------------------------------------------------------
    for dt in (INT64, FLOAT64, TIME64NS, BOOLEAN, STRING):
        reg.scalar("equal", (dt, dt), BOOLEAN, lambda a, b: a == b)
        reg.scalar("notEqual", (dt, dt), BOOLEAN, lambda a, b: a != b)
    for dt in (INT64, FLOAT64, TIME64NS):
        reg.scalar("lessThan", (dt, dt), BOOLEAN, lambda a, b: a < b)
        reg.scalar("lessThanEqual", (dt, dt), BOOLEAN, lambda a, b: a <= b)
        reg.scalar("greaterThan", (dt, dt), BOOLEAN, lambda a, b: a > b)
        reg.scalar("greaterThanEqual", (dt, dt), BOOLEAN, lambda a, b: a >= b)
    # Tolerance sized for f32 planes (one ULP at magnitude 1 is ~1.2e-7).
    reg.scalar("approxEqual", (FLOAT64, FLOAT64), BOOLEAN, lambda a, b: jnp.abs(a - b) < 1e-4)

    # -- logical -------------------------------------------------------------
    reg.scalar("logicalAnd", (BOOLEAN, BOOLEAN), BOOLEAN, lambda a, b: a & b)
    reg.scalar("logicalOr", (BOOLEAN, BOOLEAN), BOOLEAN, lambda a, b: a | b)
    reg.scalar("logicalNot", (BOOLEAN,), BOOLEAN, lambda a: ~a)
    reg.scalar("invert", (BOOLEAN,), BOOLEAN, lambda a: ~a)

    # -- unary math ----------------------------------------------------------
    for dt in (INT64, FLOAT64):
        reg.scalar("abs", (dt,), dt, jnp.abs)
        reg.scalar("negate", (dt,), dt, jnp.negative)
    reg.scalar("ceil", (FLOAT64,), FLOAT64, jnp.ceil)
    reg.scalar("floor", (FLOAT64,), FLOAT64, jnp.floor)
    reg.scalar("round", (FLOAT64,), FLOAT64, jnp.round)
    reg.scalar("sqrt", (FLOAT64,), FLOAT64, jnp.sqrt)
    reg.scalar("exp", (FLOAT64,), FLOAT64, jnp.exp)
    reg.scalar("ln", (FLOAT64,), FLOAT64, jnp.log)
    reg.scalar("log2", (FLOAT64,), FLOAT64, jnp.log2)
    reg.scalar("log10", (FLOAT64,), FLOAT64, jnp.log10)
    reg.scalar("log", (FLOAT64, FLOAT64), FLOAT64, lambda b, x: jnp.log(x) / jnp.log(b))

    # -- bin + time conversions ----------------------------------------------
    reg.scalar(
        "bin",
        (INT64, INT64),
        INT64,
        lambda v, s: v - v % jnp.where(s == 0, 1, s),
        doc="Round v down to the nearest multiple of s (px.bin).",
    )
    reg.scalar("bin", (TIME64NS, INT64), TIME64NS, lambda v, s: v - v % jnp.where(s == 0, 1, s))
    reg.scalar("time_to_int64", (TIME64NS,), INT64, lambda t: t)
    reg.scalar("int64_to_time", (INT64,), TIME64NS, lambda t: t)

    # -- UDAs ----------------------------------------------------------------
    # Float carries are f64 even though column planes are f32: [G]-sized,
    # sort-free accumulators keep billions-row sums exact without tripping
    # the f64-sort compile blowup (see types/dtypes.py).
    # 64-bit INTEGER segment reductions avoid XLA scatter: a 64-bit
    # scatter-add on a 2M-row window costs ~125ms real on the TPU (vs
    # ~15ms for i32) — the sort-based form (argsort group ids once,
    # cumsum, boundary gathers) is ~2x cheaper per agg, and the shared
    # argsort/searchsorted CSE away across the aggs of one fused window
    # program. 32-bit-and-smaller dtypes keep the plain scatter (cheaper
    # than a sort), and so do floats (prefix-difference sums cancel).

    def _sorted_segments() -> bool:
        """TPU only: XLA's TPU sort is fast (~10ms/2M) while 64-bit
        scatters cost ~125ms; on CPU the trade inverts hard (argsort 2M
        ~660ms vs scatter-add ~8ms). Trace-time check — executables are
        per-backend."""
        return jax.default_backend() == "tpu"

    def _seg_order(gids, mask, g):
        """(order, sorted_gids, ends): rows sorted by group id, invalid
        rows last (slot g); ends[k] = one past segment k's last row.
        Pure function of (gids, mask) — duplicated calls CSE under jit."""
        gi = jnp.where(mask, gids, g).astype(jnp.int32)
        order = jnp.argsort(gi).astype(jnp.int32)
        sg = gi[order]
        ends = jnp.searchsorted(
            sg, jnp.arange(g, dtype=jnp.int32), side="right"
        ).astype(jnp.int32)
        return order, sg, ends

    def _seg_sum(carry, gids, mask, v):
        g = carry.shape[0]
        v = v.astype(carry.dtype)
        # Floats keep the scatter: the cumsum-diff trick subtracts window-
        # wide prefixes, which catastrophically cancels when a huge-sum
        # group precedes a tiny one. Int64 is safe (wraparound differences
        # are exact).
        if (
            np.dtype(carry.dtype).itemsize <= 4
            or not jnp.issubdtype(carry.dtype, jnp.integer)
            or not _sorted_segments()
        ):
            contrib = jnp.where(mask, v, jnp.zeros((), v.dtype))
            return carry + jax.ops.segment_sum(
                contrib, jnp.where(mask, gids, g), num_segments=g + 1
            )[:-1]
        order, _sg, ends = _seg_order(gids, mask, g)
        contrib = jnp.where(mask, v, jnp.zeros((), v.dtype))[order]
        # blocked_cumsum: XLA:TPU cannot compile a flat multi-million-row
        # i64 cumsum (scoped-vmem overflow in the u32-pair reduce-window
        # lowering); the two-level blocked scan is bit-identical.
        cs0 = jnp.concatenate(
            [jnp.zeros(1, contrib.dtype), blocked_cumsum(contrib)]
        )
        tot = cs0[ends]  # cumulative sum up to each segment's end
        return carry + tot - jnp.concatenate(
            [jnp.zeros(1, tot.dtype), tot[:-1]]
        )

    def _seg_count(carry, gids, mask):
        """Row count per group: boundary diffs on the shared sorted ids
        (TPU), or an i32 scatter (CPU — sorts are slow there). Window
        counts always fit i32 (window size < 2^31)."""
        g = carry.shape[0]
        if not _sorted_segments():
            cnt = jax.ops.segment_sum(
                mask.astype(jnp.int32), jnp.where(mask, gids, g),
                num_segments=g + 1,
            )[:-1]
            return carry + cnt.astype(carry.dtype)
        _order, _sg, ends = _seg_order(gids, mask, g)
        cnt = ends - jnp.concatenate([jnp.zeros(1, ends.dtype), ends[:-1]])
        return carry + cnt.astype(carry.dtype)

    for dt, zdtype in ((INT64, jnp.int64), (FLOAT64, jnp.float64)):
        reg.uda(
            "sum",
            (dt,),
            dt,
            init=lambda g, _z=zdtype: jnp.zeros(g, dtype=_z),
            update=lambda c, gids, mask, v: _seg_sum(c, gids, mask, v),
            merge=lambda a, b: a + b,
            finalize=lambda c: c,
            doc="Sum of the group.",
        )
    reg.uda(
        "sum",
        (BOOLEAN,),
        INT64,
        init=lambda g: jnp.zeros(g, dtype=jnp.int64),
        update=lambda c, gids, mask, v: _seg_sum(c, gids, mask, v.astype(jnp.int64)),
        merge=lambda a, b: a + b,
        finalize=lambda c: c,
    )

    reg.uda(
        "count",
        (FLOAT64,),
        INT64,
        init=lambda g: jnp.zeros(g, dtype=jnp.int64),
        update=lambda c, gids, mask, v: _seg_count(c, gids, mask),
        merge=lambda a, b: a + b,
        finalize=lambda c: c,
        doc="Number of rows in the group.",
    )

    reg.uda(
        "mean",
        (FLOAT64,),
        FLOAT64,
        init=lambda g: (jnp.zeros(g, dtype=jnp.float64), jnp.zeros(g, dtype=jnp.float64)),
        update=lambda c, gids, mask, v: (
            _seg_sum(c[0], gids, mask, v),
            _seg_count(c[1], gids, mask),
        ),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda c: jnp.where(c[1] > 0, c[0] / jnp.maximum(c[1], 1.0), jnp.nan),
        doc="Arithmetic mean of the group (sum/count carry; merges exactly).",
    )
    # Direct integer/bool overloads: EXACT i64 sums (the FLOAT64 path
    # rides f32 device planes) via the shared sort-based reduction — no
    # 64-bit-float scatter (~125ms per 2M-row window on the chip).
    reg.uda(
        "mean",
        (INT64,),
        FLOAT64,
        init=lambda g: (jnp.zeros(g, dtype=jnp.int64), jnp.zeros(g, dtype=jnp.int64)),
        update=lambda c, gids, mask, v: (
            _seg_sum(c[0], gids, mask, v),
            _seg_count(c[1], gids, mask),
        ),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda c: jnp.where(
            c[1] > 0,
            c[0].astype(jnp.float64) / jnp.maximum(c[1], 1).astype(jnp.float64),
            jnp.nan,
        ),
        doc="Arithmetic mean (exact int64 sum/count carry).",
    )
    reg.uda(
        "mean",
        (BOOLEAN,),
        FLOAT64,
        init=lambda g: (jnp.zeros(g, dtype=jnp.int64), jnp.zeros(g, dtype=jnp.int64)),
        update=lambda c, gids, mask, v: (
            _seg_sum(c[0], gids, mask, v.astype(jnp.int64)),
            _seg_count(c[1], gids, mask),
        ),
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda c: jnp.where(
            c[1] > 0,
            c[0].astype(jnp.float64) / jnp.maximum(c[1], 1).astype(jnp.float64),
            jnp.nan,
        ),
        doc="Fraction of true rows (exact integer carry).",
    )

    def _seg_extreme64(carry, gids, mask, v, neutral, is_max):
        """64-bit int min/max without a 64-bit scatter: two-key sort
        (group id primary, value secondary) makes each segment's extreme
        its first/last element; the group-id sort CSEs with the other
        aggs' _seg_order."""
        g = carry.shape[0]
        n = v.shape[0]
        gi = jnp.where(mask, gids, g).astype(jnp.int32)
        ov = jnp.argsort(v, stable=True).astype(jnp.int32)
        order = ov[jnp.argsort(gi[ov], stable=True).astype(jnp.int32)]
        sv = v[order]
        ends = jnp.searchsorted(
            gi[order], jnp.arange(g, dtype=jnp.int32), side="right"
        ).astype(jnp.int32)
        starts = jnp.concatenate([jnp.zeros(1, ends.dtype), ends[:-1]])
        if is_max:
            val = sv[jnp.clip(ends - 1, 0, max(n - 1, 0))]
        else:
            val = sv[jnp.clip(starts, 0, max(n - 1, 0))]
        upd = jnp.where(ends > starts, val, jnp.full((), neutral, v.dtype))
        return jnp.maximum(carry, upd) if is_max else jnp.minimum(carry, upd)

    def _seg_min(carry, gids, mask, v, neutral):
        g = carry.shape[0]
        if (np.dtype(v.dtype).itemsize > 4
                and jnp.issubdtype(v.dtype, jnp.integer)
                and _sorted_segments()):
            return _seg_extreme64(carry, gids, mask, v, neutral, is_max=False)
        contrib = jnp.where(mask, v, jnp.full((), neutral, v.dtype))
        upd = jax.ops.segment_min(contrib, jnp.where(mask, gids, g), num_segments=g + 1)[:-1]
        return jnp.minimum(carry, upd)

    def _seg_max(carry, gids, mask, v, neutral):
        g = carry.shape[0]
        if (np.dtype(v.dtype).itemsize > 4
                and jnp.issubdtype(v.dtype, jnp.integer)
                and _sorted_segments()):
            return _seg_extreme64(carry, gids, mask, v, neutral, is_max=True)
        contrib = jnp.where(mask, v, jnp.full((), neutral, v.dtype))
        upd = jax.ops.segment_max(contrib, jnp.where(mask, gids, g), num_segments=g + 1)[:-1]
        return jnp.maximum(carry, upd)

    for dt, zd, lo, hi in (
        (INT64, jnp.int64, _I64_MIN, _I64_MAX),
        (FLOAT64, jnp.float64, -jnp.inf, jnp.inf),
        (TIME64NS, jnp.int64, _I64_MIN, _I64_MAX),
    ):
        reg.uda(
            "min",
            (dt,),
            dt,
            init=lambda g, _z=zd, _hi=hi: jnp.full(g, _hi, dtype=_z),
            update=lambda c, gids, mask, v, _hi=hi: _seg_min(c, gids, mask, v, _hi),
            merge=jnp.minimum,
            finalize=lambda c: c,
        )
        reg.uda(
            "max",
            (dt,),
            dt,
            init=lambda g, _z=zd, _lo=lo: jnp.full(g, _lo, dtype=_z),
            update=lambda c, gids, mask, v, _lo=lo: _seg_max(c, gids, mask, v, _lo),
            merge=jnp.maximum,
            finalize=lambda c: c,
        )
