"""Regex scalar UDFs (dictionary-side).

Reference parity: ``src/carnot/funcs/builtins/regex_ops.cc`` — RegexMatchUDF
("regex_match", pattern compiled once in Init) and RegexReplaceUDF
("replace"). Patterns compile once per plan binding and run over distinct
dictionary strings only.
"""

from __future__ import annotations

import functools
import re

from ..udf import BOOLEAN, STRING, Executor


@functools.lru_cache(maxsize=256)
def _compile(pattern: str):
    try:
        return re.compile(pattern)
    except re.error:
        return None


def _match(pattern: str, s: str) -> bool:
    rx = _compile(pattern)
    return bool(rx.fullmatch(s)) if rx else False


def _replace(pattern: str, s: str, sub: str) -> str:
    rx = _compile(pattern)
    return rx.sub(sub, s) if rx else s


def register(reg):
    reg.scalar(
        "regex_match", (STRING, STRING), BOOLEAN, _match,
        executor=Executor.HOST_DICT, dict_arg=1,
        doc="Full-string regex match (RE2 semantics approximated by re).",
    )
    reg.scalar(
        "replace", (STRING, STRING, STRING), STRING, _replace,
        executor=Executor.HOST_DICT, dict_arg=1,
        doc="Replace all regex matches in s with sub.",
    )
