"""PII redaction UDFs (dictionary-side).

Reference parity: ``src/carnot/funcs/builtins/pii_ops.{h,cc}`` —
``RedactPIIUDF`` runs a tagger pipeline (regex taggers for IPv4/IPv6,
emails, MAC addresses, IMEI/IMEISV, credit-card numbers with a Luhn
check) and substitutes ``<REDACTED_$TYPE>``. Best-effort by the
reference's own documentation — not a privacy guarantee. Tagger
precedence matches the reference: the credit-card tagger runs before
the IMEI tagger, so a Luhn-valid 15-digit IMEI redacts under the
``<REDACTED_CC_NUMBER>`` label (still redacted, differently named).

Runs once per distinct string in the column dictionary (HOST_DICT), so
redacting a billion-row column costs O(vocabulary).
"""

from __future__ import annotations

import ipaddress
import re

from ..udf import STRING, Executor

_IPV4 = re.compile(
    r"\b(?:(?:25[0-5]|2[0-4]\d|1\d\d|[1-9]?\d)\.){3}"
    r"(?:25[0-5]|2[0-4]\d|1\d\d|[1-9]?\d)\b"
)
# Candidate colon-hex tokens; real IPv6-ness (incl. '::' compression) is
# decided by ipaddress parsing, not the regex.
_IPV6_CAND = re.compile(
    r"(?<![0-9A-Fa-f:.])([0-9A-Fa-f]*:[0-9A-Fa-f:.]+)(?![0-9A-Fa-f:.])"
)
_EMAIL = re.compile(
    r"\b[A-Za-z0-9._%+-]+@[A-Za-z0-9.-]+\.[A-Za-z]{2,}\b"
)
_MAC = re.compile(
    r"\b(?:[0-9A-Fa-f]{2}[:-]){5}[0-9A-Fa-f]{2}\b"
)
# 13-19 digits with optional space/dash separators (candidate CCs; the
# Luhn check below culls false positives, as the reference does).
_CC = re.compile(r"\b(?:\d[ -]?){12,18}\d\b")
_IMEI = re.compile(r"\b\d{2}[- ]?\d{6}[- ]?\d{6}[- ]?\d(?:\d)?\b")


def _luhn_ok(digits: str) -> bool:
    total = 0
    for i, ch in enumerate(reversed(digits)):
        d = ord(ch) - 48
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        total += d
    return total % 10 == 0


def _redact_cc(m: re.Match) -> str:
    digits = re.sub(r"[ -]", "", m.group(0))
    if 13 <= len(digits) <= 19 and _luhn_ok(digits):
        return "<REDACTED_CC_NUMBER>"
    return m.group(0)


def _redact_imei(m: re.Match) -> str:
    # The CC tagger runs first and its 13-19-digit Luhn check subsumes
    # Luhn-valid IMEIs (reference tagger order does the same — both get
    # redacted, under the CC label). What reaches here is Luhn-failing:
    # the only safely taggable leftover is the separated 16-digit IMEISV
    # grouping, which carries no check digit.
    digits = re.sub(r"[- ]", "", m.group(0))
    if len(digits) == 16 and re.search(r"[- ]", m.group(0)):
        return "<REDACTED_IMEI>"
    return m.group(0)


def _redact_ipv6(m: re.Match) -> str:
    tok = m.group(0)
    if tok.count(":") < 2:
        return tok
    try:
        parsed = ipaddress.ip_address(tok.split("%", 1)[0])
    except ValueError:
        return tok
    return "<REDACTED_IPV6>" if parsed.version == 6 else tok


def redact_pii(s: str) -> str:
    # MAC before IPv6: 6-octet colon forms are valid colon-hex candidates.
    s = _EMAIL.sub("<REDACTED_EMAIL>", s)
    s = _MAC.sub("<REDACTED_MAC_ADDR>", s)
    s = _IPV4.sub("<REDACTED_IPV4>", s)
    s = _IPV6_CAND.sub(_redact_ipv6, s)
    s = _CC.sub(_redact_cc, s)
    s = _IMEI.sub(_redact_imei, s)
    return s


def register(reg):
    reg.scalar(
        "redact_pii_best_effort", (STRING,), STRING, redact_pii,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="Best-effort replacement of PII (emails, IPs, MAC addresses, "
            "credit cards, IMEIs) with <REDACTED_$TYPE> markers.",
    )
