"""Conditional scalar UDFs.

Reference parity: ``src/carnot/funcs/builtins/conditionals.cc`` —
SelectUDF("select", cond, then, else). Device-side jnp.where; string
branches operate on ids (the plan binder aligns both branches to one
dictionary before tracing).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..udf import BOOLEAN, FLOAT64, INT64, STRING, TIME64NS


def register(reg):
    for dt in (INT64, FLOAT64, STRING, BOOLEAN, TIME64NS):
        reg.scalar(
            "select", (BOOLEAN, dt, dt), dt,
            lambda c, a, b: jnp.where(c, a, b),
            doc="Elementwise: a where cond else b.",
        )
