"""Sketch UDAs: t-digest quantiles (and HLL count-distinct).

Reference parity: ``src/carnot/funcs/builtins/math_sketches.h:34``
(QuantilesUDA over tdigest; finalize emits JSON {p01,p10,p25,p50,p75,p90,p99}).
Here the digest is the batched sorted-binning implementation in
``pixie_tpu.ops.tdigest``; finalize yields [G, 7] floats that the host
materializes to JSON (or the planner plucks directly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops import tdigest
from ...ops.hll import hll_estimate, hll_init, hll_update
from ..udf import FLOAT64, INT64, STRING

QUANTILE_FIELDS = ("p01", "p10", "p25", "p50", "p75", "p90", "p99")
QUANTILE_POINTS = (0.01, 0.10, 0.25, 0.50, 0.75, 0.90, 0.99)


def register(reg):
    reg.uda(
        "quantiles",
        (FLOAT64,),
        STRING,
        init=lambda g: tdigest.digest_init(g),
        update=lambda c, gids, mask, v: tdigest.digest_update(c, gids, mask, v),
        merge=tdigest.digest_merge,
        finalize=lambda c: tdigest.digest_quantile(c, QUANTILE_POINTS),
        struct_fields=QUANTILE_FIELDS,
        doc="Approximate quantiles of the group via a mergeable t-digest.",
        semantic_type=1000,  # SemanticType.ST_QUANTILES (types.proto:84)
    )

    # Direct single-quantile UDAs (not in the reference's registry, but the
    # planner fuses pluck_float64(quantiles(x), 'p99') into these so the
    # hot path never materializes JSON).
    for field, point in zip(QUANTILE_FIELDS, QUANTILE_POINTS):
        reg.uda(
            f"_quantile_{field}",
            (FLOAT64,),
            FLOAT64,
            init=lambda g: tdigest.digest_init(g),
            update=lambda c, gids, mask, v: tdigest.digest_update(c, gids, mask, v),
            merge=tdigest.digest_merge,
            finalize=lambda c, _p=point: tdigest.digest_quantile(c, (_p,))[:, 0],
            doc=f"Approximate {field} of the group via t-digest.",
        )

    for dt in (INT64, STRING):
        reg.uda(
            "count_distinct",
            (dt,),
            INT64,
            init=lambda g: hll_init(g),
            update=lambda c, gids, mask, v: hll_update(c, gids, mask, v),
            merge=jnp.maximum,
            finalize=hll_estimate,
            doc="Approximate distinct count via a mergeable HyperLogLog sketch.",
        )
