"""Cluster/engine introspection UDTFs.

Reference parity: ``src/vizier/funcs/md_udtfs/md_udtfs_impl.h`` —
``GetTables`` (:105), ``GetTableSchemas`` (:169), ``GetUDTFList`` (:337),
``GetUDFList`` (:429), ``GetUDAList`` (:490), debug table info (:554).
These run against the executing engine (ctx); the service-level
``GetAgentStatus`` (:258) is registered by the agent runtime with a bus
connection bound in (``pixie_tpu.services.vizier_funcs``).
"""

from __future__ import annotations

import json

from ...types.dtypes import DataType
from ..udtf import UDTFExecutor

S = DataType.STRING
I = DataType.INT64


def _get_tables(engine):
    names, rows, bts = [], [], []
    for name, t in sorted(engine.tables.items()):
        if t is None:
            continue
        st = t.stats()
        names.append(name)
        rows.append(st.num_rows)
        bts.append(st.bytes)
    return {"table_name": names, "num_rows": rows, "size_bytes": bts}


def _get_table_schemas(engine):
    tables, cols, types = [], [], []
    for name, t in sorted(engine.tables.items()):
        if t is None:
            continue
        for cname, dt in t.relation.items():
            tables.append(name)
            cols.append(cname)
            types.append(dt.name)
    return {"table_name": tables, "column_name": cols, "column_type": types}


def _get_udf_list(engine):
    names, sigs = [], []
    for n in engine.registry.scalar_names():
        for ov in engine.registry.scalar_overloads(n):
            names.append(n)
            sigs.append(
                json.dumps(
                    {
                        "args": [t.name for t in ov.arg_types],
                        "return": ov.return_type.name,
                        "executor": ov.executor.name,
                    }
                )
            )
    return {"name": names, "signature": sigs}


def _get_uda_list(engine):
    names, sigs = [], []
    for n in engine.registry.uda_names():
        for ov in engine.registry.uda_overloads(n):
            names.append(n)
            sigs.append(
                json.dumps(
                    {
                        "args": [t.name for t in ov.arg_types],
                        "return": ov.return_type.name,
                    }
                )
            )
    return {"name": names, "signature": sigs}


def _get_udtf_list(engine):
    names, execs, rels = [], [], []
    for n in engine.registry.udtf_names():
        d = engine.registry.get_udtf(n)
        names.append(n)
        execs.append(d.executor.name)
        rels.append(json.dumps([[c, t.name] for c, t in d.relation]))
    return {"name": names, "executor": execs, "relation": rels}


def _get_debug_table_info(engine):
    out = {
        k: []
        for k in (
            "table_name",
            "bytes",
            "hot_bytes",
            "cold_bytes",
            "hot_rows",
            "cold_rows",
            "cold_raw_bytes",
            "cold_demotions",
            "cold_evictions",
            "num_batches",
            "batches_expired",
            "compacted_batches",
            "min_time",
        )
    }
    for name, t in sorted(engine.tables.items()):
        if t is None:
            continue
        st = t.stats()
        out["table_name"].append(name)
        out["bytes"].append(st.bytes)
        out["hot_bytes"].append(st.hot_bytes)
        out["cold_bytes"].append(st.cold_bytes)
        out["hot_rows"].append(st.hot_rows)
        out["cold_rows"].append(st.cold_rows)
        out["cold_raw_bytes"].append(st.cold_raw_bytes)
        out["cold_demotions"].append(st.demotions)
        out["cold_evictions"].append(st.evictions)
        out["num_batches"].append(st.num_batches)
        out["batches_expired"].append(st.batches_expired)
        out["compacted_batches"].append(st.compacted_batches)
        out["min_time"].append(st.min_time)
    return out


def register_introspection(reg) -> None:
    reg.udtf(
        "GetTables",
        [("table_name", S), ("num_rows", I), ("size_bytes", I)],
        _get_tables,
        executor=UDTFExecutor.ALL_AGENTS,
        doc="Lists tables with row counts and byte sizes.",
    )
    reg.udtf(
        "GetTableSchemas",
        [("table_name", S), ("column_name", S), ("column_type", S)],
        _get_table_schemas,
        executor=UDTFExecutor.ALL_AGENTS,
        doc="Lists every column of every table.",
    )
    reg.udtf(
        "GetUDFList",
        [("name", S), ("signature", S)],
        _get_udf_list,
        doc="Lists registered scalar UDF overloads.",
    )
    reg.udtf(
        "GetUDAList",
        [("name", S), ("signature", S)],
        _get_uda_list,
        doc="Lists registered UDA overloads.",
    )
    reg.udtf(
        "GetUDTFList",
        [("name", S), ("executor", S), ("relation", S)],
        _get_udtf_list,
        doc="Lists registered UDTFs.",
    )
    reg.udtf(
        "GetVersion",
        [("key", S), ("value", S)],
        _get_version,
        doc="Build/version metadata of the executing process "
            "(reference Version UDTF / statusz surface).",
    )
    reg.udtf(
        "GetDebugTableInfo",
        [
            ("table_name", S),
            ("bytes", I),
            ("hot_bytes", I),
            ("cold_bytes", I),
            ("hot_rows", I),
            ("cold_rows", I),
            ("cold_raw_bytes", I),
            ("cold_demotions", I),
            ("cold_evictions", I),
            ("num_batches", I),
            ("batches_expired", I),
            ("compacted_batches", I),
            ("min_time", I),
        ],
        _get_debug_table_info,
        executor=UDTFExecutor.ALL_AGENTS,
        doc="Table-store internals per table (debug).",
    )


def _get_version(engine):
    from ... import version as _v

    info = _v.version_info()
    return {
        "key": list(info),
        "value": ["" if v is None else str(v) for v in info.values()],
    }
