"""Network UDFs (dictionary-side).

Reference parity: ``src/carnot/funcs/net/net_ops.h`` — ``NSLookupUDF``
(reverse-DNS with a per-process cache) and CIDR/IP helpers. Lookups run
once per distinct address in the dictionary; resolution failures (or
sandboxed environments with no resolver) fall back to the input address,
matching the reference's cache-miss behavior.
"""

from __future__ import annotations

import ipaddress
import socket

from ..udf import BOOLEAN, INT64, STRING, Executor

_NSLOOKUP_CACHE: dict[str, str] = {}
_NSLOOKUP_CACHE_MAX = 1 << 16
_NSLOOKUP_TIMEOUT_S = 1.0
_resolver_pool = None


def _resolver():
    global _resolver_pool
    if _resolver_pool is None:
        from concurrent.futures import ThreadPoolExecutor

        _resolver_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="nslookup"
        )
    return _resolver_pool


def nslookup(addr: str) -> str:
    hit = _NSLOOKUP_CACHE.get(addr)
    if hit is not None:
        return hit
    # NB: never socket.setdefaulttimeout here — that is process-global
    # state and would put read timeouts on every other socket in the
    # process (the TCP bus transport included). gethostbyaddr has no
    # per-call timeout, so the lookup runs on a resolver pool with a
    # result deadline: a dead resolver costs ~1s per distinct address,
    # not a resolver-timeout each (HOST_DICT runs this per DISTINCT
    # string at plan-bind time).
    from concurrent.futures import TimeoutError as FutTimeout

    try:
        fut = _resolver().submit(socket.gethostbyaddr, addr)
        name = fut.result(timeout=_NSLOOKUP_TIMEOUT_S)[0]
    except (OSError, ValueError, FutTimeout):
        name = addr
    if len(_NSLOOKUP_CACHE) >= _NSLOOKUP_CACHE_MAX:
        _NSLOOKUP_CACHE.clear()
    _NSLOOKUP_CACHE[addr] = name
    return name


def ip_to_int(addr: str) -> int:
    """IPv4 dotted-quad -> int (0 on parse failure)."""
    try:
        return int(ipaddress.IPv4Address(addr))
    except (ipaddress.AddressValueError, ValueError):
        return 0


def cidr_contains(addr: str, cidr) -> bool:
    try:
        return ipaddress.ip_address(addr) in ipaddress.ip_network(
            str(cidr), strict=False
        )
    except ValueError:
        return False


def register(reg):
    reg.scalar(
        "nslookup", (STRING,), STRING, nslookup,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="Reverse-DNS lookup (cached; falls back to the address).",
    )
    reg.scalar(
        "ip_to_int", (STRING,), INT64, ip_to_int,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="IPv4 address -> integer (0 when unparseable).",
    )
    reg.scalar(
        "cidr_contains", (STRING, STRING), BOOLEAN, cidr_contains,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="True when the address lies inside the (literal) CIDR block.",
    )
