"""Request-path endpoint clustering UDFs (dictionary-side).

Reference parity: ``src/carnot/funcs/builtins/request_path_ops.{h,cc}``
— ``RequestPathClusteringFitUDA`` (:230) clusters a corpus of request
paths into endpoint templates ("/a/b/123" -> "/a/b/*") and
``RequestPathClusteringPredictUDF``/``RequestPathEndpointMatcherUDF``
apply them.

Divergence (documented): the reference fits per-depth centroid clusters
over the observed corpus; here templating is a per-string decision —
path segments that look machine-generated (numeric, uuid, long hex,
high-digit-density tokens) become ``*``. This runs once per distinct
path in the dictionary and produces the same endpoint grouping for the
id-segment shapes the reference's own tests exercise, without a
stateful fit pass.
"""

from __future__ import annotations

import re

from ..udf import BOOLEAN, STRING, Executor

_NUM = re.compile(r"^\d+$")
_UUID = re.compile(
    r"^[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
    r"[0-9a-fA-F]{4}-[0-9a-fA-F]{12}$"
)
_HEX = re.compile(r"^[0-9a-fA-F]{8,}$")


def _is_id_segment(seg: str) -> bool:
    if not seg:
        return False
    if _NUM.match(seg) or _UUID.match(seg) or _HEX.match(seg):
        return True
    digits = sum(c.isdigit() for c in seg)
    return len(seg) >= 8 and digits / len(seg) >= 0.5


def _split(path: str):
    # "/a/b" and "a/b" are equivalent (request_path_ops.h:43); strip any
    # query string first.
    path = path.split("?", 1)[0]
    return [s for s in path.split("/") if s]


def cluster_request_path(path: str) -> str:
    segs = [("*" if _is_id_segment(s) else s) for s in _split(path)]
    return "/" + "/".join(segs)


def _endpoint_matches(path: str, template: str) -> bool:
    ps, ts = _split(path), _split(template)
    if len(ps) != len(ts):
        return False
    return all(t == "*" or t == p for p, t in zip(ps, ts))


def register(reg):
    reg.scalar(
        "_predict_request_path_cluster", (STRING,), STRING,
        cluster_request_path,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="Map a request path to its endpoint template "
            "(id-like segments become '*').",
    )
    # The user-facing alias the px scripts use.
    reg.scalar(
        "cluster_request_path", (STRING,), STRING, cluster_request_path,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="Map a request path to its endpoint template "
            "(id-like segments become '*').",
    )

    def matcher(path: str, template) -> bool:
        return _endpoint_matches(path, str(template))

    reg.scalar(
        "_match_endpoint", (STRING, STRING), BOOLEAN, matcher,
        executor=Executor.HOST_DICT, dict_arg=0,
        doc="True when the request path matches the endpoint template "
            "(literal second argument).",
    )
