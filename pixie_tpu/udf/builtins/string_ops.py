"""String scalar UDFs — executed against the dictionary, not the rows.

Reference parity: ``src/carnot/funcs/builtins/string_ops.cc`` (contains,
length, find, substring, tolower, toupper, trim, strip_prefix, atoi, ...).

Every function here is HOST_DICT: it maps distinct dictionary strings to
new values once per plan binding; the device applies an int32 gather.
O(distinct strings), not O(rows) — the opposite cost model from Carnot's
per-row Exec() calls.
"""

from __future__ import annotations

from ..udf import BOOLEAN, INT64, STRING, Executor


def _atoi(s: str) -> int:
    try:
        return int(s.strip())
    except ValueError:
        return 0


def register(reg):
    def dict_udf(name, arg_types, ret, fn, dict_arg=0, doc=""):
        reg.scalar(name, arg_types, ret, fn, executor=Executor.HOST_DICT, dict_arg=dict_arg, doc=doc)

    dict_udf("contains", (STRING, STRING), BOOLEAN, lambda s, sub: sub in s,
             doc="True when s contains the substring.")
    dict_udf("length", (STRING,), INT64, len)
    dict_udf("find", (STRING, STRING), INT64, lambda s, sub: s.find(sub))
    dict_udf("substring", (STRING, INT64, INT64), STRING,
             lambda s, pos, length: s[pos : pos + length])
    dict_udf("tolower", (STRING,), STRING, str.lower)
    dict_udf("toupper", (STRING,), STRING, str.upper)
    dict_udf("trim", (STRING,), STRING, str.strip)
    dict_udf("strip_prefix", (STRING, STRING), STRING,
             lambda prefix, s: s[len(prefix):] if s.startswith(prefix) else s,
             dict_arg=1, doc="Remove prefix from s when present.")
    dict_udf("atoi", (STRING,), INT64, _atoi)
    dict_udf("startswith", (STRING, STRING), BOOLEAN, lambda s, p: s.startswith(p))
    dict_udf("endswith", (STRING, STRING), BOOLEAN, lambda s, p: s.endswith(p))
    from ...types.semantic import SemanticType

    reg.scalar(
        "pod_name_to_namespace", (STRING,), STRING,
        lambda s: s.split("/", 1)[0] if "/" in s else "",
        executor=Executor.HOST_DICT,
        semantic_type=int(SemanticType.ST_NAMESPACE_NAME),
        doc="Namespace of a 'namespace/pod' name ('' if unqualified).",
    )
