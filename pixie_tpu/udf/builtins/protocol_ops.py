"""Protocol-name translation UDFs (device-side id tables).

Reference parity: ``src/carnot/funcs/protocols/protocol_ops.{h,cc}`` —
``ProtocolNameUDF`` (the conn_stats ``protocol`` enum,
``src/shared/protocols/protocols.h:28``), ``HTTPRespMessageUDF``,
``MySQLCommandNameUDF``, ``KafkaAPIKeyNameUDF``.

TPU-first design: each is an int -> name mapping, so the device applies
a single gather through a pre-staged id table whose output dictionary
holds the names — no host round-trip per row.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...types.strings import StringDictionary
from ..udf import INT64, STRING

# shared/protocols/protocols.h enum order (ids ARE dictionary ids).
PROTOCOL_NAMES = (
    "Unknown", "HTTP", "HTTP2", "MySQL", "CQL", "PGSQL", "DNS", "Redis",
    "NATS", "Mongo", "Kafka", "Mux", "AMQP", "TLS",
)

HTTP_RESP_MESSAGES = {
    100: "Continue", 101: "Switching Protocols", 102: "Processing",
    103: "Early Hints",
    200: "OK", 201: "Created", 202: "Accepted",
    203: "Non-Authoritative Information", 204: "No Content",
    205: "Reset Content", 206: "Partial Content", 207: "Multi-Status",
    208: "Already Reported", 226: "IM Used",
    300: "Multiple Choices", 301: "Moved Permanently", 302: "Found",
    303: "See Other", 304: "Not Modified", 305: "Use Proxy",
    307: "Temporary Redirect", 308: "Permanent Redirect",
    400: "Bad Request", 401: "Unauthorized", 402: "Payment Required",
    403: "Forbidden", 404: "Not Found", 405: "Method Not Allowed",
    406: "Not Acceptable", 407: "Proxy Authentication Required",
    408: "Request Timeout", 409: "Conflict", 410: "Gone",
    411: "Length Required", 412: "Precondition Failed",
    413: "Payload Too Large", 414: "URI Too Long",
    415: "Unsupported Media Type", 416: "Range Not Satisfiable",
    417: "Expectation Failed", 418: "I'm a teapot",
    421: "Misdirected Request", 422: "Unprocessable Entity",
    423: "Locked", 424: "Failed Dependency", 425: "Too Early",
    426: "Upgrade Required", 428: "Precondition Required",
    429: "Too Many Requests", 431: "Request Header Fields Too Large",
    451: "Unavailable For Legal Reasons",
    500: "Internal Server Error", 501: "Not Implemented",
    502: "Bad Gateway", 503: "Service Unavailable",
    504: "Gateway Timeout", 505: "HTTP Version Not Supported",
    506: "Variant Also Negotiates", 507: "Insufficient Storage",
    508: "Loop Detected", 510: "Not Extended",
    511: "Network Authentication Required",
}

MYSQL_COMMANDS = (
    "Sleep", "Quit", "InitDB", "Query", "FieldList", "CreateDB", "DropDB",
    "Refresh", "Shutdown", "Statistics", "ProcessInfo", "Connect",
    "ProcessKill", "Debug", "Ping", "Time", "DelayedInsert", "ChangeUser",
    "BinlogDump", "TableDump", "ConnectOut", "RegisterSlave",
    "StmtPrepare", "StmtExecute", "StmtSendLongData", "StmtClose",
    "StmtReset", "SetOption", "StmtFetch", "Daemon", "BinlogDumpGTID",
    "ResetConnection",
)

KAFKA_API_KEYS = (
    "Produce", "Fetch", "ListOffsets", "Metadata", "LeaderAndIsr",
    "StopReplica", "UpdateMetadata", "ControlledShutdown", "OffsetCommit",
    "OffsetFetch", "FindCoordinator", "JoinGroup", "Heartbeat",
    "LeaveGroup", "SyncGroup", "DescribeGroups", "ListGroups",
    "SaslHandshake", "ApiVersions", "CreateTopics", "DeleteTopics",
    "DeleteRecords", "InitProducerId", "OffsetForLeaderEpoch",
    "AddPartitionsToTxn", "AddOffsetsToTxn", "EndTxn", "WriteTxnMarkers",
    "TxnOffsetCommit", "DescribeAcls", "CreateAcls", "DeleteAcls",
    "DescribeConfigs", "AlterConfigs", "AlterReplicaLogDirs",
    "DescribeLogDirs", "SaslAuthenticate", "CreatePartitions",
    "CreateDelegationToken", "RenewDelegationToken",
    "ExpireDelegationToken", "DescribeDelegationToken", "DeleteGroups",
    "ElectLeaders", "IncrementalAlterConfigs", "AlterPartitionReassignments",
    "ListPartitionReassignments", "OffsetDelete", "DescribeClientQuotas",
    "AlterClientQuotas", "DescribeUserScramCredentials",
    "AlterUserScramCredentials",
)


def _enum_table_udf(names, unknown="Unknown"):
    """(fn, out_dict) mapping int ids -> dictionary ids via clamp."""
    # Enum ids ARE dictionary ids — only true while names are unique
    # (StringDictionary dedups, which would shift every later id).
    assert len(set(names)) == len(names), "duplicate enum name"
    vocab = list(names)
    if unknown not in vocab:
        vocab.append(unknown)
    d = StringDictionary(vocab)
    unk = d.lookup(unknown)
    n = len(names)

    def fn(x):
        x32 = x.astype(jnp.int32)
        return jnp.where((x32 >= 0) & (x32 < n), jnp.clip(x32, 0, n - 1),
                         unk).astype(jnp.int32)

    return fn, d


def _dense_table_udf(mapping, size, unknown="Unknown"):
    """(fn, out_dict) for sparse int -> name maps via a dense id table."""
    vocab = sorted(set(mapping.values())) + [unknown]
    d = StringDictionary(vocab)
    table = np.full(size + 1, d.lookup(unknown), dtype=np.int32)
    for code, name in mapping.items():
        table[code] = d.lookup(name)
    def fn(x):
        # jnp.asarray at TRACE time (an eager jax Array captured as a jit
        # constant poisons axon-tunnel dispatch).
        safe = jnp.clip(x.astype(jnp.int32), 0, size)
        ids = jnp.asarray(table)[safe]
        return jnp.where(x.astype(jnp.int32) == safe, ids, table[size]).astype(
            jnp.int32
        )

    return fn, d


def register(reg):
    fn, d = _enum_table_udf(PROTOCOL_NAMES)
    reg.scalar("protocol_name", (INT64,), STRING, fn, out_dict=d,
               doc="conn_stats protocol enum -> protocol name.")
    fn, d = _dense_table_udf(HTTP_RESP_MESSAGES, 599)
    reg.scalar("http_resp_message", (INT64,), STRING, fn, out_dict=d,
               doc="HTTP status code -> reason phrase.")
    fn, d = _enum_table_udf(MYSQL_COMMANDS)
    reg.scalar("mysql_command_name", (INT64,), STRING, fn, out_dict=d,
               doc="MySQL command byte -> command name.")
    fn, d = _enum_table_udf(KAFKA_API_KEYS)
    reg.scalar("kafka_api_key_name", (INT64,), STRING, fn, out_dict=d,
               doc="Kafka API key -> API name.")
