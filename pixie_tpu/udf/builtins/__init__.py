"""Builtin function registration root.

Reference parity: ``src/carnot/funcs/funcs.cc:30`` RegisterFuncsOrDie.
"""

from . import (
    collections,
    conditionals,
    introspection,
    json_ops,
    math_ops,
    math_sketches,
    ml_ops,
    net_ops,
    pii_ops,
    protocol_ops,
    regex_ops,
    request_path_ops,
    sql_ops,
    string_ops,
)


def register_all(reg):
    math_ops.register(reg)
    math_sketches.register(reg)
    conditionals.register(reg)
    collections.register(reg)
    string_ops.register(reg)
    json_ops.register(reg)
    regex_ops.register(reg)
    sql_ops.register(reg)
    ml_ops.register(reg)
    pii_ops.register(reg)
    request_path_ops.register(reg)
    net_ops.register(reg)
    protocol_ops.register(reg)
    introspection.register_introspection(reg)
