"""JSON scalar UDFs (dictionary-side).

Reference parity: ``src/carnot/funcs/builtins/json_ops.cc`` — pluck,
pluck_int64, pluck_float64, pluck_array (rapidjson per row). Here each runs
once per distinct dictionary string.
"""

from __future__ import annotations

import json

from ..udf import FLOAT64, INT64, STRING, Executor


def _pluck(s: str, key: str):
    try:
        v = json.loads(s).get(key)
    except (json.JSONDecodeError, AttributeError, TypeError):
        return None
    return v


def _pluck_str(s: str, key: str) -> str:
    v = _pluck(s, key)
    if v is None:
        return ""
    if isinstance(v, str):
        return v
    return json.dumps(v)


def _pluck_int(s: str, key: str) -> int:
    v = _pluck(s, key)
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


def _pluck_float(s: str, key: str) -> float:
    v = _pluck(s, key)
    try:
        return float(v)
    except (TypeError, ValueError):
        return float("nan")


def _pluck_array(s: str, idx: int) -> str:
    try:
        v = json.loads(s)
        return json.dumps(v[idx]) if isinstance(v[idx], (dict, list)) else str(v[idx])
    except (json.JSONDecodeError, IndexError, TypeError):
        return ""


def register(reg):
    kw = dict(executor=Executor.HOST_DICT, dict_arg=0)
    reg.scalar("pluck", (STRING, STRING), STRING, _pluck_str, **kw,
               doc="Extract a key from a JSON object as a string.")
    reg.scalar("pluck_int64", (STRING, STRING), INT64, _pluck_int, **kw)
    reg.scalar("pluck_float64", (STRING, STRING), FLOAT64, _pluck_float, **kw)
    reg.scalar("pluck_array", (STRING, INT64), STRING, _pluck_array, **kw)
