"""ML UDAs: streaming k-means and reservoir sampling.

Reference parity: ``src/carnot/funcs/builtins/ml_ops.h`` — ``KMeansUDA``
(:88: coreset update/merge, finalize runs kmeans and emits the centroids
as a string) and ``ReservoirSampleUDA`` (:145: uniform sample with
count-weighted merge). The transformer/sentencepiece UDFs (:52,:68) wrap
a TFLite model pool and stay out of scope — they are model-serving, not
engine, surface.

The carries are bottom-k priority sketches (``pixie_tpu.ops.ml``):
associative merges, so partial aggregation and cross-device folds work
unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...ops import ml
from ..udf import FLOAT64, INT64, STRING

KMEANS_K_MAX = 8
KMEANS_FIELDS = tuple(f"c{i}" for i in range(KMEANS_K_MAX))
CORESET_CAPACITY = 256


def _kmeans_init(g):
    res = ml.reservoir_init(g, CORESET_CAPACITY)
    return (*res, jnp.zeros((g,), dtype=jnp.int32))  # + per-group k


def _kmeans_update(carry, gids, mask, values, k):
    import jax

    *res, k_carry = carry
    g = carry[0].shape[0]
    res = ml.reservoir_update(tuple(res), gids, mask, values)
    k_new = jnp.maximum(
        k_carry,
        jax.ops.segment_max(
            jnp.where(mask, k, 0).astype(jnp.int32),
            jnp.where(mask, gids, g),
            num_segments=g + 1,
        )[:-1],
    )
    return (*res, k_new)


def _kmeans_merge(a, b):
    *ra, ka = a
    *rb, kb = b
    return (*ml.reservoir_merge(tuple(ra), tuple(rb)), jnp.maximum(ka, kb))


def _kmeans_finalize(carry):
    vals, prio, _count, k = carry
    k = jnp.clip(k, 1, KMEANS_K_MAX)
    return ml.kmeans_groups(vals, prio < ml._EMPTY, KMEANS_K_MAX, k)


def _reservoir_update(carry, gids, mask, values):
    return ml.reservoir_update(carry, gids, mask, values)


def register(reg):
    reg.uda(
        "kmeans",
        (FLOAT64, INT64),
        STRING,
        init=_kmeans_init,
        update=_kmeans_update,
        merge=_kmeans_merge,
        finalize=_kmeans_finalize,
        struct_fields=KMEANS_FIELDS,
        doc=(
            "Streaming 1-D k-means over the group: kmeans(value, k). "
            f"Centroids beyond k (max {KMEANS_K_MAX}) are NaN; the carry "
            "is a mergeable bottom-k coreset."
        ),
    )
    # Samples must be bit-exact elements of the data: each overload keeps
    # a reservoir of the input's full-precision dtype (x64 is enabled —
    # no float32 round trip).
    for dt, jdt, empty in (
        (FLOAT64, jnp.float64, jnp.nan),
        (INT64, jnp.int64, 0),
    ):
        reg.uda(
            "reservoir_sample",
            (dt,),
            dt,
            init=lambda g, _jdt=jdt: ml.reservoir_init(g, 1, _jdt),
            update=_reservoir_update,
            merge=ml.reservoir_merge,
            finalize=lambda c, _e=empty: jnp.where(
                c[1][:, 0] < ml._EMPTY, c[0][:, 0], _e
            ),
            doc="Uniform random sample of one group element (mergeable).",
        )
