"""Collection UDAs.

Reference parity: ``src/carnot/funcs/builtins/collections.cc`` —
AnyUDA("any", :33): returns an arbitrary member of the group. Implemented
as a segment-max (any deterministic pick works; max is collective-friendly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..udf import BOOLEAN, FLOAT64, INT64, STRING, TIME64NS

_NEUTRAL = {
    INT64: jnp.iinfo(jnp.int64).min,
    TIME64NS: jnp.iinfo(jnp.int64).min,
    FLOAT64: -jnp.inf,
    STRING: -(2**31),  # ids are int32; NULL decode for empty groups
    BOOLEAN: False,
}


def register(reg):
    def _update(c, gids, mask, v, lo):
        g = c.shape[0]
        contrib = jnp.where(mask, v, jnp.full((), lo, v.dtype))
        upd = jax.ops.segment_max(contrib, jnp.where(mask, gids, g), num_segments=g + 1)[:-1]
        return jnp.maximum(c, upd)

    for dt, lo in _NEUTRAL.items():
        reg.uda(
            "any", (dt,), dt,
            init=lambda g, _dt=dt, _lo=lo: jnp.full(
                g, _lo, dtype={BOOLEAN: jnp.bool_, STRING: jnp.int32, FLOAT64: jnp.float64}.get(_dt, jnp.int64)
            ),
            update=lambda c, gids, mask, v, _lo=lo: _update(c, gids, mask, v, _lo),
            merge=jnp.maximum,
            finalize=lambda c: c,
            doc="An arbitrary value from the group.",
        )
