"""UDF/UDA core protocol — the TPU-native analog of Carnot's UDF framework.

Reference parity: ``src/carnot/udf/udf.h`` — ``ScalarUDF`` (:78) and ``UDA``
with Update/Merge/Finalize + Serialize/DeSerialize for partial aggregation
(:91-100). TPU-first redesign:

- A **ScalarUDF** is a vectorized function over whole column planes
  (jnp arrays), traced into the fragment program. No per-row dispatch, no
  virtual calls — XLA fuses the whole expression tree
  (contrast: ``src/carnot/exec/expression_evaluator.cc`` evaluates node by
  node over ColumnWrappers).
- A **UDA** is *segmented*: ``update(carry, group_ids, mask, *args)``
  folds a whole batch into a ``[num_groups, ...]`` carry pytree using
  segment reductions, and ``merge(a, b)`` is associative so cross-device
  finalize is an all_gather + tree-merge (or psum when the carry is
  linear). The reference's ``Serialize/DeSerialize`` partial-agg protocol
  is just "the carry is a pytree" here.
- **Executor classes** say where a UDF runs:
  - DEVICE: pure jnp, inside the compiled fragment (math, conditionals).
  - HOST_DICT: string -> value functions applied to the column's string
    dictionary host-side at plan-bind time; the device applies an int32
    gather through the resulting lookup table. O(distinct), not O(rows).
    (regex/json/sql-normalize land here — the "host UDF" escape hatch.)
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Sequence

import jax.numpy as jnp

from ..types.dtypes import DataType

BOOLEAN = DataType.BOOLEAN
INT64 = DataType.INT64
UINT128 = DataType.UINT128
FLOAT64 = DataType.FLOAT64
STRING = DataType.STRING
TIME64NS = DataType.TIME64NS


class Executor(enum.Enum):
    DEVICE = "device"
    HOST_DICT = "host_dict"  # str -> scalar/str over the dictionary


@dataclass(frozen=True)
class ScalarUDFDef:
    """A scalar UDF overload.

    ``fn`` operates on one jnp array per single-plane arg; UINT128 args
    arrive as (hi, lo) tuples (``planes=True`` registrations take/return
    plane tuples for every arg).
    """

    name: str
    arg_types: tuple[DataType, ...]
    return_type: DataType
    fn: Callable
    executor: Executor = Executor.DEVICE
    # HOST_DICT only: fn is str -> python value; which arg is the string
    # column (all other args must be literals at plan time).
    dict_arg: int = 0
    # DEVICE UDFs returning STRING may carry their own output dictionary
    # (metadata lookups emit ids into an entity-name dictionary rather than
    # remapping an input dictionary).
    out_dict: object = None
    doc: str = ""
    # What the RETURN VALUE means (udf/type_inference.h analog): drives
    # ctx-property resolution and docgen. 1 == SemanticType.ST_NONE
    # (plain int default keeps the dataclass import-cycle-free).
    semantic_type: int = 1


@dataclass(frozen=True)
class UDADef:
    """A segmented user-defined aggregate.

    - ``init(num_groups) -> carry``: zero carry, pytree of [G, ...] arrays.
    - ``update(carry, group_ids, mask, *args) -> carry``: fold a batch;
      ``group_ids`` int32[n] in [0, G) (rows with mask False must not
      contribute), each arg a column plane array.
    - ``merge(a, b) -> carry``: associative combine of two carries
      (the partial-agg path: per-device carries merged across the mesh).
    - ``finalize(carry) -> array`` of [G] results (or [G, k] for
      multi-valued sketches; see ``finalize_type``).
    """

    name: str
    arg_types: tuple[DataType, ...]
    return_type: DataType
    init: Callable
    update: Callable
    merge: Callable
    finalize: Callable
    # When return_type is STRING and struct_fields is set, finalize returns
    # [G, len(struct_fields)] floats; the host materializes JSON objects
    # (Carnot's QuantilesUDA returns a JSON string the same way), and the
    # planner may fuse pluck_float64(agg, field) to a direct plane read.
    struct_fields: tuple[str, ...] | None = None
    doc: str = ""
    # Semantic type of the finalized value (ST_QUANTILES for sketches
    # etc.); 1 == SemanticType.ST_NONE.
    semantic_type: int = 1


# -- overload resolution -----------------------------------------------------

# Implicit cast lattice: arg type -> param types it may widen to, with cost.
_CASTS: dict[tuple[DataType, DataType], int] = {
    (BOOLEAN, INT64): 1,
    (BOOLEAN, FLOAT64): 2,
    (INT64, FLOAT64): 1,
    (TIME64NS, INT64): 1,
    (TIME64NS, FLOAT64): 2,
    (INT64, TIME64NS): 1,  # int64_to_time-style contexts
}


def cast_cost(have: DataType, want: DataType) -> int | None:
    if have == want:
        return 0
    return _CASTS.get((have, want))


def apply_cast(x, have: DataType, want: DataType):
    """Cast a column plane array between logical types (device-side).

    FLOAT64 planes are physically f32 (see types/dtypes.py) — casting to
    f64 here would fork compiled programs per plane dtype and re-admit f64
    into fused device code.
    """
    if have == want:
        return x
    if want == FLOAT64:
        return x.astype(jnp.float32)
    if want in (INT64, TIME64NS):
        return x.astype(jnp.int64)
    raise TypeError(f"no device cast {have} -> {want}")


class SignatureError(TypeError):
    pass


def resolve_overload(overloads: Sequence, arg_types: Sequence[DataType]):
    """Pick the cheapest-cast overload; raise on none/ambiguous."""
    best, best_cost, tie = None, None, False
    for ov in overloads:
        if len(ov.arg_types) != len(arg_types):
            continue
        cost = 0
        ok = True
        for have, want in zip(arg_types, ov.arg_types):
            c = cast_cost(have, want)
            if c is None:
                ok = False
                break
            cost += c
        if not ok:
            continue
        if best_cost is None or cost < best_cost:
            best, best_cost, tie = ov, cost, False
        elif cost == best_cost:
            tie = True
    if best is None:
        raise SignatureError(
            f"no overload of {overloads[0].name!r} matches argument types "
            f"({', '.join(t.name for t in arg_types)})"
        )
    if tie:
        raise SignatureError(
            f"ambiguous overloads of {overloads[0].name!r} for argument types "
            f"({', '.join(t.name for t in arg_types)})"
        )
    return best
