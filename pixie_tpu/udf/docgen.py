"""Registry documentation generator (the docs pipeline).

Reference parity: the UDF doc-extraction pipeline
(``/root/reference/src/carnot/docstring/`` + ``udf_exporter``) that turns
registered-function metadata into published reference docs. Here the
registry is the single source: every scalar/UDA/UDTF overload renders
into one markdown document.
"""

from __future__ import annotations


def _sig(arg_types, ret, semantic: int = 1) -> str:
    from ..types.semantic import SemanticType

    args = ", ".join(t.name for t in arg_types)
    sig = f"({args}) -> {ret.name}"
    if semantic not in (0, 1):  # UNSPECIFIED / NONE render nothing
        try:
            sig += f" [{SemanticType(semantic).name}]"
        except ValueError:
            sig += f" [semantic={semantic}]"  # user-defined value
    return sig


def generate_markdown(registry=None) -> str:
    """Markdown reference for every registered function."""
    from .registry import default_registry

    reg = registry or default_registry()
    lines = ["# pixie_tpu function reference", ""]

    lines += ["## Scalar functions", ""]
    for name in sorted(reg.scalar_names()):
        ovs = reg.scalar_overloads(name)
        doc = next((o.doc for o in ovs if o.doc), "")
        lines.append(f"### `{name}`")
        if doc:
            lines.append(doc)
        lines.append("")
        for o in ovs:
            lines.append(
                f"- `{name}{_sig(o.arg_types, o.return_type, o.semantic_type)}`"
            )
        lines.append("")

    lines += ["## Aggregate functions", ""]
    for name in sorted(reg.uda_names()):
        ovs = reg.uda_overloads(name)
        doc = next((o.doc for o in ovs if o.doc), "")
        lines.append(f"### `{name}`")
        if doc:
            lines.append(doc)
        lines.append("")
        for o in ovs:
            lines.append(
                f"- `{name}{_sig(o.arg_types, o.return_type, o.semantic_type)}`"
            )
        lines.append("")

    udtfs = sorted(reg.udtf_names())
    if udtfs:
        lines += ["## Table-generating functions (UDTF)", ""]
        for name in udtfs:
            d = reg.get_udtf(name)
            lines.append(f"### `{name}`")
            if d.doc:
                lines.append(d.doc)
            lines.append("")
            rel = ", ".join(f"{n}: {t.name}" for n, t in d.relation)
            lines.append(f"- returns `({rel})`")
            if d.init_args:
                args = ", ".join(
                    f"{e[0]}: {e[1].name}"
                    + (f" = {e[2]!r}" if len(e) > 2 else "")
                    for e in d.init_args
                )
                lines.append(f"- init args: `{args}`")
            lines.append("")
    return "\n".join(lines)
