"""User-Defined Table Functions: registry-backed table sources.

Reference parity: ``src/carnot/udf/udtf.h`` — a UDTF declares an output
relation, an executor class (where in the cluster it runs), and init
args; the planner surfaces it as ``px.<Name>(...)`` producing a
DataFrame. Cluster-introspection UDTFs live in ``src/vizier/funcs``
(``md_udtfs_impl.h:105-717``) and are registered here by the engine and
service layers with their backing context bound in.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Callable

from ..types.dtypes import DataType


class UDTFExecutor(enum.Enum):
    """Where a UDTF instance runs (udtf.h UDTFSourceExecutor)."""

    ALL_AGENTS = "all_agents"  # every data agent runs one instance
    ALL_PEM = "all_pem"  # data agents only
    ONE_KELVIN = "one_kelvin"  # a single merge-tier instance


@dataclass(frozen=True)
class UDTFDef:
    name: str
    # Output schema: tuple[(col name, DataType)].
    relation: tuple
    # fn(ctx, **init_args) -> {col: sequence}; ctx is the executing
    # engine (tables + registry) plus whatever the registrar closed over.
    fn: Callable
    executor: UDTFExecutor = UDTFExecutor.ONE_KELVIN
    # Declared init args, checked at compile time: each entry is
    # (name, DataType) for a required arg or (name, DataType, default)
    # for an optional one (udtf.h UDTFArg semantics).
    init_args: tuple = ()
    doc: str = ""

    def arg_required(self, name: str) -> bool:
        for entry in self.init_args:
            if entry[0] == name:
                return len(entry) == 2
        return False
