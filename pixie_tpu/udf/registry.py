"""UDF/UDA registry keyed by name + argument types.

Reference parity: ``src/carnot/udf/registry.h:101`` (Registry with
RegisterOrDie / GetScalarUDF by name+types). Overload resolution applies
the implicit-cast lattice in ``udf.py``.
"""

from __future__ import annotations

from typing import Callable, Iterable

from ..types.dtypes import DataType
from .udf import Executor, ScalarUDFDef, SignatureError, UDADef, resolve_overload


class Registry:
    def __init__(self, name: str = "default"):
        self.name = name
        self._scalar: dict[str, list[ScalarUDFDef]] = {}
        self._uda: dict[str, list[UDADef]] = {}
        self._udtf: dict[str, object] = {}  # name -> UDTFDef

    # -- registration --------------------------------------------------------
    def register_scalar(self, udf: ScalarUDFDef) -> None:
        for existing in self._scalar.setdefault(udf.name, []):
            if existing.arg_types == udf.arg_types:
                raise ValueError(
                    f"duplicate scalar UDF {udf.name!r} with arg types {udf.arg_types}"
                )
        self._scalar[udf.name].append(udf)
        self._ctx_funcs_cache = None  # metadata resolver derives from this

    def register_uda(self, uda: UDADef) -> None:
        for existing in self._uda.setdefault(uda.name, []):
            if existing.arg_types == uda.arg_types:
                raise ValueError(
                    f"duplicate UDA {uda.name!r} with arg types {uda.arg_types}"
                )
        self._uda[uda.name].append(uda)

    def scalar(
        self,
        name: str,
        arg_types: Iterable[DataType],
        return_type: DataType,
        fn: Callable,
        executor: Executor = Executor.DEVICE,
        dict_arg: int = 0,
        out_dict=None,
        doc: str = "",
        semantic_type: int = 1,
    ) -> ScalarUDFDef:
        udf = ScalarUDFDef(
            name=name,
            arg_types=tuple(arg_types),
            return_type=return_type,
            fn=fn,
            executor=executor,
            dict_arg=dict_arg,
            out_dict=out_dict,
            doc=doc,
            semantic_type=semantic_type,
        )
        self.register_scalar(udf)
        return udf

    def uda(
        self,
        name: str,
        arg_types: Iterable[DataType],
        return_type: DataType,
        *,
        init: Callable,
        update: Callable,
        merge: Callable,
        finalize: Callable,
        struct_fields: tuple[str, ...] | None = None,
        doc: str = "",
        semantic_type: int = 1,
    ) -> UDADef:
        d = UDADef(
            name=name,
            arg_types=tuple(arg_types),
            return_type=return_type,
            init=init,
            update=update,
            merge=merge,
            finalize=finalize,
            struct_fields=struct_fields,
            doc=doc,
            semantic_type=semantic_type,
        )
        self.register_uda(d)
        return d

    def register_udtf(self, udtf) -> None:
        if udtf.name in self._udtf:
            raise ValueError(f"duplicate UDTF {udtf.name!r}")
        self._udtf[udtf.name] = udtf

    def udtf(self, name, relation, fn, executor=None, init_args=(), doc=""):
        from .udtf import UDTFDef, UDTFExecutor

        d = UDTFDef(
            name=name,
            relation=tuple(relation),
            fn=fn,
            executor=executor or UDTFExecutor.ONE_KELVIN,
            init_args=tuple(init_args),
            doc=doc,
        )
        self.register_udtf(d)
        return d

    # -- lookup --------------------------------------------------------------
    def has_scalar(self, name: str) -> bool:
        return name in self._scalar

    def has_uda(self, name: str) -> bool:
        return name in self._uda

    def get_scalar(self, name: str, arg_types: Iterable[DataType]) -> ScalarUDFDef:
        if name not in self._scalar:
            raise SignatureError(f"no scalar UDF named {name!r}")
        return resolve_overload(self._scalar[name], tuple(arg_types))

    def get_uda(self, name: str, arg_types: Iterable[DataType]) -> UDADef:
        if name not in self._uda:
            raise SignatureError(f"no UDA named {name!r}")
        return resolve_overload(self._uda[name], tuple(arg_types))

    def has_udtf(self, name: str) -> bool:
        return name in self._udtf

    def get_udtf(self, name: str):
        if name not in self._udtf:
            raise SignatureError(f"no UDTF named {name!r}")
        return self._udtf[name]

    def scalar_names(self) -> list[str]:
        return sorted(self._scalar)

    def uda_names(self) -> list[str]:
        return sorted(self._uda)

    def scalar_overloads(self, name: str) -> list[ScalarUDFDef]:
        return list(self._scalar.get(name, []))

    def uda_overloads(self, name: str) -> list[UDADef]:
        return list(self._uda.get(name, []))

    def udtf_names(self) -> list[str]:
        return sorted(self._udtf)

    def clone(self, name: str | None = None, exclude=()) -> "Registry":
        """Shallow copy (defs are frozen), optionally dropping some names —
        used to rebind state-backed funcs (metadata) without losing caller
        registrations."""
        out = Registry(name or self.name)
        ex = set(exclude)
        out._scalar = {n: list(v) for n, v in self._scalar.items() if n not in ex}
        out._uda = {n: list(v) for n, v in self._uda.items() if n not in ex}
        out._udtf = {n: v for n, v in self._udtf.items() if n not in ex}
        return out

    def docs(self) -> dict[str, str]:
        """name -> doc for every registered func (doc-extraction parity)."""
        out = {}
        for name, ovs in {**self._scalar, **self._uda}.items():
            out[name] = next((o.doc for o in ovs if o.doc), "")
        return out


_default_registry: Registry | None = None


def default_registry() -> Registry:
    """Process-wide registry with all builtins registered (lazily)."""
    global _default_registry
    if _default_registry is None:
        _default_registry = Registry("builtins")
        from .builtins import register_all

        register_all(_default_registry)
    return _default_registry
