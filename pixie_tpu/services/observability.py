"""Service observability: metrics registry + healthz/statusz/metrics HTTP.

Reference parity: the prometheus-cpp registry every C++ service carries
(``src/common/metrics/metrics.h:27`` — e.g. PEM node-memory gauges,
table-store counters) and the shared Go service handlers
(``src/shared/services/``: ``healthz``, ``statusz``, prometheus
``metrics``). Transport is stdlib http.server (no external deps); the
text exposition follows the Prometheus format so standard scrapers work.
"""

from __future__ import annotations

import http.server
import json
import threading
from dataclasses import dataclass, field


@dataclass
class _Metric:
    name: str
    kind: str  # "counter" | "gauge"
    help: str
    values: dict = field(default_factory=dict)  # labels tuple -> float


class MetricsRegistry:
    """Process-wide named counters/gauges with label support."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._collectors: list = []  # callables run at render time

    def counter(self, name: str, help: str = "") -> "Counter":
        with self._lock:
            m = self._metrics.setdefault(name, _Metric(name, "counter", help))
        return Counter(m, self._lock)

    def gauge(self, name: str, help: str = "") -> "Gauge":
        with self._lock:
            m = self._metrics.setdefault(name, _Metric(name, "gauge", help))
        return Gauge(m, self._lock)

    def register_collector(self, fn) -> None:
        """``fn(registry)`` runs before each render — pull-style metrics
        (table stats, cache bytes) refresh here."""
        self._collectors.append(fn)

    def render(self) -> str:
        for fn in list(self._collectors):
            fn(self)

        def esc(v) -> str:  # exposition-format label escaping
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        lines = []
        with self._lock:
            for m in sorted(self._metrics.values(), key=lambda m: m.name):
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                for labels, v in sorted(m.values.items()):
                    if labels:
                        lbl = ",".join(
                            f'{k}="{esc(val)}"' for k, val in labels
                        )
                        lines.append(f"{m.name}{{{lbl}}} {v}")
                    else:
                        lines.append(f"{m.name} {v}")
        return "\n".join(lines) + "\n"


class _Bound:
    def __init__(self, metric: _Metric, lock, labels=()):
        self._m = metric
        self._lock = lock
        self._labels = tuple(sorted(labels))

    def labels(self, **kw):
        return type(self)(self._m, self._lock, tuple(kw.items()))


class Counter(_Bound):
    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._m.values[self._labels] = (
                self._m.values.get(self._labels, 0.0) + v
            )


class Gauge(_Bound):
    def set(self, v: float) -> None:
        with self._lock:
            self._m.values[self._labels] = float(v)


#: Default process registry (metrics.h GetMetricsRegistry analog).
default_registry = MetricsRegistry()


class ObservabilityServer:
    """healthz / statusz / metrics endpoints for one service process."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 statusz_fn=None, health_fn=None):
        self.registry = registry or default_registry
        self.statusz_fn = statusz_fn  # () -> dict
        self.health_fn = health_fn  # () -> (bool, str)
        self._httpd = None

    def handle(self, path: str) -> tuple[int, str, str]:
        """(status, content_type, body) — transport-independent core."""
        if path == "/healthz":
            ok, msg = (True, "ok") if self.health_fn is None else self.health_fn()
            return (200 if ok else 503, "text/plain", msg + "\n")
        if path == "/statusz":
            from ..config import all_flags
            from ..version import version_info

            status = {
                "version": version_info(),
                "flags": {k: v for k, (v, _) in all_flags().items()},
            }
            if self.statusz_fn is not None:
                status.update(self.statusz_fn())
            return (200, "application/json", json.dumps(status, indent=1))
        if path == "/version":
            from ..version import version_info

            return (200, "application/json", json.dumps(version_info()))
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4", self.registry.render())
        return (404, "text/plain", "not found\n")

    def start(self, port: int = 0) -> int:
        """Serve on a background thread; returns the bound port."""
        obs = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                code, ctype, body = obs.handle(self.path.split("?")[0])
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(
            target=self._httpd.serve_forever, name="observability", daemon=True
        )
        t.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def engine_collector(engine):
    """Collector exporting an engine's table + device-cache stats
    (table_metrics.h / pem_manager.h:63 node-memory gauges analog)."""

    def collect(reg: MetricsRegistry) -> None:
        from ..table_store.device_cache import total_resident_bytes

        g_rows = reg.gauge("pixie_table_rows", "Rows resident per table")
        g_bytes = reg.gauge("pixie_table_bytes", "Bytes resident per table")
        for name, t in engine.tables.items():
            if t is None:
                continue
            st = t.stats()
            g_rows.labels(table=name).set(st.num_rows)
            g_bytes.labels(table=name).set(st.bytes)
        reg.gauge(
            "pixie_device_cache_bytes",
            "Device-resident window bytes (all tables)",
        ).set(total_resident_bytes())
        # Window-prefetch pipeline (exec/pipeline.py): lifetime totals of
        # windows executed, producer staging time, and consumer stall
        # time. stall << stage means the overlap is hiding staging cost;
        # stall ~= stage means the device is waiting on the host.
        pt = getattr(engine, "pipeline_totals", None)
        if pt is not None:
            reg.gauge(
                "pixie_pipeline_depth",
                "Configured window-prefetch depth (1 = serial)",
            ).set(getattr(engine, "pipeline_depth", 1))
            reg.gauge(
                "pixie_pipeline_windows_total",
                "Windows executed through the window pipeline",
            ).set(pt["windows"])
            reg.gauge(
                "pixie_pipeline_stage_seconds_total",
                "Prefetch-thread seconds spent staging windows",
            ).set(round(pt["stage_secs"], 6))
            reg.gauge(
                "pixie_pipeline_stall_seconds_total",
                "Query-thread seconds stalled waiting for a window",
            ).set(round(pt["stall_secs"], 6))

    return collect
