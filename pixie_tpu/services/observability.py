"""Service observability: metrics registry + healthz/statusz/metrics HTTP.

Reference parity: the prometheus-cpp registry every C++ service carries
(``src/common/metrics/metrics.h:27`` — e.g. PEM node-memory gauges,
table-store counters) and the shared Go service handlers
(``src/shared/services/``: ``healthz``, ``statusz``, prometheus
``metrics``). Transport is stdlib http.server (no external deps); the
text exposition follows the Prometheus format so standard scrapers work.

Metric kinds: ``counter`` (monotonic), ``gauge``, and ``histogram``
(fixed buckets; cumulative ``_bucket{le=...}`` + ``_sum``/``_count``
exposition, prometheus-cpp Histogram analog). The query-lifecycle
tracer (``exec/trace.py``) records ``pixie_query_duration_seconds``,
``pixie_window_stage_seconds`` and ``pixie_pipeline_stall_seconds``
histograms here; ``/debug/queryz`` lists its in-flight + recent traces.
"""

from __future__ import annotations

import bisect
import http.server
import json
import threading
import time
from dataclasses import dataclass, field

#: Prometheus client default latency buckets (seconds).
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


@dataclass
class _Metric:
    name: str
    kind: str  # "counter" | "gauge" | "histogram"
    help: str
    values: dict = field(default_factory=dict)  # labels tuple -> value
    # histogram only: ascending finite upper bounds (le); +Inf implicit.
    buckets: tuple = ()


def _esc_label(v) -> str:
    """Exposition-format label-value escaping."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _esc_help(v) -> str:
    """HELP text escaping (the format escapes backslash + newline only;
    quotes are legal in HELP)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_bound(b: float) -> str:
    """Bucket bound rendering: 0.005 -> '0.005', 1.0 -> '1'."""
    return format(b, "g")


class MetricsRegistry:
    """Process-wide named counters/gauges/histograms with label support."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._collectors: list = []  # callables run at render time

    def counter(self, name: str, help: str = "") -> "Counter":
        with self._lock:
            m = self._metrics.setdefault(name, _Metric(name, "counter", help))
        return Counter(m, self._lock)

    def gauge(self, name: str, help: str = "") -> "Gauge":
        with self._lock:
            m = self._metrics.setdefault(name, _Metric(name, "gauge", help))
        return Gauge(m, self._lock)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> "Histogram":
        bk = tuple(sorted(float(b) for b in buckets))
        with self._lock:
            m = self._metrics.setdefault(
                name, _Metric(name, "histogram", help, buckets=bk)
            )
        return Histogram(m, self._lock)

    def register_collector(self, fn) -> None:
        """``fn(registry)`` runs before each render — pull-style metrics
        (table stats, cache bytes) refresh here."""
        self._collectors.append(fn)

    def values(self, name: str) -> dict:
        """Snapshot of a counter/gauge's per-label-set values
        ({labels tuple: value}; {} for unknown names or histograms —
        those go through ``histogram_state``/``quantiles``)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m.kind == "histogram":
                return {}
            return dict(m.values)

    def render(self) -> str:
        # A raising collector must not 500 the whole scrape: count it
        # and keep rendering the rest (prometheus-cpp Collect contract).
        failed = []
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:
                failed.append(getattr(fn, "__name__", repr(fn)))
        if failed:
            c = self.counter(
                "pixie_collector_errors_total",
                "Metric collector callbacks that raised during a render",
            )
            for name in failed:
                c.labels(collector=name).inc()

        lines = []
        with self._lock:
            for m in sorted(self._metrics.values(), key=lambda m: m.name):
                if m.help:
                    lines.append(f"# HELP {m.name} {_esc_help(m.help)}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                if m.kind == "histogram":
                    self._render_histogram(m, lines)
                    continue
                for labels, v in sorted(m.values.items()):
                    if labels:
                        lbl = ",".join(
                            f'{k}="{_esc_label(val)}"' for k, val in labels
                        )
                        lines.append(f"{m.name}{{{lbl}}} {v}")
                    else:
                        lines.append(f"{m.name} {v}")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_histogram(m: _Metric, lines: list) -> None:
        for labels, st in sorted(m.values.items()):
            base = ",".join(
                f'{k}="{_esc_label(val)}"' for k, val in labels
            )

            def series(name, extra=""):
                lbl = ",".join(x for x in (base, extra) if x)
                return f"{name}{{{lbl}}}" if lbl else name

            cum = 0
            for b, c in zip(m.buckets, st["counts"]):
                cum += c
                lines.append(
                    f'{series(m.name + "_bucket", f_le(b))} {cum}'
                )
            cum += st["counts"][-1]
            lines.append(f'{series(m.name + "_bucket", LE_INF)} {cum}')
            lines.append(f'{series(m.name + "_sum")} {st["sum"]}')
            lines.append(f'{series(m.name + "_count")} {st["count"]}')

    def histogram_state(self, name: str, **labels):
        """Raw cumulative state of a histogram metric, summed across
        matching label sets: ``(bounds, counts, count, sum)`` with
        ``counts`` carrying the implicit +Inf slot last, or None when
        the metric is missing. Callers that want PER-RUN quantiles
        snapshot this before and after and interpolate over the delta
        (``delta_quantiles``) — the histograms themselves are
        process-lifetime cumulative."""
        want = set(labels.items())
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m.kind != "histogram":
                return None
            counts = [0] * (len(m.buckets) + 1)
            total = 0
            sum_ = 0.0
            for lbls, st in m.values.items():
                if want and not want <= set(lbls):
                    continue
                for i, c in enumerate(st["counts"]):
                    counts[i] += c
                total += st["count"]
                sum_ += st["sum"]
            return (m.buckets, counts, total, sum_)

    def quantiles(self, name: str, qs=(0.5, 0.95, 0.99), **labels):
        """Approximate quantiles of a histogram metric from its buckets
        (prometheus ``histogram_quantile`` linear interpolation; the
        +Inf bucket clamps to the highest finite bound). Label kwargs
        filter; observations are summed across all matching label sets.
        Returns {q: value} or None when the metric is missing/empty."""
        st = self.histogram_state(name, **labels)
        if st is None:
            return None
        bounds, counts, total, _sum = st
        if total == 0 or not bounds:
            # Zero observations (or a bucketless histogram, where every
            # observation lands in +Inf and no finite interpolation
            # exists): there IS no quantile — None, never a made-up 0.0.
            return None
        return _interpolate_quantiles(bounds, counts, total, qs)


def delta_quantiles(before, after, qs=(0.5, 0.95, 0.99)):
    """Quantiles of the observations recorded BETWEEN two
    ``MetricsRegistry.histogram_state`` snapshots (bucket-count
    subtraction + the shared interpolation). Returns {q: value} or None
    when either snapshot is missing or nothing was observed in between
    — the load tester's per-run latency report
    (``services/load_tester.py``)."""
    if before is None or after is None:
        return None
    bounds, counts_b, total_b, _ = before
    _bounds_a, counts_a, total_a, _ = after
    total = total_a - total_b
    if total <= 0 or not bounds or len(counts_a) != len(counts_b):
        return None
    counts = [a - b for a, b in zip(counts_a, counts_b)]
    if any(c < 0 for c in counts):
        return None  # metric reset between snapshots
    return _interpolate_quantiles(bounds, counts, total, qs)


def _interpolate_quantiles(bounds, counts, total, qs) -> dict:
    """histogram_quantile linear interpolation over cumulative bucket
    counts (the +Inf bucket clamps to the highest finite bound). One
    shared implementation for both quantile surfaces — callers
    guarantee ``total > 0`` and non-empty ``bounds``."""
    out = {}
    for q in qs:
        rank = q * total
        cum = 0.0
        val = bounds[-1]
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(bounds):  # +Inf bucket
                    val = bounds[-1]
                else:
                    lo = bounds[i - 1] if i > 0 else 0.0
                    val = lo + (bounds[i] - lo) * max(rank - cum, 0.0) / c
                break
            cum += c
        out[q] = val
    return out


def f_le(b: float) -> str:
    """le="..." label fragment for one finite bucket bound."""
    return f'le="{_fmt_bound(b)}"'


LE_INF = 'le="+Inf"'


class _Bound:
    def __init__(self, metric: _Metric, lock, labels=()):
        self._m = metric
        self._lock = lock
        self._labels = tuple(sorted(labels))

    def labels(self, **kw):
        return type(self)(self._m, self._lock, tuple(kw.items()))

    def value(self) -> float:
        """Current scalar value for this label set (0.0 if never set) —
        counters/gauges only; histograms keep structured state."""
        with self._lock:
            v = self._m.values.get(self._labels, 0.0)
        return v if isinstance(v, float) else 0.0


class Counter(_Bound):
    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError(
                f"counter {self._m.name} cannot decrease (inc {v}); "
                "Prometheus counters are monotonic — use a gauge"
            )
        with self._lock:
            self._m.values[self._labels] = (
                self._m.values.get(self._labels, 0.0) + v
            )


class Gauge(_Bound):
    def set(self, v: float) -> None:
        with self._lock:
            self._m.values[self._labels] = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self._m.values[self._labels] = (
                self._m.values.get(self._labels, 0.0) + v
            )

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)


class Histogram(_Bound):
    def quantiles(self, qs=(0.5, 0.95, 0.99)):
        """Approximate quantiles for THIS bound label set (all label
        sets when unbound). Returns {q: value} or None on a
        zero-observation histogram — callers never special-case an
        empty distribution, they get None, not a crash or a fake 0."""
        with self._lock:
            st = self._m.values.get(self._labels)
            bounds = self._m.buckets
            if st is not None:
                counts = list(st["counts"])
                total = st["count"]
            elif not self._labels:
                # Unbound handle: aggregate across every label set.
                counts = [0] * (len(bounds) + 1)
                total = 0
                for s in self._m.values.values():
                    for i, c in enumerate(s["counts"]):
                        counts[i] += c
                    total += s["count"]
            else:
                return None
        if total == 0 or not bounds:
            return None
        return _interpolate_quantiles(bounds, counts, total, qs)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            st = self._m.values.get(self._labels)
            if st is None:
                st = self._m.values[self._labels] = {
                    "counts": [0] * (len(self._m.buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            # le semantics: an observation equal to a bound counts in
            # that bound's bucket (bisect_left finds the first bound
            # >= v); past the last bound -> the implicit +Inf slot.
            st["counts"][bisect.bisect_left(self._m.buckets, v)] += 1
            st["sum"] += v
            st["count"] += 1


#: Default process registry (metrics.h GetMetricsRegistry analog).
default_registry = MetricsRegistry()


def default_counter(name: str, help: str = "") -> Counter:
    """Bound counter on the process-wide default registry. Registration
    is idempotent and binding is cheap — call at the increment site, no
    per-caller lazy-cache dance needed."""
    return default_registry.counter(name, help)


class ObservabilityServer:
    """healthz / statusz / metrics / debug endpoints for one service
    process. Wire a ``tracer`` (``exec.trace.Tracer``, e.g.
    ``engine.tracer``) to serve ``/debug/queryz`` — the in-flight +
    recent query-trace listing (Carnot's per-query
    OperatorExecutionStats surface, made always-on)."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 statusz_fn=None, health_fn=None, tracer=None,
                 trace_view=None, programs=None, tablez_fn=None,
                 cachez_fn=None, profilez_fn=None, busz_fn=None):
        self.registry = registry or default_registry
        self.statusz_fn = statusz_fn  # () -> dict
        self.health_fn = health_fn  # () -> (bool, str)
        self.tracer = tracer  # exec.trace.Tracer | None
        # services.telemetry.ClusterTraceView | None: wire one to serve
        # /debug/tracez — the cluster-stitched distributed-trace view.
        self.trace_view = trace_view
        # exec.programs.ProgramRegistry | None: wire one to serve
        # /debug/programz — the compiled-program registry (per-program
        # compile wall-time, XLA cost/memory analysis, hit counts).
        self.programs = programs
        # () -> dict | None: wire one to serve /debug/tablez — the
        # storage-tier freshness snapshot (an agent serves its local
        # TableStore.freshness(); a broker serves the tracker's
        # cluster merge — watermark max, counters summed, lag spread).
        self.tablez_fn = tablez_fn
        # () -> dict | None: wire one to serve /debug/cachez — the
        # watermark-validated result-cache snapshot (entries with their
        # per-table stored watermarks, byte budget, hit counts) plus any
        # registered materialized views (exec/views.py).
        self.cachez_fn = cachez_fn
        # (agent_id=None, tenant=None, script_hash=None) -> profile
        # summary rows ({stack, count, qid, script_hash, tenant,
        # phase}): wire one to serve /debug/pprof (collapsed format)
        # and /debug/flamez (static HTML flamegraph). An agent serves
        # its local profiler summary; a broker serves the tracker's
        # cluster merge plus its own samples.
        self.profilez_fn = profilez_fn
        # () -> dict | None: wire one to serve /debug/busz — the
        # transport-tier snapshot (an agent serves its bus's busz();
        # a broker serves the tracker's cluster merge + its local bus
        # + per-connection BusServer accounting).
        self.busz_fn = busz_fn
        self._httpd = None

    def handle(self, path: str) -> tuple[int, str, str]:
        """(status, content_type, body) — transport-independent core.
        ``path`` may carry a query string (``/debug/pprof?seconds=5``);
        endpoints that take no parameters ignore it."""
        path, _, query = path.partition("?")
        if path in ("/debug/pprof", "/debug/flamez"):
            return self._handle_profile(path, query)
        if path == "/healthz":
            ok, msg = (True, "ok") if self.health_fn is None else self.health_fn()
            return (200 if ok else 503, "text/plain", msg + "\n")
        if path == "/statusz":
            from ..config import all_flags
            from ..version import version_info

            status = {
                "version": version_info(),
                "flags": {k: v for k, (v, _) in all_flags().items()},
            }
            if self.statusz_fn is not None:
                status.update(self.statusz_fn())
            return (200, "application/json", json.dumps(status, indent=1))
        if path == "/version":
            from ..version import version_info

            return (200, "application/json", json.dumps(version_info()))
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4", self.registry.render())
        if path == "/debug/queryz":
            if self.tracer is None:
                return (404, "text/plain", "no tracer wired\n")
            body = json.dumps(
                {
                    "in_flight": self.tracer.in_flight(),
                    "recent": self.tracer.recent(),
                },
                indent=1,
                default=str,
            )
            return (200, "application/json", body)
        if path == "/debug/tablez":
            if self.tablez_fn is None:
                return (404, "text/plain", "no table stats wired\n")
            body = json.dumps(self.tablez_fn(), indent=1, default=str)
            return (200, "application/json", body)
        if path == "/debug/cachez":
            if self.cachez_fn is None:
                return (404, "text/plain", "no result cache wired\n")
            body = json.dumps(self.cachez_fn(), indent=1, default=str)
            return (200, "application/json", body)
        if path == "/debug/busz":
            if self.busz_fn is None:
                return (404, "text/plain", "no bus stats wired\n")
            body = json.dumps(self.busz_fn(), indent=1, default=str)
            return (200, "application/json", body)
        if path == "/debug/programz":
            if self.programs is None:
                return (404, "text/plain", "no program registry wired\n")
            body = json.dumps(
                self.programs.programz(), indent=1, default=str
            )
            return (200, "application/json", body)
        if path == "/debug/tracez" or path.startswith("/debug/tracez/"):
            if self.trace_view is None:
                return (404, "text/plain", "no trace view wired\n")
            tid = path[len("/debug/tracez/"):] if "/tracez/" in path else ""
            if tid:
                tr = self.trace_view.get(tid)
                if tr is None:
                    return (404, "text/plain", f"no trace {tid}\n")
                body = json.dumps(tr, indent=1, default=str)
            else:
                body = json.dumps(
                    self.trace_view.tracez(), indent=1, default=str
                )
            return (200, "application/json", body)
        return (404, "text/plain", "not found\n")

    def _handle_profile(self, path: str, query: str) -> tuple[int, str, str]:
        """/debug/pprof (flamegraph collapsed text) and /debug/flamez
        (static HTML flamegraph) over the wired profile source.

        Parameters: ``agent``/``tenant``/``script`` filter the merged
        summary; ``seconds=N`` (pprof) windows it — two cumulative
        snapshots N seconds apart, per-stack growth between them —
        instead of the since-start totals."""
        if self.profilez_fn is None:
            return (404, "text/plain", "no profiler wired\n")
        import urllib.parse

        from .telemetry import (
            collapsed_text, counts_delta, flame_html, profile_counts,
        )

        params = urllib.parse.parse_qs(query)

        def one(name):
            vals = params.get(name)
            return vals[0] if vals else None

        agent, tenant, script = one("agent"), one("tenant"), one("script")
        counts = profile_counts(
            self.profilez_fn(
                agent_id=agent, tenant=tenant, script_hash=script
            )
        )
        if path == "/debug/flamez":
            label = " ".join(
                f"{k}={v}" for k, v in
                (("agent", agent), ("tenant", tenant), ("script", script))
                if v
            )
            title = "pixie cpu flame" + (f" [{label}]" if label else "")
            return (200, "text/html", flame_html(counts, title=title))
        try:
            seconds = float(one("seconds") or 0)
        except ValueError:
            seconds = 0.0
        if seconds > 0:
            # Windowed profile: cumulative counts are monotonic, so the
            # delta between two snapshots is exactly the window's
            # samples. Cap the in-handler wait (this blocks one server
            # thread, nothing else).
            time.sleep(min(seconds, 60.0))
            after = profile_counts(
                self.profilez_fn(
                    agent_id=agent, tenant=tenant, script_hash=script
                )
            )
            counts = counts_delta(counts, after)
        return (200, "text/plain", collapsed_text(counts))

    def start(self, port: int = 0) -> int:
        """Serve on a background thread; returns the bound port."""
        obs = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                code, ctype, body = obs.handle(self.path)
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # silence per-request stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
        t = threading.Thread(
            target=self._httpd.serve_forever, name="observability", daemon=True
        )
        t.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def engine_collector(engine):
    """Collector exporting an engine's table + device-cache stats
    (table_metrics.h / pem_manager.h:63 node-memory gauges analog)."""

    def collect(reg: MetricsRegistry) -> None:
        import time as _time

        from ..table_store.device_cache import total_resident_bytes

        g_rows = reg.gauge("pixie_table_rows", "Rows resident per table")
        # tier label: "hot" = ring bytes, "cold" = encoded cold-store
        # bytes (pxtier). Untiered tables report only tier="hot" (their
        # whole ring), so sum-over-tiers is always total resident bytes.
        g_bytes = reg.gauge(
            "pixie_table_bytes", "Bytes resident per table and tier"
        )
        g_demote = reg.gauge(
            "pixie_cold_demotions_total",
            "Windows demoted hot->cold per table (pxtier)",
        )
        g_evict = reg.gauge(
            "pixie_cold_evictions_total",
            "Cold windows evicted (true expiry) per table",
        )
        g_decode = reg.gauge(
            "pixie_cold_decode_seconds_total",
            "Seconds spent decoding cold windows per table",
        )
        # Storage-tier freshness (monotonic counters rendered as gauges
        # set to the counter value at scrape — the pipeline-totals
        # idiom; `table` label cardinality is bounded by the process's
        # created-table set, like pixie_table_rows above).
        g_rows_t = reg.gauge(
            "pixie_table_rows_total", "Rows ever appended per table"
        )
        g_bytes_t = reg.gauge(
            "pixie_table_bytes_total", "Bytes ever appended per table"
        )
        g_exp_t = reg.gauge(
            "pixie_table_expired_bytes_total",
            "Bytes dropped by ring expiry per table",
        )
        g_lag = reg.gauge(
            "pixie_table_watermark_lag_seconds",
            "Now minus the max event-time watermark per table "
            "(ingest staleness; absent without a time index)",
        )
        now_ns = _time.time_ns()
        for name, t in engine.tables.items():
            if t is None:
                continue
            st = t.stats()
            g_rows.labels(table=name).set(st.num_rows)
            if getattr(t, "_tier", None) is not None:
                g_bytes.labels(table=name, tier="hot").set(st.hot_bytes)
                g_bytes.labels(table=name, tier="cold").set(st.cold_bytes)
                g_demote.labels(table=name).set(st.demotions)
                g_evict.labels(table=name).set(st.evictions)
                g_decode.labels(table=name).set(
                    round(st.decode_seconds, 6)
                )
            else:
                # Untiered: hot_bytes/cold_bytes here are the ring's
                # INTERNAL recent/merged split — the whole ring is the
                # hot storage tier.
                g_bytes.labels(table=name, tier="hot").set(st.bytes)
            g_rows_t.labels(table=name).set(st.rows_added)
            g_bytes_t.labels(table=name).set(st.bytes_added)
            g_exp_t.labels(table=name).set(st.bytes_expired)
            if st.watermark >= 0:
                g_lag.labels(table=name).set(
                    round((now_ns - st.watermark) / 1e9, 3)
                )
        reg.gauge(
            "pixie_device_cache_bytes",
            "Device-resident window bytes (all tables)",
        ).set(total_resident_bytes())
        # Window-prefetch pipeline (exec/pipeline.py): lifetime totals of
        # windows executed, producer staging time, and consumer stall
        # time. stall << stage means the overlap is hiding staging cost;
        # stall ~= stage means the device is waiting on the host.
        pt = getattr(engine, "pipeline_totals", None)
        if pt is not None:
            reg.gauge(
                "pixie_pipeline_depth",
                "Configured window-prefetch depth (1 = serial)",
            ).set(getattr(engine, "pipeline_depth", 1))
            reg.gauge(
                "pixie_pipeline_windows_total",
                "Windows executed through the window pipeline",
            ).set(pt["windows"])
            reg.gauge(
                "pixie_pipeline_stage_seconds_total",
                "Prefetch-thread seconds spent staging windows",
            ).set(round(pt["stage_secs"], 6))
            reg.gauge(
                "pixie_pipeline_stall_seconds_total",
                "Query-thread seconds stalled waiting for a window",
            ).set(round(pt["stall_secs"], 6))

    return collect
