"""Agent tracker: registration, heartbeats, expiry, live-state snapshots.

Reference parity: the metadata service's agent manager + topic listener
(``src/vizier/services/metadata/controllers/agent/agent.go:100``,
``agent_topic_listener.go:41,305-322``): agents register and get an ASID,
heartbeat every few seconds, and are expired + deleted after a minute of
silence — at which point the planner stops scheduling to them. Agents
report their table schemas here (the schema-tracker role), which feeds
the query broker's CompilerState.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..planner.distributed import AgentInfo, DistributedState
from .msgbus import MessageBus

TOPIC_REGISTER = "agent.register"
TOPIC_HEARTBEAT = "agent.heartbeat"
TOPIC_EXPIRED = "agent.expired"
TOPIC_QUARANTINED = "agent.quarantined"

DEFAULT_EXPIRY_S = 60.0
DEFAULT_CHECK_INTERVAL_S = 5.0

#: Bound on per-agent flap-history entries kept by the tracker: with
#: ephemeral agent ids (pod-suffixed names churning for weeks) the
#: bookkeeping must not grow without limit.
MAX_FLAP_TRACKED = 1024


class _Record:
    def __init__(self, info: AgentInfo, schemas: dict,
                 table_stats: dict | None = None):
        self.info = info
        self.schemas = schemas  # {table name: Relation}
        # Ingest-sketch summaries ({table: {rows, ndv, zones}}) the
        # agent ships with registration/heartbeats — the broker-side
        # seed for pxbound's predicted costs (admission control).
        self.table_stats = dict(table_stats or {})
        # Cumulative folded-stack profile summary rows the agent ships
        # in heartbeats ({stack, count, qid, script_hash, tenant,
        # phase}; see ingest/profiler.py profile_summary) — replace-on-
        # heartbeat, merged cluster-wide by AgentTracker.profile().
        self.profile: list[dict] = []
        # Cumulative transport-tier summary rows (busstats snapshot
        # shape) the agent ships in register/heartbeats — replace-on-
        # heartbeat, merged cluster-wide by AgentTracker.bus_stats().
        self.bus: list[dict] = []
        self.last_heartbeat = time.monotonic()


class AgentTracker:
    def __init__(
        self,
        bus: MessageBus,
        expiry_s: float = DEFAULT_EXPIRY_S,
        check_interval_s: float = DEFAULT_CHECK_INTERVAL_S,
        flap_threshold: int | None = None,
        flap_window_s: float | None = None,
        quarantine_s: float | None = None,
        passive: bool = False,
    ):
        from ..config import get_flag

        self.bus = bus
        # Passive (standby-mirror) mode, broker HA: observe the
        # register/heartbeat stream and keep the live-agent map warm,
        # but publish NOTHING — the leader's tracker owns registration
        # acks, re-register nudges, expiry/quarantine events, and the
        # mds.agent_status reply. activate() flips this on takeover.
        self.passive = bool(passive)
        self.expiry_s = expiry_s
        self.check_interval_s = check_interval_s
        # Flap detection: an agent expiring `flap_threshold` times within
        # `flap_window_s` is quarantined out of distributed_state()
        # planning for `quarantine_s` — it may re-register and heartbeat
        # (schemas stay visible) but no new queries are scheduled to it
        # until the cooldown passes.
        self.flap_threshold = (
            int(get_flag("agent_flap_threshold"))
            if flap_threshold is None else int(flap_threshold)
        )
        self.flap_window_s = (
            float(get_flag("agent_flap_window_s"))
            if flap_window_s is None else float(flap_window_s)
        )
        self.quarantine_s = (
            float(get_flag("agent_quarantine_s"))
            if quarantine_s is None else float(quarantine_s)
        )
        self._expiry_history: dict[str, deque] = {}
        self._quarantine_until: dict[str, float] = {}  # aid -> monotonic
        self._lock = threading.Lock()
        self._agents: dict[str, _Record] = {}
        self._next_asid = 1
        self._subs = [
            bus.subscribe(TOPIC_REGISTER, self._on_register),
            bus.subscribe(TOPIC_HEARTBEAT, self._on_heartbeat),
            bus.subscribe("mds.agent_status", self._on_agent_status_request),
        ]
        self._stop = threading.Event()
        self._expiry_thread = threading.Thread(target=self._expiry_loop, daemon=True)
        self._expiry_thread.start()

    # -- message handlers ----------------------------------------------------
    def _on_register(self, msg: dict):
        agent_id = msg["agent_id"]
        with self._lock:
            asid = self._next_asid
            self._next_asid += 1
            info = AgentInfo(
                agent_id=agent_id,
                processes_data=msg.get("processes_data", True),
                accepts_remote_sources=msg.get("accepts_remote_sources", False),
                tables=frozenset(msg.get("schemas", {})),
                asid=asid,
            )
            rec = _Record(
                info, dict(msg.get("schemas", {})),
                msg.get("table_stats"),
            )
            rec.bus = list(msg.get("bus") or [])
            self._agents[agent_id] = rec
        if not self.passive:
            self.bus.publish(f"agent.{agent_id}.registered", {"asid": asid})

    def _on_heartbeat(self, msg: dict):
        agent_id = msg["agent_id"]
        with self._lock:
            rec = self._agents.get(agent_id)
            if rec is None:
                # Unknown agent (e.g. expired): tell it to re-register —
                # the reference's heartbeat-NACK resync path
                # (``manager.h:207`` re-register hook).
                if not self.passive:
                    self.bus.publish(f"agent.{agent_id}.reregister", {})
                return
            rec.last_heartbeat = time.monotonic()
            if "table_stats" in msg:
                rec.table_stats = dict(msg["table_stats"] or {})
            if "profile" in msg:
                rec.profile = list(msg["profile"] or [])
            if "bus" in msg:
                rec.bus = list(msg["bus"] or [])
            if "schemas" in msg:
                rec.schemas = dict(msg["schemas"])
                rec.info = AgentInfo(
                    agent_id=rec.info.agent_id,
                    processes_data=rec.info.processes_data,
                    accepts_remote_sources=rec.info.accepts_remote_sources,
                    tables=frozenset(msg["schemas"]),
                    asid=rec.info.asid,
                )

    def has_agent(self, agent_id: str) -> bool:
        """True while ``agent_id`` is registered and unexpired."""
        with self._lock:
            return agent_id in self._agents

    def agents_info(self) -> list:
        """Live-agent status rows (id, asid, kind, heartbeat age, tables)."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "agent_id": aid,
                    "asid": rec.info.asid,
                    "kind": (
                        "kelvin" if rec.info.accepts_remote_sources else "pem"
                    ),
                    "last_heartbeat_s": now - rec.last_heartbeat,
                    "num_tables": len(rec.schemas),
                    "quarantined": (
                        self._quarantine_until.get(aid, 0.0) > now
                    ),
                }
                for aid, rec in sorted(self._agents.items())
            ]

    def _on_agent_status_request(self, msg: dict):
        """MDS stub service for the GetAgentStatus UDTF
        (``md_udtfs_impl.h:258`` hits MDS the same way)."""
        if self.passive:
            return  # the leader's tracker answers
        self.bus.publish(msg["_reply_to"], {"agents": self.agents_info()})

    def activate(self) -> None:
        """Leave passive (standby-mirror) mode: this tracker now OWNS
        the agent lifecycle — registration acks, re-register nudges,
        expiry/quarantine events, status replies (broker-HA takeover)."""
        self.passive = False

    # -- expiry --------------------------------------------------------------
    def _expiry_loop(self):
        while not self._stop.wait(self.check_interval_s):
            self.expire_silent()

    def expire_silent(self) -> list[str]:
        now = time.monotonic()
        expired = []
        with self._lock:
            for aid, rec in list(self._agents.items()):
                if now - rec.last_heartbeat > self.expiry_s:
                    del self._agents[aid]
                    expired.append(aid)
        for aid in expired:
            self._publish_expiry(aid, "expired (silent)")
        return expired

    def force_expire(self, agent_id: str, reason: str = "killed") -> bool:
        """Expire ``agent_id`` NOW, without waiting out the silence
        window — the deterministic failure-detection path used by fault
        injection and by operators reaping a known-dead node. Returns
        True when the agent was registered."""
        with self._lock:
            existed = self._agents.pop(agent_id, None) is not None
        if existed:
            self._publish_expiry(agent_id, reason)
        return existed

    def _publish_expiry(self, agent_id: str, reason: str) -> None:
        """Flap bookkeeping + the ``agent.expired`` event every query
        subscriber (broker, forwarder) keys failover on."""
        now = time.monotonic()
        quarantined = False
        with self._lock:
            hist = self._expiry_history.setdefault(agent_id, deque())
            hist.append(now)
            while hist and now - hist[0] > self.flap_window_s:
                hist.popleft()
            if (
                len(hist) >= self.flap_threshold
                and self._quarantine_until.get(agent_id, 0.0) <= now
            ):
                self._quarantine_until[agent_id] = now + self.quarantine_s
                quarantined = True
            # Bound the bookkeeping: drop histories whose window has
            # fully lapsed (agents that died and never came back) and
            # lapsed quarantines — insertion order approximates LRU for
            # any overflow beyond that.
            if len(self._expiry_history) > MAX_FLAP_TRACKED:
                for aid, h in list(self._expiry_history.items()):
                    if aid == agent_id:
                        continue
                    if not h or now - h[-1] > self.flap_window_s:
                        del self._expiry_history[aid]
                    if len(self._expiry_history) <= MAX_FLAP_TRACKED:
                        break
                while len(self._expiry_history) > MAX_FLAP_TRACKED:
                    self._expiry_history.pop(
                        next(iter(self._expiry_history))
                    )
            for aid, until in list(self._quarantine_until.items()):
                if until <= now:
                    del self._quarantine_until[aid]
        if self.passive:
            return  # mirror bookkeeping only; the leader emits events
        self.bus.publish(TOPIC_EXPIRED, {"agent_id": agent_id,
                                         "reason": reason})
        if quarantined:
            self._count_quarantine(agent_id)
            self.bus.publish(
                TOPIC_QUARANTINED,
                {"agent_id": agent_id, "cooldown_s": self.quarantine_s},
            )

    def _count_quarantine(self, agent_id: str) -> None:
        from .observability import default_counter

        # Deliberately unlabeled: ephemeral agent ids would be an
        # unbounded label cardinality on a long-lived broker. The
        # WHICH is on the agent.quarantined event + /statusz.
        default_counter(
            "pixie_agent_quarantined_total",
            "Flapping agents quarantined out of query planning",
        ).inc()

    # -- quarantine ----------------------------------------------------------
    def is_quarantined(self, agent_id: str) -> bool:
        with self._lock:
            return self._quarantine_until.get(agent_id, 0.0) > time.monotonic()

    def quarantined(self) -> dict[str, float]:
        """{agent_id: cooldown remaining (s)} for active quarantines;
        lapsed entries are dropped."""
        now = time.monotonic()
        with self._lock:
            for aid, until in list(self._quarantine_until.items()):
                if until <= now:
                    del self._quarantine_until[aid]
            return {
                aid: round(until - now, 3)
                for aid, until in self._quarantine_until.items()
            }

    # -- queries -------------------------------------------------------------
    def distributed_state(self) -> DistributedState:
        now = time.monotonic()
        with self._lock:
            agents, quarantined = [], []
            for aid, rec in self._agents.items():
                if self._quarantine_until.get(aid, 0.0) > now:
                    quarantined.append(aid)
                else:
                    agents.append(rec.info)
            return DistributedState(
                agents=agents, quarantined=sorted(quarantined)
            )

    def schemas(self) -> dict:
        """Union of table schemas across live agents."""
        out: dict = {}
        with self._lock:
            for rec in self._agents.values():
                out.update(rec.schemas)
        return out

    def table_stats(self) -> dict:
        """Cluster-wide per-table summary, merged with per-field
        semantics (each agent holds a disjoint shard):

        - sketch fields — ``rows`` summed, per-column NDV summed (an
          upper bound: per-agent HLL registers don't cross the
          heartbeat, so the sums can't dedup values shared between
          agents), zone bounds unioned. Emitted only when at least one
          agent actually shipped sketch data for the table: a table
          known only through its freshness record must stay UNBOUNDED
          to pxbound (a synthesized ``rows: 0`` would be an unsound
          known-zero bound).
        - ``freshness`` — monotonic counters (``rows_total``,
          ``bytes_total``, ``expired_*``) and live sizes SUM, the
          event-time ``watermark`` and ``last_append`` take the MAX,
          ``min_time`` the min; plus ``agents`` (contributing agent
          count) and ``watermark_spread_ns`` (max - min of per-agent
          watermarks — the "which PEM is behind" lag spread).

        Feeds the broker's CompilerState so pxbound's predicted costs
        (and the planner's NDV sizing) work cluster-wide, and
        ``/debug/tablez`` + the bundled storage scripts cluster-merged.
        """
        from ..table_store.table_store import merge_freshness

        out: dict = {}
        agent_wms: dict[str, list] = {}  # table -> per-agent watermarks
        with self._lock:
            records = [rec.table_stats for rec in self._agents.values()]
        for stats in records:
            for table, st in (stats or {}).items():
                if not isinstance(st, dict):
                    continue
                cur = out.setdefault(table, {})
                if "rows" in st:
                    cur.setdefault("rows", 0)
                    cur.setdefault("ndv", {})
                    cur.setdefault("zones", {})
                    cur["rows"] += int(st.get("rows", 0) or 0)
                    for c, v in (st.get("ndv") or {}).items():
                        cur["ndv"][c] = cur["ndv"].get(c, 0) + int(v)
                    for c, z in (st.get("zones") or {}).items():
                        lo, hi = z[0], z[1]
                        if c in cur["zones"]:
                            plo, phi = cur["zones"][c]
                            lo, hi = min(plo, lo), max(phi, hi)
                        cur["zones"][c] = (lo, hi)
                fresh = st.get("freshness")
                if isinstance(fresh, dict):
                    cur["freshness"] = merge_freshness(
                        cur.get("freshness"), fresh
                    )
                    cur["freshness"]["agents"] = (
                        cur["freshness"].get("agents", 0) + 1
                    )
                    wm = int(fresh.get("watermark", -1))
                    if wm >= 0:
                        agent_wms.setdefault(table, []).append(wm)
        for table, st in out.items():
            if "ndv" in st:
                # NDV can never exceed the row count.
                st["ndv"] = {
                    c: min(v, st["rows"])
                    for c, v in st["ndv"].items() if v
                }
            wms = agent_wms.get(table)
            if wms and "freshness" in st:
                st["freshness"]["watermark_spread_ns"] = (
                    max(wms) - min(wms)
                )
        return out

    def table_freshness(self) -> dict:
        """{table: merged freshness} view of :meth:`table_stats` — the
        ``/debug/tablez`` payload on a broker."""
        return {
            table: st["freshness"]
            for table, st in self.table_stats().items()
            if "freshness" in st
        }

    def profile(
        self,
        agent_id: str | None = None,
        tenant: str | None = None,
        script_hash: str | None = None,
    ) -> list[dict]:
        """Cluster-merged folded-stack profile: each agent's latest
        heartbeat summary, counts summed across agents per (stack,
        attribution) key — the /debug/pprof and `px profile` source.
        Filters narrow to one agent / tenant / script hash; merged rows
        come back hottest first."""
        with self._lock:
            summaries = [
                (aid, list(rec.profile))
                for aid, rec in self._agents.items()
                if rec.profile and (agent_id is None or aid == agent_id)
            ]
        merged: dict[tuple, int] = {}
        for _aid, rows in summaries:
            for r in rows:
                if tenant is not None and r.get("tenant", "") != tenant:
                    continue
                if (script_hash is not None
                        and r.get("script_hash", "") != script_hash):
                    continue
                key = (
                    r.get("stack", ""), r.get("qid", ""),
                    r.get("script_hash", ""), r.get("tenant", ""),
                    r.get("phase", ""),
                )
                if not key[0]:
                    continue
                merged[key] = merged.get(key, 0) + int(r.get("count", 0))
        rows = [
            {
                "stack": k[0], "count": n, "qid": k[1],
                "script_hash": k[2], "tenant": k[3], "phase": k[4],
            }
            for k, n in merged.items()
        ]
        rows.sort(key=lambda r: (-r["count"], r["stack"]))
        return rows

    def profile_agents(self) -> list[str]:
        """Agents whose latest heartbeat carried a profile summary."""
        with self._lock:
            return sorted(
                aid for aid, rec in self._agents.items() if rec.profile
            )

    def bus_stats(self) -> dict:
        """Cluster-merged transport tier: each agent's latest heartbeat
        bus summary, merged per (kind, topic_class, direction) key —
        counters summed, queue high-water maxed, and the lag/service
        quantiles taken as the MAX across agents (a worst-participant
        view: cross-agent histogram merge would need the buckets, which
        heartbeats deliberately don't ship). The /debug/busz source."""
        with self._lock:
            agents = {
                aid: [dict(r) for r in rec.bus]
                for aid, rec in self._agents.items()
                if rec.bus
            }
        merged: dict[tuple, dict] = {}
        for rows in agents.values():
            for r in rows:
                key = (
                    r.get("kind", ""), r.get("topic_class", ""),
                    r.get("direction", ""),
                )
                m = merged.get(key)
                if m is None:
                    merged[key] = dict(r)
                    continue
                for f in ("msgs", "bytes", "errors"):
                    m[f] = int(m.get(f, 0)) + int(r.get(f, 0))
                for f in ("lag_p50_ms", "lag_p99_ms",
                          "service_p50_ms", "service_p99_ms"):
                    m[f] = max(float(m.get(f, 0.0)), float(r.get(f, 0.0)))
                m["queue_high_water"] = max(
                    int(m.get("queue_high_water", 0)),
                    int(r.get("queue_high_water", 0)),
                )
        out = sorted(
            merged.values(),
            key=lambda r: (r["kind"], r["topic_class"], r["direction"]),
        )
        return {"agents": agents, "merged": out}

    def agent_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._agents)

    def close(self):
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
