"""Agent tracker: registration, heartbeats, expiry, live-state snapshots.

Reference parity: the metadata service's agent manager + topic listener
(``src/vizier/services/metadata/controllers/agent/agent.go:100``,
``agent_topic_listener.go:41,305-322``): agents register and get an ASID,
heartbeat every few seconds, and are expired + deleted after a minute of
silence — at which point the planner stops scheduling to them. Agents
report their table schemas here (the schema-tracker role), which feeds
the query broker's CompilerState.
"""

from __future__ import annotations

import threading
import time

from ..planner.distributed import AgentInfo, DistributedState
from .msgbus import MessageBus

TOPIC_REGISTER = "agent.register"
TOPIC_HEARTBEAT = "agent.heartbeat"
TOPIC_EXPIRED = "agent.expired"

DEFAULT_EXPIRY_S = 60.0
DEFAULT_CHECK_INTERVAL_S = 5.0


class _Record:
    def __init__(self, info: AgentInfo, schemas: dict):
        self.info = info
        self.schemas = schemas  # {table name: Relation}
        self.last_heartbeat = time.monotonic()


class AgentTracker:
    def __init__(
        self,
        bus: MessageBus,
        expiry_s: float = DEFAULT_EXPIRY_S,
        check_interval_s: float = DEFAULT_CHECK_INTERVAL_S,
    ):
        self.bus = bus
        self.expiry_s = expiry_s
        self.check_interval_s = check_interval_s
        self._lock = threading.Lock()
        self._agents: dict[str, _Record] = {}
        self._next_asid = 1
        self._subs = [
            bus.subscribe(TOPIC_REGISTER, self._on_register),
            bus.subscribe(TOPIC_HEARTBEAT, self._on_heartbeat),
            bus.subscribe("mds.agent_status", self._on_agent_status_request),
        ]
        self._stop = threading.Event()
        self._expiry_thread = threading.Thread(target=self._expiry_loop, daemon=True)
        self._expiry_thread.start()

    # -- message handlers ----------------------------------------------------
    def _on_register(self, msg: dict):
        agent_id = msg["agent_id"]
        with self._lock:
            asid = self._next_asid
            self._next_asid += 1
            info = AgentInfo(
                agent_id=agent_id,
                processes_data=msg.get("processes_data", True),
                accepts_remote_sources=msg.get("accepts_remote_sources", False),
                tables=frozenset(msg.get("schemas", {})),
                asid=asid,
            )
            self._agents[agent_id] = _Record(info, dict(msg.get("schemas", {})))
        self.bus.publish(f"agent.{agent_id}.registered", {"asid": asid})

    def _on_heartbeat(self, msg: dict):
        agent_id = msg["agent_id"]
        with self._lock:
            rec = self._agents.get(agent_id)
            if rec is None:
                # Unknown agent (e.g. expired): tell it to re-register —
                # the reference's heartbeat-NACK resync path
                # (``manager.h:207`` re-register hook).
                self.bus.publish(f"agent.{agent_id}.reregister", {})
                return
            rec.last_heartbeat = time.monotonic()
            if "schemas" in msg:
                rec.schemas = dict(msg["schemas"])
                rec.info = AgentInfo(
                    agent_id=rec.info.agent_id,
                    processes_data=rec.info.processes_data,
                    accepts_remote_sources=rec.info.accepts_remote_sources,
                    tables=frozenset(msg["schemas"]),
                    asid=rec.info.asid,
                )

    def has_agent(self, agent_id: str) -> bool:
        """True while ``agent_id`` is registered and unexpired."""
        with self._lock:
            return agent_id in self._agents

    def agents_info(self) -> list:
        """Live-agent status rows (id, asid, kind, heartbeat age, tables)."""
        now = time.monotonic()
        with self._lock:
            return [
                {
                    "agent_id": aid,
                    "asid": rec.info.asid,
                    "kind": (
                        "kelvin" if rec.info.accepts_remote_sources else "pem"
                    ),
                    "last_heartbeat_s": now - rec.last_heartbeat,
                    "num_tables": len(rec.schemas),
                }
                for aid, rec in sorted(self._agents.items())
            ]

    def _on_agent_status_request(self, msg: dict):
        """MDS stub service for the GetAgentStatus UDTF
        (``md_udtfs_impl.h:258`` hits MDS the same way)."""
        self.bus.publish(msg["_reply_to"], {"agents": self.agents_info()})

    # -- expiry --------------------------------------------------------------
    def _expiry_loop(self):
        while not self._stop.wait(self.check_interval_s):
            self.expire_silent()

    def expire_silent(self) -> list[str]:
        now = time.monotonic()
        expired = []
        with self._lock:
            for aid, rec in list(self._agents.items()):
                if now - rec.last_heartbeat > self.expiry_s:
                    del self._agents[aid]
                    expired.append(aid)
        for aid in expired:
            self.bus.publish(TOPIC_EXPIRED, {"agent_id": aid})
        return expired

    # -- queries -------------------------------------------------------------
    def distributed_state(self) -> DistributedState:
        with self._lock:
            return DistributedState(agents=[r.info for r in self._agents.values()])

    def schemas(self) -> dict:
        """Union of table schemas across live agents."""
        out: dict = {}
        with self._lock:
            for rec in self._agents.values():
                out.update(rec.schemas)
        return out

    def agent_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._agents)

    def close(self):
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
