"""Service-context UDTFs: cluster state via control-plane requests.

Reference parity: ``src/vizier/funcs/`` — the vizier-level UDTF registry
whose funcs hold gRPC stubs into the metadata service
(``md_udtfs_impl.h:258`` GetAgentStatus). Here the stub is a bus
request/reply to the agent tracker's MDS topic.
"""

from __future__ import annotations

from ..types.dtypes import DataType
from ..udf.udtf import UDTFExecutor
from .msgbus import MessageBus

S = DataType.STRING
I = DataType.INT64
F = DataType.FLOAT64


# Names owned by this module; bind_service_registry excludes them before
# re-registering so clones never collide.
SERVICE_UDTF_NAMES = ("GetAgentStatus",)


def bind_service_registry(registry, bus: MessageBus, name: str):
    """Clone ``registry`` and (re)bind every service UDTF to ``bus``.
    The one place that knows the service UDTF name list."""
    reg = registry.clone(name, exclude=SERVICE_UDTF_NAMES)
    register_vizier_udtfs(reg, bus)
    return reg


def register_vizier_udtfs(registry, bus: MessageBus) -> None:
    """Bind service UDTFs to a control-plane connection. Called by agents
    at startup (the VizierFuncFactoryContext analog)."""

    def _get_agent_status(engine):
        reply = bus.request("mds.agent_status", {}, timeout_s=5.0)
        rows = reply["agents"]
        return {
            "agent_id": [r["agent_id"] for r in rows],
            "asid": [r["asid"] for r in rows],
            "kind": [r["kind"] for r in rows],
            "last_heartbeat_s": [r["last_heartbeat_s"] for r in rows],
            "num_tables": [r["num_tables"] for r in rows],
        }

    registry.udtf(
        "GetAgentStatus",
        [
            ("agent_id", S),
            ("asid", I),
            ("kind", S),
            ("last_heartbeat_s", F),
            ("num_tables", I),
        ],
        _get_agent_status,
        executor=UDTFExecutor.ONE_KELVIN,
        doc="Live agents with heartbeat ages, from the metadata tracker.",
    )
