"""Fatal-signal and crash handling for deployed roles.

Reference parity: ``src/common/signal/signal_action.h`` (Envoy-style
SignalAction: install handlers for fatal signals, dump a backtrace,
invoke registered FatalErrorHandlers) and ``fatal_handler.h``
(FatalErrorHandlerInterface). The Python analog rests on
``faulthandler`` for the hard faults (SIGSEGV/SIGBUS/SIGFPE/SIGILL/
SIGABRT dump every thread's stack, even with the GIL wedged) and on
``sys.excepthook``/``threading.excepthook`` for uncaught exceptions;
both paths run the registered fatal handlers (last-gasp flushes) and
leave a timestamped crash log next to the process.
"""

from __future__ import annotations

import datetime
import faulthandler
import io
import os
import signal
import sys
import threading
import traceback
from typing import Callable, Optional

_lock = threading.Lock()
_fatal_handlers: list = []
_crash_file: Optional[io.TextIOWrapper] = None
_installed = False


def register_fatal_handler(fn: Callable[[], None]) -> None:
    """SignalAction::RegisterFatalErrorHandler analog: ``fn`` runs (best
    effort, exceptions swallowed) on uncaught exceptions and graceful
    SIGTERM teardown. Hard faults dump stacks only — arbitrary Python
    can't run on a corrupted interpreter, matching the reference's
    signal-safety constraints."""
    with _lock:
        _fatal_handlers.append(fn)


def run_fatal_handlers() -> None:
    """Public last-gasp trigger for roles that own their SIGTERM
    teardown (deploy._wait_forever) — runs every registered handler,
    best effort."""
    _run_fatal_handlers()


def _run_fatal_handlers() -> None:
    with _lock:
        handlers = list(_fatal_handlers)
    for fn in handlers:
        try:
            fn()
        except Exception:
            pass


def _stamp(kind: str) -> str:
    now = datetime.datetime.now(datetime.timezone.utc).isoformat()
    return f"=== pixie_tpu crash [{kind}] pid={os.getpid()} at {now} ===\n"


def install(
    crash_log_path: Optional[str] = None,
    role: str = "",
    sigterm_exits: bool = True,
) -> None:
    """Install the process-wide crash machinery (idempotent).

    - faulthandler on a crash-log file (+ stderr) for hard faults
    - excepthooks recording uncaught exceptions and running fatal
      handlers
    - a SIGTERM handler that runs fatal handlers then exits 0 (the
    clean k8s teardown path the reference services share)
    """
    global _crash_file, _installed
    if _installed:
        return
    _installed = True

    path = crash_log_path or os.environ.get(
        "PIXIE_TPU_CRASH_LOG", f"crash_{role or 'process'}.log"
    )
    try:
        _crash_file = open(path, "a", buffering=1)
    except OSError:
        _crash_file = None
    # faulthandler accepts ONE file: prefer the log (stderr may be gone
    # under a supervisor); it dumps all thread stacks on hard faults.
    faulthandler.enable(file=_crash_file or sys.stderr, all_threads=True)

    prev_except = sys.excepthook

    def excepthook(tp, val, tb):
        if _crash_file is not None:
            _crash_file.write(_stamp("uncaught-exception"))
            traceback.print_exception(tp, val, tb, file=_crash_file)
        _run_fatal_handlers()
        prev_except(tp, val, tb)

    sys.excepthook = excepthook

    prev_thread_except = threading.excepthook

    def thread_excepthook(args):
        if _crash_file is not None:
            _crash_file.write(
                _stamp(f"thread-exception:{args.thread.name if args.thread else '?'}")
            )
            traceback.print_exception(
                args.exc_type, args.exc_value, args.exc_traceback,
                file=_crash_file,
            )
        _run_fatal_handlers()
        prev_thread_except(args)

    threading.excepthook = thread_excepthook

    if sigterm_exits:

        def on_sigterm(signum, frame):
            if _crash_file is not None:
                _crash_file.write(_stamp("sigterm"))
            _run_fatal_handlers()
            sys.exit(0)

        try:
            signal.signal(signal.SIGTERM, on_sigterm)
        except ValueError:
            pass  # non-main thread (tests): faulthandler still active


