"""Socket transport for the message bus: agents in separate processes.

Reference parity: the control plane is NATS pub/sub with protobuf
envelopes (``src/common/event/nats.h:36-60``; ``launch_query.go:36``) and
the data plane is gRPC streaming (``grpc_router.{h,cc}``). Here one
framed-TCP layer carries both: a ``BusServer`` wraps the broker-side
in-process ``MessageBus`` and remote ``RemoteBus`` clients mirror the bus
API (subscribe/publish), with every frame encoded by the versioned wire
codec (``wire.py``) — no pickle crosses the socket.

Frames: 4-byte little-endian length + wire-encoded dict
{"op": "pub"|"sub"|"unsub", "topic": str, "msg": ...?, "sid": int?}.

Wire telemetry (``bus_telemetry`` flag, services/busstats.py): both
endpoints count frames/bytes per peer and direction off ``_send_frame``
/ ``_recv_frame_sized`` returns, request RTTs, send-stall time under
the send lock, and connect/drop/auth-failure events — the cluster's
wire-byte ground truth, served via ``busz()`` / ``/debug/busz``.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque

from ..config import get_flag
from ..exec import tracectx
from .busstats import BusStats, HANDLER_ERROR_RING, topic_class
from .msgbus import BusTimeout, MessageBus
from .wire import WireError, decode, encode

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


def _harden_socket(sock: socket.socket, send_timeout_s: int = 10) -> None:
    """Transport hardening for both bus endpoints:

    - SO_SNDTIMEO bounds blocking sends so a peer that stops READING
      can't park a sender inside its send lock forever (the timeout
      surfaces as TimeoutError ⊂ OSError and the caller reaps).
    - SO_KEEPALIVE (+ aggressive probe knobs where available) detects
      half-open connections — a peer HOST that died without FIN would
      otherwise leave recv() blocked forever now that reads are
      unbounded (idle is normal on this bus).
    """
    # Every knob best-effort: hardening must never take a connection
    # (or the server's accept loop) down — platforms vary in timeval
    # layout and option support.
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDTIMEO,
            struct.pack("ll", send_timeout_s, 0),
        )
    except OSError:
        pass
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    except OSError:
        pass
    for opt, val in (
        ("TCP_KEEPIDLE", 30), ("TCP_KEEPINTVL", 10), ("TCP_KEEPCNT", 3),
    ):
        if hasattr(socket, opt):
            try:
                sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)
            except OSError:
                pass


def _send_frame(sock: socket.socket, obj) -> int:
    """Encode + send one frame; returns the wire bytes written (length
    prefix included) so callers can account without re-encoding."""
    payload = encode(obj)
    if len(payload) > MAX_FRAME:
        # Fail the PUBLISHER visibly; an oversize frame on the wire would
        # instead kill the receiver's connection and silently drop all of
        # its subscriptions.
        raise ValueError(
            f"frame of {len(payload)} bytes exceeds MAX_FRAME {MAX_FRAME}; "
            "chunk the payload"
        )
    sock.sendall(_LEN.pack(len(payload)) + payload)
    return _LEN.size + len(payload)


#: Required keys per frame op (both directions share the codec).
_FRAME_KEYS = {
    "pub": ("topic", "msg"),
    "sub": ("topic", "sid"),
    "unsub": ("sid",),
    "msg": ("sid", "msg"),
}


def _recv_frame(sock: socket.socket):
    return _recv_frame_sized(sock)[0]


def _recv_frame_sized(sock: socket.socket):
    """One frame off the wire as ``(frame | None, wire_bytes)`` — the
    byte count feeds the per-peer recv accounting."""
    header = _recv_exact(sock, 4)
    if header is None:
        return None, 0
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise ConnectionError(f"frame of {n} bytes exceeds limit")
    payload = _recv_exact(sock, n)
    if payload is None:
        return None, _LEN.size
    frame = decode(payload)
    # Schema gate at the frame boundary: a frame that decodes but has
    # the wrong SHAPE (non-dict, non-str op/topic, non-int sid) is just
    # as malformed as undecodable bytes — fail it here as WireError so
    # the read loops keep their narrow except lists and no handler runs
    # on hostile input (e.g. bus.subscribe before an unhashable-sid
    # lookup raised would leak the subscription forever).
    if not isinstance(frame, dict):
        raise WireError(f"frame is {type(frame).__name__}, not a dict")
    op = frame.get("op")
    if not isinstance(op, str):
        raise WireError("frame has no string 'op'")
    required = _FRAME_KEYS.get(op, ())
    for key in required:
        if key not in frame:
            raise WireError(f"'{op}' frame missing {key!r}")
    if "topic" in frame and not isinstance(frame["topic"], str):
        raise WireError("frame 'topic' is not a string")
    if "sid" in frame and not isinstance(frame["sid"], int):
        raise WireError("frame 'sid' is not an int")
    return frame, _LEN.size + n


def _recv_exact(sock: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class BusServer:
    """Bridges a local MessageBus to remote RemoteBus clients.

    With ``secret`` set (or the ``bus_secret`` flag), a client's FIRST
    frame must be ``{"op": "auth", "token": ...}`` carrying a valid
    bearer token (``auth.sign_token``); anything else closes the
    connection — the netbus trust boundary (the reference checks JWT
    claims at every gRPC service edge, authcontext/context.go:38).
    """

    def __init__(self, bus: MessageBus, host: str = "127.0.0.1", port: int = 0,
                 secret: str | None = None):
        self.bus = bus
        self.secret = get_flag("bus_secret") if secret is None else secret
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._clients: list = []
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._accept_loop, name="busserver", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            try:
                # Same hardening as the client side: bounded sends (a
                # non-reading client can't park forwarders in sendall —
                # the TimeoutError ⊂ OSError path in _send closes and
                # drops its subscriptions) + keepalive for half-open
                # peers. Per-client setup failure drops THAT client,
                # never the acceptor.
                _harden_socket(sock)
                client = _ClientConn(self, sock)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            with self._lock:
                self._clients.append(client)
            client.start()

    def _drop(self, client) -> None:
        with self._lock:
            if client in self._clients:
                self._clients.remove(client)

    def busz(self) -> list[dict]:
        """Per-connection wire accounting for ``/debug/busz`` (the
        metric-label peer is the auth subject; THIS is where individual
        connections stay distinguishable)."""
        with self._lock:
            clients = list(self._clients)
        out = []
        for c in clients:
            try:
                addr = c.sock.getpeername()
                remote = f"{addr[0]}:{addr[1]}"
            except OSError:
                remote = "?"
            out.append({
                "remote": remote,
                "peer": c.peer,
                "subscriptions": len(c._subs),
                **c._sent_counts,
                **c._recv_counts,
            })
        return out

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            c.close()


class _ClientConn:
    """Server-side state for one remote client."""

    def __init__(self, server: BusServer, sock: socket.socket):
        self.server = server
        self.sock = sock
        # Per-peer wire accounting: the metric-label peer is the auth
        # subject ("anon" without a secret — bounded cardinality); the
        # per-connection detail below feeds BusServer.busz() only.
        self.peer = "client"
        # Split by writer thread: sent counters mutate under _send_lock
        # (any dispatcher thread may _send), recv counters only on the
        # read-loop thread — no cross-thread writes to either dict.
        self._sent_counts = {"frames_sent": 0, "bytes_sent": 0}
        self._recv_counts = {"frames_recv": 0, "bytes_recv": 0}
        self._send_lock = threading.Lock()
        # Guards _subs + _closed: close() can run from any subscription
        # dispatcher thread (via a _send failure) while the read loop
        # registers new subscriptions — an unlocked insert racing close
        # would leak that subscription's dispatcher thread forever.
        self._subs_lock = threading.Lock()
        self._closed = False
        self._subs: dict[int, object] = {}  # sid -> Subscription
        self.auth_ctx = None  # AuthContext once authenticated
        self._thread = threading.Thread(
            target=self._read_loop, name="busserver-client", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def _read_loop(self) -> None:
        from .auth import ANONYMOUS, AuthError, verify_token

        st = self.server.bus.stats
        try:
            if self.server.secret:
                # Authentication handshake gates EVERYTHING else.
                frame = self._recv_counted(st)
                if frame is None or frame.get("op") != "auth":
                    if st is not None:
                        st.on_conn_event(self.peer, "auth_failure")
                    self._send({"op": "auth_err", "error": "auth required"})
                    return
                try:
                    self.auth_ctx = verify_token(
                        self.server.secret, frame.get("token")
                    )
                except AuthError as e:
                    if st is not None:
                        st.on_conn_event(self.peer, "auth_failure")
                    self._send({"op": "auth_err", "error": str(e)})
                    return
                self.peer = self.auth_ctx.subject or "anon"
                self._send({"op": "auth_ok", "sub": self.auth_ctx.subject})
            else:
                self.auth_ctx = ANONYMOUS
                self.peer = "anon"
            if st is not None:
                st.on_conn_event(self.peer, "connect")
            while True:
                frame = self._recv_counted(st)
                if frame is None:
                    break
                op = frame.get("op")
                if op == "auth":
                    # Token offered to a no-secret server (or re-auth):
                    # acknowledge so the client handshake completes.
                    self._send({"op": "auth_ok", "sub": ""})
                    continue
                if op == "pub":
                    self.server.bus.publish(frame["topic"], frame["msg"])
                elif op == "sub":
                    sid, topic = frame["sid"], frame["topic"]

                    def fwd(msg, _sid=sid, _topic=topic):
                        self._send({"op": "msg", "sid": _sid, "msg": msg})

                    sub = self.server.bus.subscribe(topic, fwd)
                    with self._subs_lock:
                        if self._closed:
                            pass  # lost the race; unsubscribe below
                        else:
                            self._subs[sid] = sub
                            sub = None
                    if sub is not None:
                        sub.unsubscribe()
                elif op == "unsub":
                    with self._subs_lock:
                        sub = self._subs.pop(frame["sid"], None)
                    if sub is not None:
                        sub.unsubscribe()
        except (ConnectionError, OSError, WireError):
            # WireError covers corrupted bytes AND wrong-schema frames
            # (validated in _recv_frame) — drop the connection; real
            # handler bugs still raise visibly.
            if st is not None:
                st.on_conn_event(self.peer, "drop")
        finally:
            self.close()

    def _recv_counted(self, st):
        frame, nb = _recv_frame_sized(self.sock)
        if nb:
            # Single writer: only the read-loop thread touches the
            # recv counters (the send pair lives under _send_lock).
            self._recv_counts["frames_recv"] += 1
            self._recv_counts["bytes_recv"] += nb
            if st is not None:
                st.on_frame(self.peer, "recv", nb)
        return frame

    def _send(self, obj) -> None:
        st = self.server.bus.stats
        try:
            t0 = time.monotonic()
            with self._send_lock:
                stall_s = time.monotonic() - t0
                n = _send_frame(self.sock, obj)
                self._sent_counts["frames_sent"] += 1
                self._sent_counts["bytes_sent"] += n
            if st is not None:
                st.on_send_stall(self.peer, stall_s)
                st.on_frame(self.peer, "send", n)
        except (ConnectionError, OSError):
            if st is not None:
                st.on_conn_event(self.peer, "drop")
            self.close()

    def close(self) -> None:
        with self._subs_lock:
            self._closed = True
            subs = list(self._subs.values())
            self._subs.clear()
        for sub in subs:
            sub.unsubscribe()
        try:
            self.sock.close()
        except OSError:
            pass
        self.server._drop(self)


class _RemoteSubscription:
    """One remote subscription with its own dispatcher thread (mirrors
    msgbus.Subscription: a slow handler must not block other handlers or
    the socket read loop — e.g. query execution vs. cancellation)."""

    _SENTINEL = object()

    def __init__(self, bus: "RemoteBus", sid: int, fn, topic: str = ""):
        import queue as _queue

        self._bus = bus
        self._sid = sid
        self._fn = fn
        self.topic = topic
        self._cls = topic_class(topic) if topic else "?"
        self._hw = 0
        self._q: "_queue.Queue" = _queue.Queue()
        self._thread = threading.Thread(
            target=self._run, name=f"remotebus-sub-{sid}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        st = self._bus.stats
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            if st is not None:
                msg, enq_t = item
                lag_s = time.monotonic() - enq_t
                t0 = time.monotonic()
            else:
                msg = item
            err = False
            try:
                # Same envelope binding as msgbus.Subscription: the
                # distributed trace context survives the TCP hop (the
                # wire codec carries the _trace_ctx dict unchanged).
                with tracectx.bound(tracectx.extract(msg)):
                    self._fn(msg)
            except Exception as e:  # handler errors never kill the dispatcher
                err = True
                self._bus._on_handler_error(self.topic, e)
            if st is not None:
                st.on_handled(
                    self._cls, self.topic, lag_s,
                    time.monotonic() - t0, error=err,
                )

    def _deliver(self, msg) -> None:
        st = self._bus.stats
        if st is not None:
            depth = self._q.qsize() + 1
            if depth > self._hw:
                self._hw = depth
            st.on_deliver(self._cls, 0, depth)
            self._q.put((msg, time.monotonic()))
        else:
            self._q.put(msg)

    def unsubscribe(self) -> None:
        self._bus._unsubscribe(self._sid)
        self._q.put(self._SENTINEL)


class RemoteBus:
    """Client-side bus mirror: same subscribe/publish surface as
    MessageBus, carried over one TCP connection to a BusServer."""

    def __init__(self, host: str, port: int, connect_timeout_s: float = 10.0,
                 token: str | None = None):
        # Wire accounting peer label: the broker endpoint this client
        # dialed (config-bounded cardinality — one broker per deploy).
        # host/port kept separately so HA clients can re-dial the same
        # (or a failover) endpoint after a broker death (api.Client).
        self.host = host
        self.port = port
        self.peer = f"{host}:{port}"
        self.stats: BusStats | None = (
            BusStats() if get_flag("bus_telemetry") else None
        )
        self.handler_errors: deque = deque(maxlen=HANDLER_ERROR_RING)
        self._handler_errors_total = 0
        self.sock = socket.create_connection((host, port), connect_timeout_s)
        # create_connection leaves its timeout ARMED on the socket; the
        # read loop would then treat any 10s-idle connection as dead
        # (TimeoutError ⊂ OSError) and silently self-close — streams
        # with a stalled producer died exactly this way. Receives must
        # block forever (idle is normal); SENDS stay bounded via
        # SO_SNDTIMEO so a wedged server can't hang publishers inside
        # _send_lock.
        self.sock.settimeout(None)
        _harden_socket(self.sock, send_timeout_s=max(int(connect_timeout_s), 1))
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._handlers: dict[int, object] = {}  # sid -> callable
        self._next_sid = 1
        self._closed = threading.Event()
        # Optional faults.FaultInjector consulted on every publish
        # (mirrors MessageBus.fault_injector; netbus frames are the
        # injection point for remote-agent fault tests).
        self.fault_injector = None
        # Mint a token from the shared secret when the caller brings
        # none (deploy processes share the bus_secret flag/env).
        if token is None and get_flag("bus_secret"):
            from .auth import sign_token

            token = sign_token(get_flag("bus_secret"), "remotebus")
        if token:
            # Handshake BEFORE the read loop owns the socket: the server
            # answers auth_ok or auth_err+close, so a bad token fails
            # loudly at connect instead of silently dropping frames.
            self.sock.settimeout(connect_timeout_s)
            n = _send_frame(self.sock, {"op": "auth", "token": token})
            if self.stats is not None:
                self.stats.on_frame(self.peer, "send", n)
            reply, nb = _recv_frame_sized(self.sock)
            if self.stats is not None and nb:
                self.stats.on_frame(self.peer, "recv", nb)
            if not (isinstance(reply, dict) and reply.get("op") == "auth_ok"):
                err = (reply or {}).get("error", "connection closed")
                if self.stats is not None:
                    self.stats.on_conn_event(self.peer, "auth_failure")
                self.sock.close()
                raise ConnectionError(f"netbus auth failed: {err}")
            self.sock.settimeout(None)
        if self.stats is not None:
            self.stats.on_conn_event(self.peer, "connect")
        self._thread = threading.Thread(
            target=self._read_loop, name="remotebus", daemon=True
        )
        self._thread.start()

    def subscribe(self, topic: str, fn) -> _RemoteSubscription:
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            sub = _RemoteSubscription(self, sid, fn, topic=topic)
            self._handlers[sid] = sub
        self._send({"op": "sub", "topic": topic, "sid": sid})
        return sub

    def publish(self, topic: str, msg: dict) -> int:
        if self.stats is not None:
            self.stats.on_publish(topic, msg)
        msg = tracectx.attach(msg)  # envelope parity with MessageBus
        inj = self.fault_injector
        if inj is not None:
            for delay_s in inj.intercept(topic, msg):
                if delay_s <= 0:
                    self._send({"op": "pub", "topic": topic, "msg": msg})
                else:
                    t = threading.Timer(
                        delay_s, self._send,
                        ({"op": "pub", "topic": topic, "msg": msg},),
                    )
                    t.daemon = True
                    t.start()
            return 1
        self._send({"op": "pub", "topic": topic, "msg": msg})
        return 1

    def sever(self) -> None:
        """Hard-cut the connection WITHOUT the orderly close bookkeeping
        a caller would run — the fault-injection analog of a mid-flight
        network partition. The read loop sees EOF/reset and reaps."""
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def request(self, topic: str, msg: dict, timeout_s: float = 5.0) -> dict:
        """Request/reply over the bridge (MessageBus.request mirror).

        The publish-count check is impossible remotely; a missing
        responder surfaces as the timeout instead.
        """
        import queue as _queue
        import uuid as _uuid

        st = self.stats
        inbox = f"_inbox.{_uuid.uuid4().hex}"
        q: _queue.Queue = _queue.Queue()
        sub = self.subscribe(inbox, q.put)
        t0 = time.monotonic()
        try:
            self.publish(topic, {**msg, "_reply_to": inbox})
            reply = q.get(timeout=timeout_s)
            if st is not None:
                st.on_request(self.peer, time.monotonic() - t0)
            return reply
        except _queue.Empty:
            if st is not None:
                st.on_request(self.peer, time.monotonic() - t0,
                              error=True)
            raise BusTimeout(
                f"no reply from {topic!r} in {timeout_s}s"
            ) from None
        finally:
            sub.unsubscribe()

    def _unsubscribe(self, sid: int) -> None:
        with self._lock:
            self._handlers.pop(sid, None)
        try:
            self._send({"op": "unsub", "sid": sid})
        except (ConnectionError, OSError):
            pass  # bus already closed; the server reaps on disconnect

    def _send(self, obj) -> None:
        if self._closed.is_set():
            raise ConnectionError("remote bus closed")
        st = self.stats
        try:
            t0 = time.monotonic()
            with self._send_lock:
                stall_s = time.monotonic() - t0
                n = _send_frame(self.sock, obj)
            if st is not None:
                st.on_send_stall(self.peer, stall_s)
                st.on_frame(self.peer, "send", n)
        except (ConnectionError, OSError):
            # A failed/timed-out send may have written a PARTIAL frame:
            # the stream is desynced for good. Poison the bus so every
            # later caller fails fast instead of corrupting the wire.
            if st is not None and not self._closed.is_set():
                st.on_conn_event(self.peer, "drop")
            self.close()
            raise

    def _on_handler_error(self, topic: str, e: Exception) -> None:
        with self._lock:
            self.handler_errors.append((topic, e, time.time_ns()))
            self._handler_errors_total += 1

    def busz(self) -> dict:
        """The ``/debug/busz`` surface for this bus (MessageBus.busz
        mirror): stat rows, live subscription queue state, recent
        handler errors."""
        st = self.stats
        with self._lock:
            subs = list(self._handlers.values())
            recent = [
                {"topic": t, "error": repr(e), "unix_ns": ns}
                for t, e, ns in self.handler_errors
            ]
            errors_total = self._handler_errors_total
        queues: dict[str, dict] = {}
        for s in subs:
            ent = queues.setdefault(
                s._cls, {"subscriptions": 0, "depth": 0, "high_water": 0}
            )
            ent["subscriptions"] += 1
            ent["depth"] = max(ent["depth"], s._q.qsize())
            ent["high_water"] = max(ent["high_water"], s._hw)
        if st is not None:
            for cls, hw in st.queue_high_water().items():
                ent = queues.setdefault(
                    cls, {"subscriptions": 0, "depth": 0, "high_water": 0}
                )
                ent["high_water"] = max(ent["high_water"], hw)
        return {
            "rows": st.snapshot() if st is not None else [],
            "queues": queues,
            "handler_errors_total": errors_total,
            "recent_errors": recent,
        }

    def _read_loop(self) -> None:
        st = self.stats
        try:
            while True:
                frame, nb = _recv_frame_sized(self.sock)
                if st is not None and nb:
                    st.on_frame(self.peer, "recv", nb)
                if frame is None:
                    break
                if frame.get("op") == "msg":
                    with self._lock:
                        sub = self._handlers.get(frame["sid"])
                    if sub is not None:
                        sub._deliver(frame["msg"])
        except (ConnectionError, OSError, WireError):
            # WireError covers corrupted bytes AND wrong-schema frames
            # (validated in _recv_frame) — drop the connection; real
            # handler bugs still raise visibly.
            pass
        finally:
            # An orderly close() sets _closed BEFORE the socket dies;
            # anything else reaching here lost the connection.
            if st is not None and not self._closed.is_set():
                st.on_conn_event(self.peer, "drop")
            self._closed.set()
            self._reap_dispatchers()

    def _reap_dispatchers(self) -> None:
        """End every subscription dispatcher thread (connection gone)."""
        with self._lock:
            subs = list(self._handlers.values())
            self._handlers.clear()
        for sub in subs:
            sub._q.put(sub._SENTINEL)

    def close(self) -> None:
        self._closed.set()
        try:
            self.sock.close()
        except OSError:
            pass
        self._reap_dispatchers()
