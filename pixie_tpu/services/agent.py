"""Agent runtime: PEM (data) and Kelvin (merge) agents over the bus.

Reference parity: ``src/vizier/services/agent/manager/manager.h:102`` —
an agent connects to the control plane, registers, heartbeats every 5s,
and handles execute-query messages (``exec.h:38`` ->
``Carnot::ExecutePlan``). A PEM owns a local engine + table store and
runs data fragments; every agent can also host merge fragments (the
Kelvin role, ``kelvin_manager.h:31``), receiving bridge payloads the way
Kelvin's GRPCRouter receives ``TransferResultChunk`` streams
(``grpc_router.h:53,159``).
"""

from __future__ import annotations

import threading
import time
import traceback

from ..exec.engine import Engine, QueryError
from .msgbus import MessageBus
from .tracker import TOPIC_HEARTBEAT, TOPIC_REGISTER

DEFAULT_HEARTBEAT_INTERVAL_S = 5.0


class Agent:
    """Base manager: registration, heartbeats, execute + bridge handlers."""

    processes_data = True
    accepts_remote_sources = False

    def __init__(
        self,
        bus: MessageBus,
        agent_id: str,
        engine: Engine | None = None,
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    ):
        self.bus = bus
        self.agent_id = agent_id
        self.engine = engine or Engine()
        # Per-agent registry with service UDTFs bound to this bus (the
        # VizierFuncFactoryContext analog) — cloned so the process-wide
        # default registry stays untouched.
        from .vizier_funcs import bind_service_registry

        self.engine.registry = bind_service_registry(
            self.engine.registry, bus, f"agent-{agent_id}"
        )
        self.heartbeat_interval_s = heartbeat_interval_s
        self.asid = None
        # Dynamic tracing surface (pem/tracepoint_manager.h:48 analog):
        # traceable in-process symbols + deployed tracepoint connectors.
        from ..ingest.collector import Collector
        from ..ingest.dynamic import TraceTargetRegistry

        self.trace_targets = TraceTargetRegistry()
        self.collector = Collector()
        self.collector.wire_to(self.engine)
        self._tracepoints: dict = {}  # name -> DynamicTraceConnector
        self._registered = threading.Event()
        self._stop = threading.Event()
        self._subs = []
        self._lock = threading.Lock()
        # qid -> {"expect": {(bridge_id, agent_id)}, "got": {bid: [payload]},
        #         "plan": merge plan, "reply_to": topic}
        self._pending_merges: dict = {}
        # Bounded memory of cancelled query ids (late bridge chunks for a
        # cancelled query must be dropped, not backlogged forever).
        self._cancelled: "dict[str, None]" = {}
        self._max_cancelled = 1024
        # qid -> threading.Event for fragments currently executing: a
        # cancel mid-stream aborts between windows (ExecState keep_running).
        self._running: "dict[str, object]" = {}
        # Live queries (StreamResults analog): qid -> merge state for the
        # Kelvin half {plan, expect, latest {(bid, agent): payload}, seq}.
        self._streaming_merges: dict = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Agent":
        a = self.agent_id
        self._subs = [
            self.bus.subscribe(f"agent.{a}.registered", self._on_registered),
            self.bus.subscribe(f"agent.{a}.reregister", lambda m: self._register()),
            self.bus.subscribe(f"agent.{a}.execute", self._on_execute),
            self.bus.subscribe(f"agent.{a}.merge", self._on_merge),
            self.bus.subscribe(f"agent.{a}.bridge", self._on_bridge),
            self.bus.subscribe(
                f"agent.{a}.stream_execute", self._on_stream_execute
            ),
            self.bus.subscribe(
                f"agent.{a}.stream_merge", self._on_stream_merge
            ),
            self.bus.subscribe(
                f"agent.{a}.stream_bridge", self._on_stream_bridge
            ),
            self.bus.subscribe(f"agent.{a}.tracepoint", self._on_tracepoint),
            self.bus.subscribe("query.cancel", self._on_cancel),
        ]
        self._register()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        # The ingest loop (Stirling::RunAsThread): drains connector
        # buffers — incl. dynamically deployed tracepoints — on cadence.
        self.collector.run_as_thread()
        return self

    def stop(self):
        """Simulate agent death: no more heartbeats or message handling."""
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        # Stops connectors too, restoring any trace-wrapped callables.
        self.collector.stop()

    def _register(self):
        self.bus.publish(
            TOPIC_REGISTER,
            {
                "agent_id": self.agent_id,
                "processes_data": self.processes_data,
                "accepts_remote_sources": self.accepts_remote_sources,
                "schemas": self._schemas(),
            },
        )

    def _on_registered(self, msg):
        self.asid = msg["asid"]
        self._registered.set()

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_interval_s):
            self.bus.publish(
                TOPIC_HEARTBEAT,
                {"agent_id": self.agent_id, "schemas": self._schemas()},
            )

    def _schemas(self) -> dict:
        return {
            name: t.relation
            for name, t in self.engine.tables.items()
            if t is not None and len(t.relation)
        }

    # -- data push (Stirling's RegisterDataPushCallback target) --------------
    def append_data(self, table: str, data, time_cols=("time_",)):
        return self.engine.append_data(table, data, time_cols=time_cols)

    # -- dynamic tracepoints (TracepointManager analog) ----------------------
    def _on_tracepoint(self, msg):
        from ..services.tracepoints import FAILED, RUNNING, TOPIC_STATUS

        if msg.get("op") == "remove":
            conn = self._tracepoints.pop(msg["name"], None)
            if conn is not None:
                self.collector.remove_source(conn)
            return
        dep = msg["deployment"]
        try:
            from ..ingest.dynamic import compile_program

            old = self._tracepoints.pop(dep.name, None)
            if old is not None:
                # Re-deploy under the same name: detach the old connector
                # first (otherwise the target ends up double-wrapped and
                # every call records duplicate rows).
                self.collector.remove_source(old)
            conn = compile_program(
                dep, self.trace_targets, asid=self.asid or 0
            )
            existing = self.engine.table_store.relation(dep.table_name)
            new_rel = dep.relation()
            if existing is None:
                self.engine.create_table(dep.table_name, new_rel)
            elif list(existing.items()) != list(new_rel.items()):
                # Schema changed: replace the table (old-relation rows
                # cannot coexist with the new output spec).
                self.engine.create_table(dep.table_name, new_rel)
            # else: TTL refresh / same-schema redeploy keeps collected rows.
            self.collector.register_source(conn)
            self._tracepoints[dep.name] = conn
        except Exception as e:
            self.bus.publish(
                TOPIC_STATUS,
                {
                    "name": dep.name,
                    "agent": self.agent_id,
                    "state": FAILED,
                    "error": repr(e)[:300],
                },
            )
            return
        # Publish the new schema immediately (the broker's mutation wait
        # needs it before the next heartbeat would fire).
        self.bus.publish(
            TOPIC_HEARTBEAT,
            {"agent_id": self.agent_id, "schemas": self._schemas()},
        )
        self.bus.publish(
            TOPIC_STATUS,
            {"name": dep.name, "agent": self.agent_id, "state": RUNNING},
        )

    def poll_tracepoints(self) -> None:
        """Drain deployed-tracepoint buffers into the table store NOW —
        bypassing the collector thread's sampling/push frequencies (which
        drain on their own cadence) for tests and low-latency reads."""
        for conn in list(self._tracepoints.values()):
            try:
                conn.transfer_data(self.collector, self.collector._data_tables)
            except Exception as e:
                self.collector.errors.append((conn.name, repr(e)))
        self.collector.flush()

    # -- query execution -----------------------------------------------------
    def _on_cancel(self, msg):
        with self._lock:
            self._cancelled[msg["qid"]] = None
            while len(self._cancelled) > self._max_cancelled:
                self._cancelled.pop(next(iter(self._cancelled)))
            self._pending_merges.pop(msg["qid"], None)
            self._streaming_merges.pop(msg["qid"], None)
            ev = self._running.get(msg["qid"])
        if ev is not None:
            ev.set()

    def _on_execute(self, msg):
        """Run a data fragment; ship bridge payloads to the merge agent."""
        qid, plan = msg["qid"], msg["plan"]
        import threading as _threading

        ev = _threading.Event()
        with self._lock:
            # Atomic with _on_cancel: a cancel that lands between the
            # check and the registration must either stop us here or find
            # the event to set.
            if qid in self._cancelled:
                return
            self._running[qid] = ev
        try:
            t0 = time.perf_counter()
            outputs = self.engine.execute_plan(plan, cancel=ev)
            elapsed = time.perf_counter() - t0
        except Exception as e:
            with self._lock:
                self._running.pop(qid, None)
            if qid not in self._cancelled:
                self.bus.publish(
                    f"query.{qid}.results",
                    {
                        "error": f"{self.agent_id}: {e}",
                        "trace": traceback.format_exc(),
                    },
                )
            return
        with self._lock:
            self._running.pop(qid, None)
            if qid in self._cancelled:
                return  # cancelled during execution: results are dropped
        merge_agent = msg.get("merge_agent")
        for key, val in outputs.items():
            if isinstance(key, tuple) and key[0] == "bridge":
                self.bus.publish(
                    f"agent.{merge_agent}.bridge",
                    {
                        "qid": qid,
                        "bridge_id": key[1],
                        "from_agent": self.agent_id,
                        "payload": val,
                    },
                )
            else:  # whole plan executed locally (no split)
                self.bus.publish(
                    f"query.{qid}.results",
                    {"table": key, "batch": val, "agent": self.agent_id},
                )
        self.bus.publish(
            f"query.{qid}.agent_done",
            {"agent": self.agent_id, "exec_time_s": elapsed},
        )

    def _on_merge(self, msg):
        """Install a merge fragment; runs once all bridge payloads land."""
        qid = msg["qid"]
        if qid in self._cancelled:
            return
        with self._lock:
            # Bridge payloads may already be backlogged for this query —
            # merge the plan into the existing record, never replace it.
            pm = self._pending_merges.setdefault(
                qid, {"plan": None, "expect": None, "got": {}, "got_keys": set()}
            )
            pm["plan"] = msg["plan"]
            pm["expect"] = {
                (bid, aid)
                for bid in msg["bridge_ids"]
                for aid in msg["data_agents"]
            }
        self._maybe_finish_merge(qid)

    def _on_bridge(self, msg):
        qid = msg["qid"]
        with self._lock:
            if qid in self._cancelled:
                return
            pm = self._pending_merges.get(qid)
            if pm is None:
                # Bridge chunks can arrive before the merge plan (the
                # GRPCRouter backlogs early TransferResultChunks).
                pm = self._pending_merges.setdefault(
                    qid, {"plan": None, "expect": None, "got": {}, "got_keys": set()}
                )
            pm["got"].setdefault(msg["bridge_id"], []).append(msg["payload"])
            pm["got_keys"].add((msg["bridge_id"], msg["from_agent"]))
        self._maybe_finish_merge(qid)

    def _maybe_finish_merge(self, qid):
        with self._lock:
            pm = self._pending_merges.get(qid)
            if (
                pm is None
                or pm["expect"] is None
                or not pm["expect"] <= pm["got_keys"]
            ):
                return
            del self._pending_merges[qid]
        try:
            outputs = self.engine.execute_plan(pm["plan"], bridge_inputs=pm["got"])
        except Exception as e:
            self.bus.publish(
                f"query.{qid}.results",
                {"error": f"{self.agent_id}: {e}", "trace": traceback.format_exc()},
            )
            return
        for name, batch in outputs.items():
            self.bus.publish(
                f"query.{qid}.results",
                {"table": name, "batch": batch, "agent": self.agent_id},
            )
        self.bus.publish(f"query.{qid}.results", {"eos": True})


    # -- live queries (StreamResults analog) ---------------------------------
    def _on_stream_execute(self, msg):
        """Run a live data fragment: a streaming cursor folds appended
        rows on cadence and ships partial states / new rows to the merge
        agent until the query is cancelled
        (``query_result_forwarder.go:470`` StreamResults; infinite
        MemorySource per ``memory_source_node.cc``)."""
        from ..exec.streaming import StreamingQuery

        qid, plan = msg["qid"], msg["plan"]
        merge_agent = msg.get("merge_agent")
        interval = float(msg.get("poll_interval_s", 0.25))
        ev = threading.Event()
        with self._lock:
            if qid in self._cancelled:
                return
            self._running[qid] = ev

        def emit(up):
            if up.mode in ("state", "rows"):
                self.bus.publish(
                    f"agent.{merge_agent}.stream_bridge",
                    {
                        "qid": qid,
                        "bridge_id": up.bridge_id,
                        "from_agent": self.agent_id,
                        "payload": up.batch,
                        "seq": up.seq,
                    },
                )
            else:
                self.bus.publish(
                    f"query.{qid}.results",
                    {
                        "table": up.table,
                        "batch": up.batch,
                        "seq": up.seq,
                        "mode": up.mode,
                        "agent": self.agent_id,
                    },
                )

        def run():
            try:
                sq = StreamingQuery(self.engine, plan, emit, cancel=ev)
                sq.run(poll_interval_s=interval)
            except Exception as e:
                if qid not in self._cancelled:
                    self.bus.publish(
                        f"query.{qid}.results",
                        {
                            "error": f"{self.agent_id}: {e}",
                            "trace": traceback.format_exc(),
                        },
                    )
            finally:
                with self._lock:
                    self._running.pop(qid, None)

        threading.Thread(target=run, daemon=True).start()

    def _stream_state(self, qid):
        return self._streaming_merges.setdefault(
            qid,
            {
                "plan": None,
                "expect": None,
                "latest": {},
                "pending_rows": [],  # chunks that beat the plan install
                "seq": 0,
                "dirty": False,
                "merging": False,
                "merge_lock": threading.Lock(),
            },
        )

    def _on_stream_merge(self, msg):
        """Install a live merge: each round's freshest per-agent states
        re-merge into an updated result (incremental view maintenance —
        the reference re-runs live views from scratch on every poll)."""
        qid = msg["qid"]
        with self._lock:
            if qid in self._cancelled:
                return
            st = self._stream_state(qid)
            st["plan"] = msg["plan"]
            st["expect"] = {
                (bid, aid)
                for bid in msg["bridge_ids"]
                for aid in msg["data_agents"]
            }
            backlog = st["pending_rows"]
            st["pending_rows"] = []
        # Row chunks that raced ahead of the install flow through now, in
        # arrival order (the one-shot _on_bridge path buffers the same way).
        for bid, payload in backlog:
            self._stream_emit_rows(qid, bid, payload)
        self._maybe_stream_remerge(qid)

    def _on_stream_bridge(self, msg):
        qid = msg["qid"]
        from ..exec.engine import RowsPayload

        payload = msg["payload"]
        with self._lock:
            if qid in self._cancelled:
                return
            st = self._stream_state(qid)
            if isinstance(payload, RowsPayload):
                # Row-gather bridges append: every chunk flows through the
                # merge plan once, independently.
                st["latest"][(msg["bridge_id"], msg["from_agent"])] = None
                if st["plan"] is None:
                    st["pending_rows"].append((msg["bridge_id"], payload))
                    return
            else:
                # Agg bridges replace: only this agent's freshest state
                # participates in the next re-merge.
                st["latest"][(msg["bridge_id"], msg["from_agent"])] = payload
                payload = None
        if payload is not None:
            self._stream_emit_rows(qid, msg["bridge_id"], payload)
        else:
            self._maybe_stream_remerge(qid)

    def _stream_emit_rows(self, qid, bridge_id, payload):
        with self._lock:
            st = self._streaming_merges.get(qid)
            if st is None or st["plan"] is None:
                return
            plan = st["plan"]
            lock = st["merge_lock"]
        # Serialize executes + publishes per stream so the client's
        # arrival order matches seq order.
        with lock:
            with self._lock:
                seq = st["seq"]
                st["seq"] += 1
            try:
                outputs = self.engine.execute_plan(
                    plan, bridge_inputs={bridge_id: [payload]}
                )
            except Exception as e:
                self.bus.publish(
                    f"query.{qid}.results",
                    {"error": f"{self.agent_id}: {e}",
                     "trace": traceback.format_exc()},
                )
                return
            for name, batch in outputs.items():
                self.bus.publish(
                    f"query.{qid}.results",
                    {"table": name, "batch": batch, "seq": seq,
                     "mode": "append", "agent": self.agent_id},
                )

    def _maybe_stream_remerge(self, qid):
        """Re-merge the freshest per-agent states, coalescing bursts: a
        merge already in flight absorbs any states that land meanwhile
        (one follow-up run instead of N stale ones)."""
        with self._lock:
            st = self._streaming_merges.get(qid)
            if (
                st is None
                or st["plan"] is None
                or st["expect"] is None
                or not st["expect"] <= set(st["latest"])
            ):
                return
            if st["merging"]:
                st["dirty"] = True
                return
            st["merging"] = True
        try:
            while True:
                with self._lock:
                    st["dirty"] = False
                    plan = st["plan"]
                    by_bridge: dict = {}
                    for (bid, _aid), p in st["latest"].items():
                        if p is not None:
                            by_bridge.setdefault(bid, []).append(p)
                if by_bridge:
                    with st["merge_lock"]:
                        # seq is claimed INSIDE merge_lock (same order as
                        # _stream_emit_rows) so publish order always
                        # matches seq order — claiming it earlier let a
                        # lower-seq 'replace' land after a higher-seq
                        # update and be wrongly superseded by clients.
                        with self._lock:
                            seq = st["seq"]
                            st["seq"] += 1
                        try:
                            outputs = self.engine.execute_plan(
                                plan, bridge_inputs=by_bridge
                            )
                        except Exception as e:
                            self.bus.publish(
                                f"query.{qid}.results",
                                {"error": f"{self.agent_id}: {e}",
                                 "trace": traceback.format_exc()},
                            )
                            return
                        for name, batch in outputs.items():
                            self.bus.publish(
                                f"query.{qid}.results",
                                {"table": name, "batch": batch, "seq": seq,
                                 "mode": "replace", "agent": self.agent_id},
                            )
                with self._lock:
                    if not st["dirty"]:
                        return
        finally:
            with self._lock:
                st["merging"] = False


class PEMAgent(Agent):
    """Per-node data agent: ingest push target + data fragments
    (``pem_manager.h:39``)."""

    processes_data = True
    accepts_remote_sources = False

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # The PEM's ingest is bounded by the table-store byte budget
        # from the first append (pem_manager.cc:86-104 InitSchemas) —
        # installed as lazy per-table budgets so synthetic/partial
        # schemas in tests and tools still shape tables from their
        # first append.
        from ..ingest.schemas import table_budgets

        self.engine.table_store.table_budgets = table_budgets()


class KelvinAgent(Agent):
    """Compute-only merge agent (``kelvin_manager.h:31``)."""

    processes_data = False
    accepts_remote_sources = True
