"""Agent runtime: PEM (data) and Kelvin (merge) agents over the bus.

Reference parity: ``src/vizier/services/agent/manager/manager.h:102`` —
an agent connects to the control plane, registers, heartbeats every 5s,
and handles execute-query messages (``exec.h:38`` ->
``Carnot::ExecutePlan``). A PEM owns a local engine + table store and
runs data fragments; every agent can also host merge fragments (the
Kelvin role, ``kelvin_manager.h:31``), receiving bridge payloads the way
Kelvin's GRPCRouter receives ``TransferResultChunk`` streams
(``grpc_router.h:53,159``).
"""

from __future__ import annotations

import threading
import time
import traceback

from ..exec.engine import Engine, QueryError
from .msgbus import MessageBus
from .tracker import TOPIC_HEARTBEAT, TOPIC_REGISTER

DEFAULT_HEARTBEAT_INTERVAL_S = 5.0


class Agent:
    """Base manager: registration, heartbeats, execute + bridge handlers."""

    processes_data = True
    accepts_remote_sources = False

    def __init__(
        self,
        bus: MessageBus,
        agent_id: str,
        engine: Engine | None = None,
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    ):
        self.bus = bus
        self.agent_id = agent_id
        self.engine = engine or Engine()
        # Per-agent registry with service UDTFs bound to this bus (the
        # VizierFuncFactoryContext analog) — cloned so the process-wide
        # default registry stays untouched.
        from .vizier_funcs import bind_service_registry

        self.engine.registry = bind_service_registry(
            self.engine.registry, bus, f"agent-{agent_id}"
        )
        self.heartbeat_interval_s = heartbeat_interval_s
        self.asid = None
        # Dynamic tracing surface (pem/tracepoint_manager.h:48 analog):
        # traceable in-process symbols + deployed tracepoint connectors.
        from ..ingest.collector import Collector
        from ..ingest.dynamic import TraceTargetRegistry

        self.trace_targets = TraceTargetRegistry()
        self.collector = Collector()
        self.collector.wire_to(self.engine)
        self._tracepoints: dict = {}  # name -> DynamicTraceConnector
        self._registered = threading.Event()
        self._stop = threading.Event()
        self._subs = []
        self._lock = threading.Lock()
        # qid -> {"expect": {(bridge_id, agent_id)}, "got": {bid: [payload]},
        #         "plan": merge plan, "reply_to": topic}
        self._pending_merges: dict = {}
        # Bounded memory of cancelled query ids (late bridge chunks for a
        # cancelled query must be dropped, not backlogged forever).
        self._cancelled: "dict[str, None]" = {}
        self._max_cancelled = 1024
        # qid -> threading.Event for fragments currently executing: a
        # cancel mid-stream aborts between windows (ExecState keep_running).
        self._running: "dict[str, object]" = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Agent":
        a = self.agent_id
        self._subs = [
            self.bus.subscribe(f"agent.{a}.registered", self._on_registered),
            self.bus.subscribe(f"agent.{a}.reregister", lambda m: self._register()),
            self.bus.subscribe(f"agent.{a}.execute", self._on_execute),
            self.bus.subscribe(f"agent.{a}.merge", self._on_merge),
            self.bus.subscribe(f"agent.{a}.bridge", self._on_bridge),
            self.bus.subscribe(f"agent.{a}.tracepoint", self._on_tracepoint),
            self.bus.subscribe("query.cancel", self._on_cancel),
        ]
        self._register()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        # The ingest loop (Stirling::RunAsThread): drains connector
        # buffers — incl. dynamically deployed tracepoints — on cadence.
        self.collector.run_as_thread()
        return self

    def stop(self):
        """Simulate agent death: no more heartbeats or message handling."""
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        # Stops connectors too, restoring any trace-wrapped callables.
        self.collector.stop()

    def _register(self):
        self.bus.publish(
            TOPIC_REGISTER,
            {
                "agent_id": self.agent_id,
                "processes_data": self.processes_data,
                "accepts_remote_sources": self.accepts_remote_sources,
                "schemas": self._schemas(),
            },
        )

    def _on_registered(self, msg):
        self.asid = msg["asid"]
        self._registered.set()

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_interval_s):
            self.bus.publish(
                TOPIC_HEARTBEAT,
                {"agent_id": self.agent_id, "schemas": self._schemas()},
            )

    def _schemas(self) -> dict:
        return {
            name: t.relation
            for name, t in self.engine.tables.items()
            if t is not None and len(t.relation)
        }

    # -- data push (Stirling's RegisterDataPushCallback target) --------------
    def append_data(self, table: str, data, time_cols=("time_",)):
        return self.engine.append_data(table, data, time_cols=time_cols)

    # -- dynamic tracepoints (TracepointManager analog) ----------------------
    def _on_tracepoint(self, msg):
        from ..services.tracepoints import FAILED, RUNNING, TOPIC_STATUS

        if msg.get("op") == "remove":
            conn = self._tracepoints.pop(msg["name"], None)
            if conn is not None:
                self.collector.remove_source(conn)
            return
        dep = msg["deployment"]
        try:
            from ..ingest.dynamic import compile_program

            old = self._tracepoints.pop(dep.name, None)
            if old is not None:
                # Re-deploy under the same name: detach the old connector
                # first (otherwise the target ends up double-wrapped and
                # every call records duplicate rows).
                self.collector.remove_source(old)
            conn = compile_program(
                dep, self.trace_targets, asid=self.asid or 0
            )
            existing = self.engine.table_store.relation(dep.table_name)
            new_rel = dep.relation()
            if existing is None:
                self.engine.create_table(dep.table_name, new_rel)
            elif list(existing.items()) != list(new_rel.items()):
                # Schema changed: replace the table (old-relation rows
                # cannot coexist with the new output spec).
                self.engine.create_table(dep.table_name, new_rel)
            # else: TTL refresh / same-schema redeploy keeps collected rows.
            self.collector.register_source(conn)
            self._tracepoints[dep.name] = conn
        except Exception as e:
            self.bus.publish(
                TOPIC_STATUS,
                {
                    "name": dep.name,
                    "agent": self.agent_id,
                    "state": FAILED,
                    "error": repr(e)[:300],
                },
            )
            return
        # Publish the new schema immediately (the broker's mutation wait
        # needs it before the next heartbeat would fire).
        self.bus.publish(
            TOPIC_HEARTBEAT,
            {"agent_id": self.agent_id, "schemas": self._schemas()},
        )
        self.bus.publish(
            TOPIC_STATUS,
            {"name": dep.name, "agent": self.agent_id, "state": RUNNING},
        )

    def poll_tracepoints(self) -> None:
        """Drain deployed-tracepoint buffers into the table store NOW —
        bypassing the collector thread's sampling/push frequencies (which
        drain on their own cadence) for tests and low-latency reads."""
        for conn in list(self._tracepoints.values()):
            try:
                conn.transfer_data(self.collector, self.collector._data_tables)
            except Exception as e:
                self.collector.errors.append((conn.name, repr(e)))
        self.collector.flush()

    # -- query execution -----------------------------------------------------
    def _on_cancel(self, msg):
        with self._lock:
            self._cancelled[msg["qid"]] = None
            while len(self._cancelled) > self._max_cancelled:
                self._cancelled.pop(next(iter(self._cancelled)))
            self._pending_merges.pop(msg["qid"], None)
            ev = self._running.get(msg["qid"])
        if ev is not None:
            ev.set()

    def _on_execute(self, msg):
        """Run a data fragment; ship bridge payloads to the merge agent."""
        qid, plan = msg["qid"], msg["plan"]
        import threading as _threading

        ev = _threading.Event()
        with self._lock:
            # Atomic with _on_cancel: a cancel that lands between the
            # check and the registration must either stop us here or find
            # the event to set.
            if qid in self._cancelled:
                return
            self._running[qid] = ev
        try:
            t0 = time.perf_counter()
            outputs = self.engine.execute_plan(plan, cancel=ev)
            elapsed = time.perf_counter() - t0
        except Exception as e:
            with self._lock:
                self._running.pop(qid, None)
            if qid not in self._cancelled:
                self.bus.publish(
                    f"query.{qid}.results",
                    {
                        "error": f"{self.agent_id}: {e}",
                        "trace": traceback.format_exc(),
                    },
                )
            return
        with self._lock:
            self._running.pop(qid, None)
            if qid in self._cancelled:
                return  # cancelled during execution: results are dropped
        merge_agent = msg.get("merge_agent")
        for key, val in outputs.items():
            if isinstance(key, tuple) and key[0] == "bridge":
                self.bus.publish(
                    f"agent.{merge_agent}.bridge",
                    {
                        "qid": qid,
                        "bridge_id": key[1],
                        "from_agent": self.agent_id,
                        "payload": val,
                    },
                )
            else:  # whole plan executed locally (no split)
                self.bus.publish(
                    f"query.{qid}.results",
                    {"table": key, "batch": val, "agent": self.agent_id},
                )
        self.bus.publish(
            f"query.{qid}.agent_done",
            {"agent": self.agent_id, "exec_time_s": elapsed},
        )

    def _on_merge(self, msg):
        """Install a merge fragment; runs once all bridge payloads land."""
        qid = msg["qid"]
        if qid in self._cancelled:
            return
        with self._lock:
            # Bridge payloads may already be backlogged for this query —
            # merge the plan into the existing record, never replace it.
            pm = self._pending_merges.setdefault(
                qid, {"plan": None, "expect": None, "got": {}, "got_keys": set()}
            )
            pm["plan"] = msg["plan"]
            pm["expect"] = {
                (bid, aid)
                for bid in msg["bridge_ids"]
                for aid in msg["data_agents"]
            }
        self._maybe_finish_merge(qid)

    def _on_bridge(self, msg):
        qid = msg["qid"]
        with self._lock:
            if qid in self._cancelled:
                return
            pm = self._pending_merges.get(qid)
            if pm is None:
                # Bridge chunks can arrive before the merge plan (the
                # GRPCRouter backlogs early TransferResultChunks).
                pm = self._pending_merges.setdefault(
                    qid, {"plan": None, "expect": None, "got": {}, "got_keys": set()}
                )
            pm["got"].setdefault(msg["bridge_id"], []).append(msg["payload"])
            pm["got_keys"].add((msg["bridge_id"], msg["from_agent"]))
        self._maybe_finish_merge(qid)

    def _maybe_finish_merge(self, qid):
        with self._lock:
            pm = self._pending_merges.get(qid)
            if (
                pm is None
                or pm["expect"] is None
                or not pm["expect"] <= pm["got_keys"]
            ):
                return
            del self._pending_merges[qid]
        try:
            outputs = self.engine.execute_plan(pm["plan"], bridge_inputs=pm["got"])
        except Exception as e:
            self.bus.publish(
                f"query.{qid}.results",
                {"error": f"{self.agent_id}: {e}", "trace": traceback.format_exc()},
            )
            return
        for name, batch in outputs.items():
            self.bus.publish(
                f"query.{qid}.results",
                {"table": name, "batch": batch, "agent": self.agent_id},
            )
        self.bus.publish(f"query.{qid}.results", {"eos": True})


class PEMAgent(Agent):
    """Per-node data agent: ingest push target + data fragments
    (``pem_manager.h:39``)."""

    processes_data = True
    accepts_remote_sources = False


class KelvinAgent(Agent):
    """Compute-only merge agent (``kelvin_manager.h:31``)."""

    processes_data = False
    accepts_remote_sources = True
