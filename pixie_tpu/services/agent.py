"""Agent runtime: PEM (data) and Kelvin (merge) agents over the bus.

Reference parity: ``src/vizier/services/agent/manager/manager.h:102`` —
an agent connects to the control plane, registers, heartbeats every 5s,
and handles execute-query messages (``exec.h:38`` ->
``Carnot::ExecutePlan``). A PEM owns a local engine + table store and
runs data fragments; every agent can also host merge fragments (the
Kelvin role, ``kelvin_manager.h:31``), receiving bridge payloads the way
Kelvin's GRPCRouter receives ``TransferResultChunk`` streams
(``grpc_router.h:53,159``).
"""

from __future__ import annotations

import threading
import time
import traceback

from ..exec import tracectx
from ..exec.engine import Engine, QueryError
from ..exec.pipeline import DeadlineEvent
from ..exec.stream import QueryCancelled
from ..exec.trace import plan_script
from .msgbus import MessageBus
from .tracker import TOPIC_HEARTBEAT, TOPIC_REGISTER

DEFAULT_HEARTBEAT_INTERVAL_S = 5.0


class Agent:
    """Base manager: registration, heartbeats, execute + bridge handlers."""

    processes_data = True
    accepts_remote_sources = False

    def __init__(
        self,
        bus: MessageBus,
        agent_id: str,
        engine: Engine | None = None,
        heartbeat_interval_s: float = DEFAULT_HEARTBEAT_INTERVAL_S,
    ):
        self.bus = bus
        self.agent_id = agent_id
        self.engine = engine or Engine()
        # Per-agent registry with service UDTFs bound to this bus (the
        # VizierFuncFactoryContext analog) — cloned so the process-wide
        # default registry stays untouched.
        from .vizier_funcs import bind_service_registry

        self.engine.registry = bind_service_registry(
            self.engine.registry, bus, f"agent-{agent_id}"
        )
        self.heartbeat_interval_s = heartbeat_interval_s
        self.asid = None
        # Dynamic tracing surface (pem/tracepoint_manager.h:48 analog):
        # traceable in-process symbols + deployed tracepoint connectors.
        from ..ingest.collector import Collector
        from ..ingest.dynamic import TraceTargetRegistry

        self.trace_targets = TraceTargetRegistry()
        self.collector = Collector()
        self.collector.wire_to(self.engine)
        self._tracepoints: dict = {}  # name -> DynamicTraceConnector
        self._registered = threading.Event()
        self._stop = threading.Event()
        self._subs = []
        self._lock = threading.Lock()
        # qid -> {"expect": {(bridge_id, agent_id)}, "got": {bid: [payload]},
        #         "plan": merge plan, "reply_to": topic}
        self._pending_merges: dict = {}
        # Bounded memory of cancelled query ids (late bridge chunks for a
        # cancelled query must be dropped, not backlogged forever).
        self._cancelled: "dict[str, None]" = {}
        self._max_cancelled = 1024
        # Bounded memory of (qid, kind) dispatches already accepted: the
        # broker RETRIES un-acked dispatches (and the bus may duplicate
        # under fault injection), so every fragment handler must be
        # idempotent — a repeat re-acks (the first ack may be the lost
        # message) and is otherwise dropped.
        self._seen_dispatch: "dict[tuple, None]" = {}
        # Bounded qid -> reduced data-agent set from merge_update events
        # that arrived BEFORE the (one-shot or streaming) merge install:
        # cross-topic delivery order is unordered, so the install
        # consults this parking lot.
        self._parked_keep: "dict[str, set]" = {}
        # qid -> threading.Event for fragments currently executing: a
        # cancel mid-stream aborts between windows (ExecState keep_running).
        self._running: "dict[str, object]" = {}
        # Live queries (StreamResults analog): qid -> merge state for the
        # Kelvin half {plan, expect, latest {(bid, agent): payload}, seq}.
        self._streaming_merges: dict = {}
        # Broker-HA epoch fence: the highest dispatch epoch seen. A
        # dispatch stamped BELOW it comes from a deposed leader and is
        # rejected (no ack, no execution); unstamped dispatches (epoch
        # 0, plain single-broker deployments) always pass.
        self._max_epoch = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Agent":
        a = self.agent_id
        self._subs = [
            self.bus.subscribe(f"agent.{a}.registered", self._on_registered),
            self.bus.subscribe(f"agent.{a}.reregister", lambda m: self._register()),
            self.bus.subscribe(f"agent.{a}.execute", self._on_execute),
            self.bus.subscribe(f"agent.{a}.merge", self._on_merge),
            self.bus.subscribe(f"agent.{a}.bridge", self._on_bridge),
            self.bus.subscribe(
                f"agent.{a}.stream_execute", self._on_stream_execute
            ),
            self.bus.subscribe(
                f"agent.{a}.stream_merge", self._on_stream_merge
            ),
            self.bus.subscribe(
                f"agent.{a}.stream_bridge", self._on_stream_bridge
            ),
            self.bus.subscribe(f"agent.{a}.tracepoint", self._on_tracepoint),
            self.bus.subscribe(
                f"agent.{a}.merge_update", self._on_merge_update
            ),
            self.bus.subscribe("query.cancel", self._on_cancel),
            # Broker-HA takeover probe: a freshly elected leader asks
            # every agent which query fragments are still live here so
            # it can rebuild forwarder expectations (broker_ha.py).
            self.bus.subscribe("broker.reconcile", self._on_reconcile),
        ]
        # Dispatch acks ride a DEDICATED subscription per fragment kind:
        # each subscription has its own dispatcher thread, so receipt is
        # acknowledged immediately even while the main handler is busy
        # executing an earlier fragment — otherwise a retried dispatch's
        # re-ack would queue behind the running query and the broker
        # would declare a live, working agent lost.
        for kind in ("execute", "merge", "stream_execute", "stream_merge"):
            self._subs.append(self.bus.subscribe(
                f"agent.{a}.{kind}",
                lambda m, k=kind: self._ack_receipt(m, k),
            ))
        # Self-telemetry (services/telemetry.py): finished fragment/
        # merge traces fold into this agent's __queries__/__spans__/
        # __agents__ tables (PxL-queryable, per-agent attribution) and
        # distributed span summaries flow to the broker's tracez view.
        from ..config import get_flag

        if get_flag("self_telemetry"):
            from .telemetry import enable_self_telemetry

            self.telemetry = enable_self_telemetry(
                self.engine, agent_id=self.agent_id,
                kind="pem" if self.processes_data else "kelvin",
                bus=self.bus,
            )
        self._register()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        # The ingest loop (Stirling::RunAsThread): drains connector
        # buffers — incl. dynamically deployed tracepoints — on cadence.
        self.collector.run_as_thread()
        return self

    def stop(self):
        """Simulate agent death: no more heartbeats or message handling."""
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        # Stops connectors too, restoring any trace-wrapped callables.
        self.collector.stop()

    def _register(self):
        msg = {
            "agent_id": self.agent_id,
            "processes_data": self.processes_data,
            "accepts_remote_sources": self.accepts_remote_sources,
            "schemas": self._schemas(),
            "table_stats": self._table_stats(),
        }
        bus_rows = self._bus_summary()
        if bus_rows:
            msg["bus"] = bus_rows
        self.bus.publish(TOPIC_REGISTER, msg)

    def _bus_summary(self) -> list:
        """Compact transport-tier summary for register/heartbeats (the
        tracker's cluster merge; same rows the ``__bus__`` fold
        appends). Empty when bus_telemetry is off."""
        stats = getattr(self.bus, "stats", None)
        if stats is None:
            return []
        try:
            return stats.snapshot()
        except Exception:
            return []  # telemetry must never kill register/heartbeat

    def _on_registered(self, msg):
        self.asid = msg["asid"]
        self._registered.set()

    def _heartbeat_loop(self):
        while not self._stop.wait(self.heartbeat_interval_s):
            # ONE freshness sweep per heartbeat, shared by the storage-
            # tier fold and the envelope: the fold is forced (a row per
            # table per heartbeat, the reference's stats-on-every-
            # heartbeat shape) so a STOPPED ingest still advances fold
            # time past its frozen watermark — px/ingest_lag's signal.
            # Ring-bounded; the per-trace fold stays change-cursored so
            # query load can't multiply rows.
            fresh = self.engine.table_store.freshness()
            tel = getattr(self, "telemetry", None)
            if tel is not None:
                try:
                    tel.table_stats.fold(force=True, snapshot=fresh)
                except Exception:
                    pass  # telemetry must never kill the heartbeat loop
            hb = {
                "agent_id": self.agent_id,
                "schemas": self._schemas(),
                "table_stats": self._table_stats(freshness=fresh),
            }
            # Profiling tier: ship this agent's cumulative folded-stack
            # summary (top-N, counts monotonic) for the tracker's
            # cluster merge — /debug/pprof and `px profile` read the
            # merged view. Filtered by agent_id so co-resident agents
            # in one process don't double-ship each other's samples.
            try:
                from ..ingest.profiler import profile_summary

                prof = profile_summary(agent_id=self.agent_id)
                if prof:
                    hb["profile"] = prof
            except Exception:
                pass  # profiling must never kill the heartbeat loop
            # Transport tier: fold this agent's bus counters into
            # __bus__ (heartbeat cadence ONLY — see BusStatsCollector)
            # and ship the same summary for the tracker's cluster merge.
            if tel is not None:
                try:
                    tel.bus_stats.fold(force=True)
                except Exception:
                    pass  # telemetry must never kill the heartbeat loop
            bus_rows = self._bus_summary()
            if bus_rows:
                hb["bus"] = bus_rows
            self.bus.publish(TOPIC_HEARTBEAT, hb)

    def _schemas(self) -> dict:
        # Snapshot: heartbeat thread vs concurrent table creation
        # (same race as _compile_table_stats — a died heartbeat loop
        # silently drops this agent from the tracker at expiry).
        return {
            name: t.relation
            for name, t in list(self.engine.tables.items())
            if t is not None and len(t.relation)
        }

    def _table_stats(self, freshness: dict | None = None) -> dict:
        """Ingest-sketch summaries + freshness for the tracker
        ({table: {rows, ndv, zones, freshness}}): the sketch half is
        the broker-side seed for pxbound predicted costs and the
        planner's NDV sizing; the ``freshness`` sub-dict (watermarks,
        monotonic append/expiry counters, ingest-rate EWMA — see
        ``Table.freshness``) is what ``AgentTracker.table_stats()``
        merges cluster-wide for /debug/tablez. Tables without sketches
        ship a freshness-only entry WITHOUT a "rows" key — pxbound
        treats a missing "rows" as unbounded, so an unsketched table
        never gets a bogus known-zero row bound. Microseconds per
        column — everything was maintained at append time; the
        per-engine __observed__ feedback stays local (script hashes
        are engine-scoped history, not cluster state). ``freshness``
        lets the heartbeat loop reuse its already-taken sweep."""
        stats = self.engine._compile_table_stats()
        stats.pop("__observed__", None)
        if freshness is None:
            freshness = self.engine.table_store.freshness()
        for name, fresh in freshness.items():
            stats.setdefault(name, {})["freshness"] = fresh
        return stats

    # -- data push (Stirling's RegisterDataPushCallback target) --------------
    def append_data(self, table: str, data, time_cols=("time_",)):
        return self.engine.append_data(table, data, time_cols=time_cols)

    # -- dynamic tracepoints (TracepointManager analog) ----------------------
    def _on_tracepoint(self, msg):
        from ..services.tracepoints import FAILED, RUNNING, TOPIC_STATUS

        if msg.get("op") == "remove":
            conn = self._tracepoints.pop(msg["name"], None)
            if conn is not None:
                self.collector.remove_source(conn)
            return
        dep = msg["deployment"]
        try:
            from ..ingest.dynamic import compile_program

            old = self._tracepoints.pop(dep.name, None)
            if old is not None:
                # Re-deploy under the same name: detach the old connector
                # first (otherwise the target ends up double-wrapped and
                # every call records duplicate rows).
                self.collector.remove_source(old)
            conn = compile_program(
                dep, self.trace_targets, asid=self.asid or 0
            )
            existing = self.engine.table_store.relation(dep.table_name)
            new_rel = dep.relation()
            if existing is None:
                self.engine.create_table(dep.table_name, new_rel)
            elif list(existing.items()) != list(new_rel.items()):
                # Schema changed: replace the table (old-relation rows
                # cannot coexist with the new output spec).
                self.engine.create_table(dep.table_name, new_rel)
            # else: TTL refresh / same-schema redeploy keeps collected rows.
            self.collector.register_source(conn)
            self._tracepoints[dep.name] = conn
        except Exception as e:
            self.bus.publish(
                TOPIC_STATUS,
                {
                    "name": dep.name,
                    "agent": self.agent_id,
                    "state": FAILED,
                    "error": repr(e)[:300],
                },
            )
            return
        # Publish the new schema immediately (the broker's mutation wait
        # needs it before the next heartbeat would fire).
        self.bus.publish(
            TOPIC_HEARTBEAT,
            {"agent_id": self.agent_id, "schemas": self._schemas()},
        )
        self.bus.publish(
            TOPIC_STATUS,
            {"name": dep.name, "agent": self.agent_id, "state": RUNNING},
        )

    def poll_tracepoints(self) -> None:
        """Drain deployed-tracepoint buffers into the table store NOW —
        bypassing the collector thread's sampling/push frequencies (which
        drain on their own cadence) for tests and low-latency reads."""
        for conn in list(self._tracepoints.values()):
            try:
                conn.transfer_data(self.collector, self.collector._data_tables)
            except Exception as e:
                self.collector.errors.append((conn.name, repr(e)))
        self.collector.flush()

    # -- query execution -----------------------------------------------------
    def _bounded_put(self, d: dict, key, value=None) -> None:
        """Insert into one of the bounded bookkeeping dicts
        (``_cancelled`` / ``_seen_dispatch`` / ``_parked_keep`` /
        per-stream row dedup), evicting insertion-oldest entries past
        ``_max_cancelled``. Caller holds ``self._lock``."""
        d[key] = value
        while len(d) > self._max_cancelled:
            d.pop(next(iter(d)))

    def _on_cancel(self, msg):
        with self._lock:
            self._bounded_put(self._cancelled, msg["qid"])
            self._pending_merges.pop(msg["qid"], None)
            self._streaming_merges.pop(msg["qid"], None)
            ev = self._running.get(msg["qid"])
        if ev is not None:
            ev.set()

    def _on_reconcile(self, msg: dict) -> None:
        """Answer a new leader's takeover probe (broker HA): which query
        fragments are still live HERE — running fragments/merges and,
        for a pending merge, the data agents whose bridge payloads it
        still expects. The successor rebuilds forwarder expectations
        for the deposed leader's in-flight queries from these answers
        (services/broker_ha.py)."""
        self._epoch_ok(msg)  # the probe carries the new epoch: fence up
        reply_to = msg.get("_reply_to") or msg.get("reply_to")
        if not reply_to:
            return
        with self._lock:
            running = sorted(self._running)
            merges = {}
            for qid, pm in self._pending_merges.items():
                exp = pm.get("expect")
                got = pm.get("got_keys") or set()
                if exp is None:
                    # Bridges backlogged before the merge install: the
                    # query is live but its expectations unknown yet.
                    merges[qid] = []
                    continue
                merges[qid] = sorted(
                    {a for (_b, a) in exp if (_b, a) not in got}
                )
            streaming = sorted(self._streaming_merges)
        self.bus.publish(reply_to, {
            "agent": self.agent_id,
            "running": running,
            "pending_merges": merges,
            "streaming": streaming,
        })

    def _epoch_ok(self, msg: dict) -> bool:
        """Broker-HA epoch fence. A message stamped with an epoch BELOW
        the highest this agent has seen comes from a deposed leader:
        reject it (no ack — the sender's retry loop gives up — and no
        execution). Higher stamps raise the fence; unstamped messages
        (epoch 0) always pass, so plain single-broker deployments are
        unaffected."""
        epoch = int(msg.get("epoch", 0) or 0)
        with self._lock:
            if epoch > self._max_epoch:
                self._max_epoch = epoch
                return True
            fenced = 0 < epoch < self._max_epoch
        if fenced:
            from .observability import default_counter

            default_counter(
                "pixie_epoch_fenced_total",
                "Messages rejected as stamped by a deposed broker leader",
            ).inc()
            return False
        return True

    def _ack_receipt(self, msg: dict, kind: str) -> None:
        """Ack a fragment dispatch on ``query.{qid}.ack`` — every
        receipt, including retried/duplicated copies (the first ack may
        be the message that was lost). Deposed-leader dispatches are
        never acked: withholding the ack is what makes the old leader's
        retry loop give up (epoch fencing, broker HA)."""
        if not self._epoch_ok(msg):
            return
        self.bus.publish(
            f"query.{msg['qid']}.ack",
            {"ack": kind, "agent": self.agent_id,
             "epoch": int(msg.get("epoch", 0) or 0)},
        )

    def _dedup_dispatch_locked(self, qid: str, kind: str) -> bool:
        """True when this (qid, kind) dispatch was already accepted:
        retried or fault-duplicated dispatches must not re-run. Caller
        holds ``self._lock``."""
        dup = (qid, kind) in self._seen_dispatch
        self._bounded_put(self._seen_dispatch, (qid, kind))
        return dup

    def _dedup_dispatch(self, qid: str, kind: str) -> bool:
        with self._lock:
            return self._dedup_dispatch_locked(qid, kind)

    def _begin_fragment_trace(self, msg, qid: str, plan, kind: str):
        """Start this fragment's trace as part of the dispatching
        broker's distributed trace: the context envelope in the dispatch
        message (or the ambient one the bus dispatcher bound) parents
        the fragment's root span under the broker's dispatch span."""
        ctx = tracectx.extract(msg) or tracectx.current()
        tr = self.engine.tracer.begin_query(
            script=plan_script(plan), kind=kind, parent_ctx=ctx
        )
        tr.qid = qid
        tr.agent_id = self.agent_id
        # Tenant attribution rides the dispatch envelope: this agent's
        # __queries__/__spans__ rows carry the admitting tenant.
        tr.tenant = str(msg.get("tenant") or "")
        return tr

    @staticmethod
    def _cancel_handle(msg, ev):
        """The fragment's cooperative-cancellation handle: the broker's
        absolute deadline (when the dispatch carries one) wraps the
        cancel event, so the window pipeline aborts past-deadline work
        at its next boundary even before any query.cancel arrives."""
        deadline = msg.get("deadline_unix_s")
        if deadline is None:
            return ev
        return DeadlineEvent(ev, float(deadline))

    def _on_execute(self, msg):
        """Run a data fragment; ship bridge payloads to the merge agent."""
        qid, plan = msg["qid"], msg["plan"]
        if not self._epoch_ok(msg) or self._dedup_dispatch(qid, "execute"):
            return
        import threading as _threading

        ev = _threading.Event()
        with self._lock:
            # Atomic with _on_cancel: a cancel that lands between the
            # check and the registration must either stop us here or find
            # the event to set.
            if qid in self._cancelled:
                return
            self._running[qid] = ev
        trace = self._begin_fragment_trace(msg, qid, plan, "fragment")
        try:
            t0 = time.perf_counter()
            outputs = self.engine.execute_plan(
                plan, cancel=self._cancel_handle(msg, ev), trace=trace
            )
            elapsed = time.perf_counter() - t0
        except QueryCancelled:
            # Deadline lapsed (or a cancel raced its _cancelled mark):
            # the abort is the INTENDED outcome — dead work dropped at
            # a window boundary. The broker's deadline/cancel exit
            # accounts for this agent (missing_reasons), so publishing
            # an error here would wrongly fail the whole query.
            with self._lock:
                self._running.pop(qid, None)
            return
        except Exception as e:
            with self._lock:
                self._running.pop(qid, None)
            if qid not in self._cancelled:
                self.bus.publish(
                    f"query.{qid}.results",
                    {
                        "error": f"{self.agent_id}: {e}",
                        "trace": traceback.format_exc(),
                    },
                )
            return
        with self._lock:
            self._running.pop(qid, None)
            if qid in self._cancelled:
                return  # cancelled during execution: results are dropped
        merge_agent = msg.get("merge_agent")
        for key, val in outputs.items():
            if isinstance(key, tuple) and key[0] == "bridge":
                self.bus.publish(
                    f"agent.{merge_agent}.bridge",
                    {
                        "qid": qid,
                        "bridge_id": key[1],
                        "from_agent": self.agent_id,
                        "payload": val,
                    },
                )
            else:  # whole plan executed locally (no split)
                self.bus.publish(
                    f"query.{qid}.results",
                    {"table": key, "batch": val, "agent": self.agent_id},
                )
        self.bus.publish(
            f"query.{qid}.agent_done",
            {
                "agent": self.agent_id,
                "exec_time_s": elapsed,
                # Per-agent resource attribution (QueryResourceUsage):
                # execute_plan ended the trace, so usage is final here.
                "usage": trace.usage.to_dict(),
            },
        )

    @staticmethod
    def _new_pending_merge() -> dict:
        # "keep" narrows the participating data-agent set when the
        # broker fails over a lost agent (None = everyone expected).
        # "trace_ctx" is the broker's dispatch-span context from the
        # merge install — the merge may RUN from whichever handler
        # completes the bridge set (a different dispatcher thread whose
        # ambient context is some data agent's fragment), so the
        # install-time context is stored, not inherited.
        return {"plan": None, "expect": None, "got": {}, "got_keys": set(),
                "keep": None, "trace_ctx": None, "deadline": None,
                "tenant": ""}

    def _on_merge(self, msg):
        """Install a merge fragment; runs once all bridge payloads land."""
        qid = msg["qid"]
        if not self._epoch_ok(msg):
            return
        with self._lock:
            # Dedup marking and record install must be ONE critical
            # section: _on_bridge/_on_merge_update read "(qid, merge)
            # seen + no record" as "merge already ran" — a gap between
            # the two here would make them drop a live query's chunk.
            if self._dedup_dispatch_locked(qid, "merge"):
                return
            if qid in self._cancelled:
                return
            # Bridge payloads may already be backlogged for this query —
            # merge the plan into the existing record, never replace it.
            pm = self._pending_merges.setdefault(
                qid, self._new_pending_merge()
            )
            parked = self._parked_keep.get(qid)
            if parked is not None:
                pm["keep"] = (
                    parked if pm["keep"] is None else (pm["keep"] & parked)
                )
            pm["plan"] = msg["plan"]
            pm["trace_ctx"] = tracectx.extract(msg) or tracectx.current()
            pm["deadline"] = msg.get("deadline_unix_s")
            pm["tenant"] = str(msg.get("tenant") or "")
            pm["expect"] = {
                (bid, aid)
                for bid in msg["bridge_ids"]
                for aid in msg["data_agents"]
                if pm["keep"] is None or aid in pm["keep"]
            }
        self._maybe_finish_merge(qid)

    def _on_bridge(self, msg):
        qid = msg["qid"]
        with self._lock:
            if qid in self._cancelled:
                return
            pm = self._pending_merges.get(qid)
            if pm is None:
                if (qid, "merge") in self._seen_dispatch:
                    return  # merge already ran; a late duplicate chunk
                # Bridge chunks can arrive before the merge plan (the
                # GRPCRouter backlogs early TransferResultChunks).
                pm = self._pending_merges.setdefault(
                    qid, self._new_pending_merge()
                )
            key = (msg["bridge_id"], msg["from_agent"])
            if key in pm["got_keys"]:
                return  # duplicate delivery (retry / injected dup)
            if pm["keep"] is not None and msg["from_agent"] not in pm["keep"]:
                return  # late chunk from an agent already failed over
            pm["got"].setdefault(msg["bridge_id"], []).append(
                (msg["from_agent"], msg["payload"])
            )
            pm["got_keys"].add(key)
        self._maybe_finish_merge(qid)

    def _on_merge_update(self, msg):
        """The broker failed over a lost data agent: shrink the expected
        set to ``data_agents`` and discard the lost agents' (possibly
        incomplete) contributions so the merge runs from survivors only
        — the partial-aggregation path (Taurus-style best-effort
        scatter-gather). The reduced set is also PARKED: the update can
        beat the (retried) merge/stream_merge install on another
        dispatcher thread, and the install must still see it."""
        qid, keep = msg["qid"], set(msg["data_agents"])
        with self._lock:
            if qid in self._cancelled:
                return
            parked = self._parked_keep.get(qid)
            keep = keep if parked is None else (parked & keep)
            self._bounded_put(self._parked_keep, qid, keep)
            pm = self._pending_merges.get(qid)
            if pm is not None:
                pm["keep"] = (
                    keep if pm["keep"] is None else (pm["keep"] & keep)
                )
                if pm["expect"] is not None:
                    pm["expect"] = {
                        (b, a) for (b, a) in pm["expect"] if a in pm["keep"]
                    }
            st = self._streaming_merges.get(qid)
            if st is not None:
                st["keep"] = (
                    keep if st["keep"] is None else (st["keep"] & keep)
                )
                if st["expect"] is not None:
                    st["expect"] = {
                        (b, a) for (b, a) in st["expect"] if a in st["keep"]
                    }
                st["latest"] = {
                    k: v for k, v in st["latest"].items() if k[1] in st["keep"]
                }
        self._maybe_finish_merge(qid)
        self._maybe_stream_remerge(qid)

    def _maybe_finish_merge(self, qid):
        with self._lock:
            pm = self._pending_merges.get(qid)
            if (
                pm is None
                or pm["plan"] is None
                or pm["expect"] is None
                or not pm["expect"] <= pm["got_keys"]
            ):
                return
            del self._pending_merges[qid]
        keep = pm["keep"]
        bridge_inputs = {}
        for bid, contributions in pm["got"].items():
            # Canonical agent-id order (not arrival order): the merge
            # re-encodes later payloads' string ids into the FIRST
            # payload's dictionary, so arrival-ordered payloads made the
            # merged dictionary CONTENTS depend on bus scheduling — and
            # the content-keyed fragment cache then compiled one XLA
            # program per observed ordering. Merge folds are
            # commutative; ordering by agent id costs one sort of a
            # handful of tuples.
            payloads = [p for (a, p) in sorted(contributions)
                        if keep is None or a in keep]
            if payloads:
                bridge_inputs[bid] = payloads
        trace = self.engine.tracer.begin_query(
            script=plan_script(pm["plan"]), kind="merge",
            parent_ctx=pm["trace_ctx"],
        )
        trace.qid = qid
        trace.agent_id = self.agent_id
        trace.tenant = pm["tenant"]
        # The merge respects the query deadline AND query.cancel:
        # folding states for a client the broker already answered is
        # dead work — the same window-boundary abort as data fragments.
        # The raw event registers under _running so _on_cancel finds it
        # (safe from colliding with this agent's own data fragment: the
        # merge only starts once every expected bridge payload landed,
        # i.e. after any local fragment finished and popped its entry).
        ev = threading.Event()
        with self._lock:
            if qid in self._cancelled:
                return
            self._running[qid] = ev
        cancel = (
            DeadlineEvent(ev, float(pm["deadline"]))
            if pm["deadline"] is not None else ev
        )
        try:
            t0 = time.perf_counter()
            outputs = self.engine.execute_plan(
                pm["plan"], bridge_inputs=bridge_inputs, trace=trace,
                cancel=cancel,
            )
            elapsed = time.perf_counter() - t0
        except QueryCancelled:
            return  # cancelled/past-deadline: the broker already degraded
        except Exception as e:
            self.bus.publish(
                f"query.{qid}.results",
                {"error": f"{self.agent_id}: {e}", "trace": traceback.format_exc()},
            )
            return
        finally:
            with self._lock:
                self._running.pop(qid, None)
        for name, batch in outputs.items():
            self.bus.publish(
                f"query.{qid}.results",
                {"table": name, "batch": batch, "agent": self.agent_id},
            )
        # Merge-tier attribution rides a role-tagged agent_done (the
        # forwarder files it under merge_stats, keeping agent_stats ==
        # data agents for existing consumers). BEFORE eos, so the wait
        # loop never needs its post-eos grace budget for it.
        self.bus.publish(
            f"query.{qid}.agent_done",
            {"agent": self.agent_id, "exec_time_s": elapsed,
             "role": "merge", "usage": trace.usage.to_dict()},
        )
        self.bus.publish(f"query.{qid}.results", {"eos": True})


    # -- live queries (StreamResults analog) ---------------------------------
    def _on_stream_execute(self, msg):
        """Run a live data fragment: a streaming cursor folds appended
        rows on cadence and ships partial states / new rows to the merge
        agent until the query is cancelled
        (``query_result_forwarder.go:470`` StreamResults; infinite
        MemorySource per ``memory_source_node.cc``)."""
        from ..exec.streaming import StreamingQuery

        qid, plan = msg["qid"], msg["plan"]
        if not self._epoch_ok(msg) or self._dedup_dispatch(
            qid, "stream_execute"
        ):
            return
        merge_agent = msg.get("merge_agent")
        interval = float(msg.get("poll_interval_s", 0.25))
        ev = threading.Event()
        with self._lock:
            if qid in self._cancelled:
                return
            self._running[qid] = ev

        def emit(up):
            if up.mode in ("state", "rows"):
                self.bus.publish(
                    f"agent.{merge_agent}.stream_bridge",
                    {
                        "qid": qid,
                        "bridge_id": up.bridge_id,
                        "from_agent": self.agent_id,
                        "payload": up.batch,
                        "seq": up.seq,
                    },
                )
            else:
                self.bus.publish(
                    f"query.{qid}.results",
                    {
                        "table": up.table,
                        "batch": up.batch,
                        "seq": up.seq,
                        "mode": up.mode,
                        "agent": self.agent_id,
                    },
                )

        # The streaming cursor runs on its own thread: re-bind the
        # dispatch's trace context there so the stream's lifecycle trace
        # joins the distributed trace (contextvars are thread-local).
        ctx = tracectx.extract(msg) or tracectx.current()

        def run():
            try:
                with tracectx.bound(ctx):
                    sq = StreamingQuery(self.engine, plan, emit, cancel=ev)
                sq.run(poll_interval_s=interval)
            except Exception as e:
                if qid not in self._cancelled:
                    self.bus.publish(
                        f"query.{qid}.results",
                        {
                            "error": f"{self.agent_id}: {e}",
                            "trace": traceback.format_exc(),
                        },
                    )
            finally:
                with self._lock:
                    self._running.pop(qid, None)

        threading.Thread(target=run, daemon=True).start()

    def _stream_state(self, qid):
        # Every caller holds self._lock (the lint is intraprocedural and
        # cannot see the caller's lock). # pxlint: disable=thread-shared-state
        return self._streaming_merges.setdefault(
            qid,
            {
                "plan": None,
                "expect": None,
                "keep": None,  # reduced agent set after failover
                "latest": {},
                "pending_rows": [],  # chunks that beat the plan install
                "seen_rows": {},  # (bid, agent, seq) dedup, bounded
                "seq": 0,
                "dirty": False,
                "merging": False,
                "merge_lock": threading.Lock(),
            },
        )

    def _on_stream_merge(self, msg):
        """Install a live merge: each round's freshest per-agent states
        re-merge into an updated result (incremental view maintenance —
        the reference re-runs live views from scratch on every poll)."""
        qid = msg["qid"]
        if not self._epoch_ok(msg) or self._dedup_dispatch(
            qid, "stream_merge"
        ):
            return
        with self._lock:
            if qid in self._cancelled:
                return
            st = self._stream_state(qid)
            parked = self._parked_keep.get(qid)
            if parked is not None:
                st["keep"] = (
                    parked if st["keep"] is None else (st["keep"] & parked)
                )
            st["plan"] = msg["plan"]
            st["expect"] = {
                (bid, aid)
                for bid in msg["bridge_ids"]
                for aid in msg["data_agents"]
                if st["keep"] is None or aid in st["keep"]
            }
            backlog = st["pending_rows"]
            st["pending_rows"] = []
        # Row chunks that raced ahead of the install flow through now, in
        # arrival order (the one-shot _on_bridge path buffers the same way).
        for bid, payload in backlog:
            self._stream_emit_rows(qid, bid, payload)
        self._maybe_stream_remerge(qid)

    def _on_stream_bridge(self, msg):
        qid = msg["qid"]
        from ..exec.engine import RowsPayload

        payload = msg["payload"]
        with self._lock:
            if qid in self._cancelled:
                return
            st = self._stream_state(qid)
            if (
                st["keep"] is not None
                and msg["from_agent"] not in st["keep"]
            ):
                return  # chunk from an agent already failed over
            if isinstance(payload, RowsPayload):
                # Row-gather bridges append: every chunk flows through the
                # merge plan once, independently — so a DUPLICATED
                # delivery (retry / at-least-once transport / injected
                # dup) would double-count rows in the live view. Dedup
                # by the producer's per-cursor sequence number.
                chunk_key = (
                    msg["bridge_id"], msg["from_agent"], msg.get("seq")
                )
                if chunk_key in st["seen_rows"]:
                    return
                self._bounded_put(st["seen_rows"], chunk_key)
                st["latest"][(msg["bridge_id"], msg["from_agent"])] = None
                if st["plan"] is None:
                    st["pending_rows"].append((msg["bridge_id"], payload))
                    return
            else:
                # Agg bridges replace: only this agent's freshest state
                # participates in the next re-merge.
                st["latest"][(msg["bridge_id"], msg["from_agent"])] = payload
                payload = None
        if payload is not None:
            self._stream_emit_rows(qid, msg["bridge_id"], payload)
        else:
            self._maybe_stream_remerge(qid)

    def _stream_emit_rows(self, qid, bridge_id, payload):
        with self._lock:
            st = self._streaming_merges.get(qid)
            if st is None or st["plan"] is None:
                return
            plan = st["plan"]
            lock = st["merge_lock"]
        # Serialize executes + publishes per stream so the client's
        # arrival order matches seq order.
        with lock:
            with self._lock:
                seq = st["seq"]
                st["seq"] += 1
            try:
                outputs = self.engine.execute_plan(
                    plan, bridge_inputs={bridge_id: [payload]}
                )
            except Exception as e:
                self.bus.publish(
                    f"query.{qid}.results",
                    {"error": f"{self.agent_id}: {e}",
                     "trace": traceback.format_exc()},
                )
                return
            for name, batch in outputs.items():
                self.bus.publish(
                    f"query.{qid}.results",
                    {"table": name, "batch": batch, "seq": seq,
                     "mode": "append", "agent": self.agent_id},
                )

    def _maybe_stream_remerge(self, qid):
        """Re-merge the freshest per-agent states, coalescing bursts: a
        merge already in flight absorbs any states that land meanwhile
        (one follow-up run instead of N stale ones)."""
        with self._lock:
            st = self._streaming_merges.get(qid)
            if (
                st is None
                or st["plan"] is None
                or st["expect"] is None
                or not st["expect"] <= set(st["latest"])
            ):
                return
            if st["merging"]:
                st["dirty"] = True
                return
            st["merging"] = True
        try:
            while True:
                with self._lock:
                    st["dirty"] = False
                    plan = st["plan"]
                    by_bridge: dict = {}
                    # Canonical (bridge, agent) order — same dictionary-
                    # content determinism as the one-shot merge path.
                    for (bid, _aid), p in sorted(
                        st["latest"].items(), key=lambda kv: kv[0]
                    ):
                        if p is not None:
                            by_bridge.setdefault(bid, []).append(p)
                if by_bridge:
                    with st["merge_lock"]:
                        # seq is claimed INSIDE merge_lock (same order as
                        # _stream_emit_rows) so publish order always
                        # matches seq order — claiming it earlier let a
                        # lower-seq 'replace' land after a higher-seq
                        # update and be wrongly superseded by clients.
                        with self._lock:
                            seq = st["seq"]
                            st["seq"] += 1
                        try:
                            outputs = self.engine.execute_plan(
                                plan, bridge_inputs=by_bridge
                            )
                        except Exception as e:
                            self.bus.publish(
                                f"query.{qid}.results",
                                {"error": f"{self.agent_id}: {e}",
                                 "trace": traceback.format_exc()},
                            )
                            return
                        for name, batch in outputs.items():
                            self.bus.publish(
                                f"query.{qid}.results",
                                {"table": name, "batch": batch, "seq": seq,
                                 "mode": "replace", "agent": self.agent_id},
                            )
                with self._lock:
                    if not st["dirty"]:
                        return
        finally:
            with self._lock:
                st["merging"] = False


class PEMAgent(Agent):
    """Per-node data agent: ingest push target + data fragments
    (``pem_manager.h:39``)."""

    processes_data = True
    accepts_remote_sources = False

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # The PEM's ingest is bounded by the table-store byte budget
        # from the first append (pem_manager.cc:86-104 InitSchemas) —
        # installed as lazy per-table budgets so synthetic/partial
        # schemas in tests and tools still shape tables from their
        # first append.
        from ..ingest.schemas import table_budgets

        self.engine.table_store.table_budgets = table_budgets()


class KelvinAgent(Agent):
    """Compute-only merge agent (``kelvin_manager.h:31``)."""

    processes_data = False
    accepts_remote_sources = True
