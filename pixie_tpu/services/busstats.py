"""Transport-tier telemetry: counters/histograms for the message path.

Every tier of the engine has an observability surface — traces,
device programs, storage freshness, CPU profiles — except the bus the
whole cluster rides. ``BusStats`` is that surface: one lock-guarded
accumulator per bus (``MessageBus`` and ``RemoteBus`` each own one)
that the hot publish/deliver path stamps with monotonic clock reads
only, mirrored into the process-wide Prometheus registry and folded
into the ``__bus__`` telemetry ring on the heartbeat cadence by
``telemetry.BusStatsCollector``.

Cardinality discipline: raw topics embed query ids and agent ids
(``query.{qid}.ack``, ``agent.{aid}.execute``), so every metric label
uses :func:`topic_class` — a pure normalizer to a BOUNDED class set —
and the accumulator hard-caps distinct tracked keys at
``MAX_TRACKED_KEYS``, overflowing into ``"other"`` rather than growing
without bound on a hostile topic stream.

Lock discipline (pxlock): registry mirrors are updated OUTSIDE the
``BusStats`` lock — the accumulator lock and the metrics-registry lock
are never nested, so neither lockdep nor the static lock-order rule
ever sees an edge between them.
"""
from __future__ import annotations

import bisect
import logging
import threading
import time

from ..config import get_flag
from .observability import _interpolate_quantiles, default_registry

# Finer-than-default buckets: dispatcher lag and handler service time
# are µs-to-ms scale (the default 5ms-first bucket would flatten them),
# while a saturated queue or a stalled peer reaches seconds.
BUS_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)

# Hard bound on distinct (kind, key, direction) rows one BusStats
# tracks; past it, new keys collapse into "other". 256 >> the real
# class count (a handful of subsystem prefixes x verbs), so overflow
# only ever triggers on a topic-name bug — which the "other" row then
# makes visible instead of hiding behind unbounded growth.
MAX_TRACKED_KEYS = 256

# Ring size for MessageBus.handler_errors / RemoteBus.handler_errors.
HANDLER_ERROR_RING = 256

_slow_log = logging.getLogger("pixie_tpu.slow_handler")


def topic_class(topic: str) -> str:
    """Normalize a raw topic to a bounded-cardinality class.

    ``query.{qid}.ack`` -> ``query.ack``; ``agent.{aid}.execute`` ->
    ``agent.execute``; reply inboxes (``_inbox.{uuid}``) -> ``_inbox``;
    one- and two-part topics (``agent.register``, ``telemetry.spans``)
    are already classes and pass through; anything else deeper than two
    parts keeps only its subsystem prefix (``foo.a.b.c`` -> ``foo.*``).
    """
    if topic.startswith("_inbox."):
        return "_inbox"
    parts = topic.split(".")
    if len(parts) <= 2:
        return topic
    if parts[0] in ("query", "agent"):
        return f"{parts[0]}.{parts[-1]}"
    return f"{parts[0]}.*"


def payload_bytes(obj, _depth: int = 0) -> int:
    """Cheap payload-size estimate (NOT a serialization): strings and
    bytes count their length, scalars a flat 8, containers recurse with
    bounded depth and per-level sampling so a huge bridge payload costs
    O(1) to estimate. Close enough for byte accounting; the netbus
    frame counters carry the true wire bytes."""
    if isinstance(obj, (bytes, bytearray, str)):
        return len(obj) or 1
    if isinstance(obj, (int, float, bool)) or obj is None:
        return 8
    if _depth >= 3:
        return 64
    if isinstance(obj, dict):
        n = 0
        for i, (k, v) in enumerate(obj.items()):
            if i >= 8:
                n += (len(obj) - 8) * max(n // 8, 8)
                break
            n += payload_bytes(k, _depth + 1) + payload_bytes(v, _depth + 1)
        return n
    if isinstance(obj, (list, tuple)):
        n = 0
        for i, v in enumerate(obj):
            if i >= 8:
                # Extrapolate the unsampled tail from the sampled head.
                n += (len(obj) - 8) * max(n // 8, 8)
                break
            n += payload_bytes(v, _depth + 1)
        return n
    return 64


class _SmallHist:
    """Fixed-bucket histogram over BUS_BUCKETS (seconds). Mutated only
    under the owning BusStats lock; quantiles share the registry's
    interpolation so busz/__bus__ p50/p99 agree with /metrics."""

    __slots__ = ("counts", "count", "sum")

    def __init__(self):
        self.counts = [0] * (len(BUS_BUCKETS) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(BUS_BUCKETS, v)] += 1
        self.count += 1
        self.sum += v

    def quantiles(self, qs=(0.5, 0.99)) -> dict | None:
        """{q: seconds} via the registry's shared interpolation."""
        if self.count == 0:
            return None
        return _interpolate_quantiles(
            BUS_BUCKETS, self.counts, self.count, qs
        )


class _ClsState:
    """Per-topic-class cached handles: the row lists, internal
    histograms, and bound registry mirrors resolved ONCE, so the
    per-message path is a dict get plus list arithmetic — no tuple-key
    or label-dict construction per event. Bounded alongside the intern
    set (one entry per interned class)."""

    __slots__ = (
        "key", "pub_row", "del_row", "lag_h", "svc_h",
        "pub_mir", "del_mir",
        "m_pub", "b_pub", "m_del", "b_del",
        "lag", "svc", "errs", "slow", "qhw",
    )

    def __init__(self, key: str):
        self.key = key
        # Row lists / hists attach lazily so pub-only classes never
        # grow zero deliver rows in snapshot() (and vice versa).
        self.pub_row = None
        self.del_row = None
        self.lag_h = None
        self.svc_h = None
        # [msgs, bytes] already flushed into the registry counters —
        # the msgs/bytes mirrors batch every 32nd event per class (the
        # registry lock would otherwise be contended once per message
        # from the publisher thread). At most 31 events stale; exact
        # after every BusStats.snapshot().
        self.pub_mir = [0, 0]
        self.del_mir = [0, 0]


class BusStats:
    """Per-bus transport accumulator + registry mirror.

    Rows are keyed (kind, key, direction):

    - ``("bus", topic_class, "pub"|"deliver")`` — in-process messages;
      deliver rows carry the dispatch-lag / service-time histograms,
      the queue high-water mark, and handler-error counts.
    - ``("net", peer, "send"|"recv")`` — wire frames/bytes; send rows
      carry the send-stall (``_send_lock`` wait) histogram.
    - ``("net", peer, "conn")`` — connection events: msgs counts
      connects, errors counts drops + auth failures.
    - ``("rpc", peer, "request")`` — request/reply round trips; the lag
      histogram is the RTT, errors are timeouts/failures.

    ``snapshot()`` emits the rows in ``__bus__`` column shape.
    """

    def __init__(self, registry=None):
        self.registry = registry or default_registry
        self._lock = threading.Lock()
        # (kind, key, direction) -> [msgs, bytes, errors]
        self._rows: dict[tuple, list] = {}
        # (kind, key, direction, which) -> _SmallHist
        self._hists: dict[tuple, _SmallHist] = {}
        # topic_class -> monotonic queue-depth high-water
        self._qhw: dict[str, int] = {}
        self._keys: set[str] = set()
        # Cached bound-metric handles, keyed (metric, key[, direction]).
        # Read/insert without the stats lock: dict ops are GIL-atomic
        # and a racing double-insert just builds an equivalent bound.
        self._handles: dict[tuple, object] = {}
        # Per-topic-class handle structs for the in-process hot path,
        # keyed by INTERNED class only (bounded; hostile topics past
        # the intern cap pay the slow path into the "other" entry).
        self._cls_cache: dict[str, _ClsState] = {}
        # slow_handler_threshold_ms, re-read from the flag store every
        # 64th handled message: the hot path skips the flag lookup,
        # toggles still land within one heartbeat of traffic.
        self._slow_ms = 0.0
        self._handled_n = 0
        r = self.registry
        self._m_msgs = r.counter(
            "pixie_bus_msgs_total",
            "Messages through the in-process bus by topic class and "
            "direction (pub = publish calls, deliver = per-subscriber "
            "enqueues).")
        self._m_bytes = r.counter(
            "pixie_bus_bytes_total",
            "Estimated payload bytes through the in-process bus by "
            "topic class and direction.")
        self._m_lag = r.histogram(
            "pixie_bus_dispatch_lag_seconds",
            "Publish-to-handler-entry latency per topic class (the "
            "backpressure signal: a deep queue shows up here first).",
            buckets=BUS_BUCKETS)
        self._m_svc = r.histogram(
            "pixie_bus_handler_seconds",
            "Handler service time per topic class.",
            buckets=BUS_BUCKETS)
        self._m_qhw = r.gauge(
            "pixie_bus_queue_high_water",
            "Monotonic per-topic-class subscription queue depth "
            "high-water mark.")
        self._m_errs = r.counter(
            "pixie_bus_handler_errors_total",
            "Handler exceptions per topic class (true cumulative count;"
            " the busz ring keeps only the most recent).")
        self._m_slow = r.counter(
            "pixie_bus_slow_handlers_total",
            "Handlers slower than slow_handler_threshold_ms per topic "
            "class.")
        self._m_frames = r.counter(
            "pixie_net_frames_total",
            "Wire-bus frames by peer and direction.")
        self._m_net_bytes = r.counter(
            "pixie_net_bytes_total",
            "Wire-bus bytes (length prefix + encoded frame) by peer "
            "and direction — the cluster's wire-byte ground truth.")
        self._m_rtt = r.histogram(
            "pixie_net_request_seconds",
            "Request/reply round-trip time by peer.",
            buckets=BUS_BUCKETS)
        self._m_stall = r.histogram(
            "pixie_net_send_stall_seconds",
            "Time spent waiting for the frame send lock by peer (a "
            "slow/stalled peer backs up here).",
            buckets=BUS_BUCKETS)
        self._m_connects = r.counter(
            "pixie_net_connects_total",
            "Wire-bus connections established by peer (reconnects "
            "advance this).")
        self._m_drops = r.counter(
            "pixie_net_drops_total",
            "Wire-bus connections lost (error/EOF teardown) by peer.")
        self._m_auth_fail = r.counter(
            "pixie_net_auth_failures_total",
            "Wire-bus authentication failures by peer.")

    # -- internal -------------------------------------------------------------
    def _intern(self, key: str) -> str:
        """Caller holds self._lock. Bound distinct tracked keys."""
        if key in self._keys:
            return key
        if len(self._keys) >= MAX_TRACKED_KEYS:
            return "other"
        self._keys.add(key)
        return key

    def _row(self, kind: str, key: str, direction: str) -> list:
        """Caller holds self._lock."""
        r = self._rows.get((kind, key, direction))
        if r is None:
            r = self._rows[(kind, key, direction)] = [0, 0, 0]
        return r

    def _hist(self, kind: str, key: str, direction: str,
              which: str) -> _SmallHist:
        """Caller holds self._lock."""
        h = self._hists.get((kind, key, direction, which))
        if h is None:
            h = self._hists[(kind, key, direction, which)] = _SmallHist()
        return h

    def _bound(self, metric, **labels):
        key = (id(metric), tuple(sorted(labels.items())))
        b = self._handles.get(key)
        if b is None:
            b = self._handles[key] = metric.labels(**labels)
        return b

    def _cls_state(self, cls: str) -> _ClsState:
        """Resolve (intern + build) the per-class handle struct. Cached
        under the INTERNED key only, so the cache stays bounded; a
        racing double-build just produces equivalent bound handles."""
        with self._lock:
            key = self._intern(cls)
        cs = self._cls_cache.get(key)
        if cs is None:
            cs = _ClsState(key)
            cs.m_pub = self._m_msgs.labels(topic_class=key,
                                           direction="pub")
            cs.b_pub = self._m_bytes.labels(topic_class=key,
                                            direction="pub")
            cs.m_del = self._m_msgs.labels(topic_class=key,
                                           direction="deliver")
            cs.b_del = self._m_bytes.labels(topic_class=key,
                                            direction="deliver")
            cs.lag = self._m_lag.labels(topic_class=key)
            cs.svc = self._m_svc.labels(topic_class=key)
            cs.errs = self._m_errs.labels(topic_class=key)
            cs.slow = self._m_slow.labels(topic_class=key)
            cs.qhw = self._m_qhw.labels(topic_class=key)
            self._cls_cache[key] = cs
        return cs

    def _mirror_cls(self, cs: _ClsState) -> None:
        """Flush this class's pending msgs/bytes counter deltas into
        the registry. Delta computed and committed under the BusStats
        lock, APPLIED outside it — the no-lock-nesting rule."""
        dp = dd = None
        with self._lock:
            r = cs.pub_row
            if r is not None and r[0] != cs.pub_mir[0]:
                dp = (r[0] - cs.pub_mir[0], r[1] - cs.pub_mir[1])
                cs.pub_mir[0], cs.pub_mir[1] = r[0], r[1]
            r = cs.del_row
            if r is not None and r[0] != cs.del_mir[0]:
                dd = (r[0] - cs.del_mir[0], r[1] - cs.del_mir[1])
                cs.del_mir[0], cs.del_mir[1] = r[0], r[1]
        if dp is not None:
            cs.m_pub.inc(dp[0])
            cs.b_pub.inc(dp[1])
        if dd is not None:
            cs.m_del.inc(dd[0])
            cs.b_del.inc(dd[1])

    # -- in-process bus hot path ---------------------------------------------
    def on_publish(self, topic: str, msg) -> tuple[str, int]:
        """Count one publish; returns (topic_class, payload estimate)
        so the fan-out can stamp per-subscriber rows without repeating
        the estimate."""
        cls = topic_class(topic)
        nb = payload_bytes(msg)
        cs = self._cls_cache.get(cls) or self._cls_state(cls)
        with self._lock:
            r = cs.pub_row
            if r is None:
                r = cs.pub_row = self._row("bus", cs.key, "pub")
            r[0] += 1
            r[1] += nb
            n = r[0]
        if not n & 0x1F:
            self._mirror_cls(cs)
        return cs.key, nb

    def on_deliver(self, cls: str, nbytes: int, depth: int) -> None:
        """One per-subscriber enqueue; ``depth`` is the subscription
        queue depth observed at enqueue time (the high-water feed)."""
        cs = self._cls_cache.get(cls) or self._cls_state(cls)
        new_hw = 0
        with self._lock:
            r = cs.del_row
            if r is None:
                r = cs.del_row = self._row("bus", cs.key, "deliver")
            r[0] += 1
            r[1] += nbytes
            n = r[0]
            if depth > self._qhw.get(cs.key, 0):
                self._qhw[cs.key] = new_hw = depth
        if not n & 0x1F:
            self._mirror_cls(cs)
        if new_hw:
            cs.qhw.set(new_hw)

    def on_handled(self, cls: str, topic: str, lag_s: float,
                   service_s: float, error: bool = False) -> None:
        """Handler completed: stamp dispatch lag + service time, count
        errors, and feed the slow-handler log (same shape as the
        slow-query log: threshold flag, dedicated logger, counter)."""
        cs = self._cls_cache.get(cls) or self._cls_state(cls)
        with self._lock:
            lh = cs.lag_h
            if lh is None:
                lh = cs.lag_h = self._hist("bus", cs.key, "deliver",
                                           "lag")
                cs.svc_h = self._hist("bus", cs.key, "deliver",
                                      "service")
            lh.observe(lag_s)
            cs.svc_h.observe(service_s)
            if error:
                self._row("bus", cs.key, "deliver")[2] += 1
            n = self._handled_n
            self._handled_n = n + 1
        cs.lag.observe(lag_s)
        cs.svc.observe(service_s)
        if error:
            cs.errs.inc()
        if not n & 0x3F:  # periodic flag refresh (see __init__)
            self._slow_ms = float(get_flag("slow_handler_threshold_ms"))
        thresh_ms = self._slow_ms
        if thresh_ms > 0 and service_s * 1e3 >= thresh_ms:
            cs.slow.inc()
            _slow_log.warning(
                "slow handler: topic=%s class=%s service_ms=%.2f "
                "lag_ms=%.2f threshold_ms=%.1f%s",
                topic, cs.key, service_s * 1e3, lag_s * 1e3, thresh_ms,
                " (handler raised)" if error else "")

    # -- wire bus -------------------------------------------------------------
    def on_frame(self, peer: str, direction: str, nbytes: int) -> None:
        with self._lock:
            peer = self._intern(peer)
            r = self._row("net", peer, direction)
            r[0] += 1
            r[1] += nbytes
        self._bound(self._m_frames, peer=peer, direction=direction).inc()
        self._bound(self._m_net_bytes, peer=peer,
                    direction=direction).inc(nbytes)

    def on_send_stall(self, peer: str, stall_s: float) -> None:
        with self._lock:
            peer = self._intern(peer)
            self._hist("net", peer, "send", "lag").observe(stall_s)
        self._bound(self._m_stall, peer=peer).observe(stall_s)

    def on_conn_event(self, peer: str, event: str) -> None:
        """``event`` in ("connect", "drop", "auth_failure")."""
        with self._lock:
            peer = self._intern(peer)
            r = self._row("net", peer, "conn")
            if event == "connect":
                r[0] += 1
            else:
                r[2] += 1
        m = {"connect": self._m_connects, "drop": self._m_drops,
             "auth_failure": self._m_auth_fail}[event]
        self._bound(m, peer=peer).inc()

    def on_request(self, peer: str, rtt_s: float,
                   error: bool = False) -> None:
        with self._lock:
            peer = self._intern(peer)
            r = self._row("rpc", peer, "request")
            r[0] += 1
            if error:
                r[2] += 1
            self._hist("rpc", peer, "request", "lag").observe(rtt_s)
        self._bound(self._m_rtt, peer=peer).observe(rtt_s)

    # -- snapshots ------------------------------------------------------------
    def snapshot(self) -> list[dict]:
        """Cumulative rows in ``__bus__`` column shape (monotonic
        counters; ``px.max`` per key recovers the latest fold)."""
        # Settle the batched registry mirrors first: every snapshot
        # consumer (busz, heartbeat summary, __bus__ fold) doubles as
        # a flush point, so /metrics is exact at those cadences.
        for cs in list(self._cls_cache.values()):
            self._mirror_cls(cs)
        rows = []
        with self._lock:
            for (kind, key, direction), r in sorted(self._rows.items()):
                lag = self._hists.get((kind, key, direction, "lag"))
                svc = self._hists.get((kind, key, direction, "service"))
                lq = lag.quantiles() if lag is not None else None
                sq = svc.quantiles() if svc is not None else None
                rows.append({
                    "kind": kind,
                    "topic_class": key,
                    "direction": direction,
                    "msgs": r[0],
                    "bytes": r[1],
                    "errors": r[2],
                    "lag_p50_ms": (lq[0.5] * 1e3) if lq else 0.0,
                    "lag_p99_ms": (lq[0.99] * 1e3) if lq else 0.0,
                    "service_p50_ms": (sq[0.5] * 1e3) if sq else 0.0,
                    "service_p99_ms": (sq[0.99] * 1e3) if sq else 0.0,
                    "queue_high_water": (
                        self._qhw.get(key, 0) if kind == "bus" else 0
                    ),
                })
        return rows

    def queue_high_water(self) -> dict[str, int]:
        with self._lock:
            return dict(self._qhw)
