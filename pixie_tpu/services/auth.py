"""Shared-secret bearer tokens + AuthContext.

Reference parity: the reference threads JWT claims through every service
via an AuthContext (``/root/reference/src/shared/services/authcontext/
context.go:38``) minted from a shared signing key
(``utils/token_utils.go``). Here the analog is an HMAC-SHA256-signed
bearer token checked at the two trust boundaries: netbus connect
(``netbus.BusServer``) and broker API request handling
(``query_broker.QueryBroker.serve``). Services inside one process trust
their in-process bus, as the reference trusts intra-pod calls.

Token format: ``base64url(json payload) "." hex hmac`` — payload is
``{"sub": subject, "exp": unix_seconds, "claims": {...}}``. No external
JWT dependency; the signature covers the exact encoded payload.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from dataclasses import dataclass, field

class AuthError(Exception):
    pass


@dataclass(frozen=True)
class AuthContext:
    """Verified identity attached to a connection/request
    (authcontext.AuthContext analog)."""

    subject: str
    expiry_s: float
    claims: dict = field(default_factory=dict)

    @property
    def authenticated(self) -> bool:
        return bool(self.subject)


#: Context for deployments with auth disabled (empty secret).
ANONYMOUS = AuthContext(subject="", expiry_s=float("inf"))


def sign_token(secret: str, subject: str, ttl_s: float = 3600.0,
               claims: dict | None = None) -> str:
    if not secret:
        raise AuthError("cannot sign tokens with an empty secret")
    payload = json.dumps(
        {"sub": subject, "exp": time.time() + ttl_s, "claims": claims or {}},
        separators=(",", ":"), sort_keys=True,
    ).encode()
    body = base64.urlsafe_b64encode(payload).decode().rstrip("=")
    sig = hmac.new(secret.encode(), body.encode(), hashlib.sha256).hexdigest()
    return f"{body}.{sig}"


def verify_token(secret: str, token: str) -> AuthContext:
    """Validate signature + expiry; raises AuthError on any failure."""
    if not secret:
        return ANONYMOUS  # auth disabled
    if not token or not isinstance(token, str) or "." not in token:
        raise AuthError("missing bearer token")
    body, _, sig = token.rpartition(".")
    want = hmac.new(secret.encode(), body.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(want, sig):
        raise AuthError("bad token signature")
    try:
        pad = "=" * (-len(body) % 4)
        payload = json.loads(base64.urlsafe_b64decode(body + pad))
    except Exception as e:
        raise AuthError(f"malformed token payload: {e}") from None
    exp = float(payload.get("exp", 0))
    if exp < time.time():
        raise AuthError("token expired")
    return AuthContext(
        subject=str(payload.get("sub", "")), expiry_s=exp,
        claims=dict(payload.get("claims") or {}),
    )
