"""Cron ScriptRunner: periodically execute stored PxL scripts.

Reference parity: the query broker's cron ``ScriptRunner``
(``src/vizier/services/query_broker/script_runner/script_runner.go:62``):
it keeps a store-backed set of cron scripts, reconciles updates against a
source of truth by checksum (``:441-480`` CompareScriptState), and runs
each script on its configured frequency through the normal query path,
shipping results to the script's export sinks (OTel plugins).

Here the runner executes through any target exposing
``execute_script(query, ...)`` (QueryBroker) or ``execute_query`` (a bare
Engine), persists scripts in a Datastore, and exposes an explicit
``tick(now_s)`` so services drive it from their own loop (tests never
sleep); ``run_forever`` is the thread wrapper.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils.datastore import MemoryDatastore

_PREFIX = "cron_script/"


@dataclass
class CronScript:
    script_id: str
    pxl: str
    frequency_s: float
    enabled: bool = True

    @property
    def checksum(self) -> str:
        return hashlib.sha256(
            f"{self.pxl}\x00{self.frequency_s}\x00{self.enabled}".encode()
        ).hexdigest()[:16]

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "script_id": self.script_id,
                "pxl": self.pxl,
                "frequency_s": self.frequency_s,
                "enabled": self.enabled,
            }
        ).encode()

    @classmethod
    def from_bytes(cls, b: bytes) -> "CronScript":
        return cls(**json.loads(b.decode()))


@dataclass
class RunRecord:
    script_id: str
    started_s: float
    ok: bool
    error: str = ""
    row_counts: dict = field(default_factory=dict)


class ScriptRunner:
    """Store-backed cron script executor."""

    def __init__(self, target, store=None, on_result=None):
        self.target = target
        self.store = store if store is not None else MemoryDatastore()
        self.on_result = on_result  # callable(script, outputs) or None
        self._next_due: dict[str, float] = {}
        self._lock = threading.Lock()
        self.history: list[RunRecord] = []
        self._stop = threading.Event()

    # -- script management (the cloud source-of-truth surface) -------------
    def upsert(self, script: CronScript) -> None:
        self.store.set(_PREFIX + script.script_id, script.to_bytes())
        with self._lock:
            self._next_due.setdefault(script.script_id, 0.0)

    def delete(self, script_id: str) -> None:
        self.store.delete(_PREFIX + script_id)
        with self._lock:
            self._next_due.pop(script_id, None)

    def scripts(self) -> dict[str, CronScript]:
        return {
            k[len(_PREFIX):]: CronScript.from_bytes(v)
            for k, v in self.store.get_with_prefix(_PREFIX)
        }

    def compare_state(self, truth: dict[str, CronScript]) -> None:
        """Reconcile the stored set against a source of truth by checksum
        (script_runner.go:441-480 CompareScriptState)."""
        have = self.scripts()
        for sid, s in truth.items():
            if sid not in have or have[sid].checksum != s.checksum:
                self.upsert(s)
        for sid in list(have):
            if sid not in truth:
                self.delete(sid)

    # -- execution ---------------------------------------------------------
    def tick(self, now_s: Optional[float] = None) -> list[RunRecord]:
        """Run every due script once; returns records for this tick."""
        now = time.time() if now_s is None else now_s
        ran = []
        for sid, script in sorted(self.scripts().items()):
            if not script.enabled:
                continue
            with self._lock:
                due = self._next_due.get(sid, 0.0)
                if now < due:
                    continue
                self._next_due[sid] = now + script.frequency_s
            rec = self._run_one(script, now)
            ran.append(rec)
            self.history.append(rec)
        del self.history[:-200]  # bounded history
        return ran

    def _run_one(self, script: CronScript, now: float) -> RunRecord:
        try:
            if hasattr(self.target, "execute_script"):
                result = self.target.execute_script(script.pxl)
                outputs = result["tables"]  # broker result envelope
            else:
                outputs = self.target.execute_query(script.pxl)
            if self.on_result is not None:
                self.on_result(script, outputs)
            counts = {
                k: getattr(v, "length", None)
                for k, v in outputs.items()
                if isinstance(k, str)
            }
            return RunRecord(script.script_id, now, True, row_counts=counts)
        except Exception as e:  # a broken script must not kill the loop
            return RunRecord(script.script_id, now, False, error=repr(e)[:300])

    def run_forever(self, poll_s: float = 1.0) -> threading.Thread:
        def loop():
            while not self._stop.wait(poll_s):
                self.tick()

        t = threading.Thread(target=loop, name="cron-script-runner", daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
