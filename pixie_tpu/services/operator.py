"""Deployment operator: reconcile desired roles against live processes.

Reference parity: the Pixie operator
(``/root/reference/src/operator/controllers`` — a controller loop that
reconciles a Vizier spec: deploys components, watches their health, and
auto-recovers failed ones). There is no k8s API in this environment, so
the reconciliation target is the process level: the same deploy roles
``pixie_tpu.deploy`` exposes (broker / pem / kelvin), kept at their
desired replica counts with crash restarts and exponential backoff —
the failure-detection/recovery story for a deployment, above the
per-query degraded-mesh handling inside the engine.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field


def _terminate_and_reap(proc, timeout_s: float = 5.0) -> None:
    """SIGTERM then wait — an unreaped child stays a zombie, which reads
    as alive to liveness probes."""
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=timeout_s)
    except Exception:
        proc.kill()
        try:
            proc.wait(timeout=timeout_s)
        except Exception:
            pass


@dataclass(frozen=True)
class RoleSpec:
    """Desired state for one role (the Vizier CR spec analog)."""

    name: str
    replicas: int = 1
    #: Command argv; None = the in-repo deploy role entrypoint.
    command: tuple | None = None
    env: tuple = ()  # ((key, value), ...) extra environment

    def argv(self) -> list:
        if self.command is not None:
            return list(self.command)
        return [sys.executable, "-m", "pixie_tpu.deploy", self.name]


@dataclass
class _Instance:
    proc: object = None
    restarts: int = 0
    backoff_until: float = 0.0
    last_exit: int | None = None
    started_at: float = 0.0


class Reconciler:
    """One reconcile loop over {role -> RoleSpec}.

    ``reconcile()`` is a single pass (the controller's Reconcile());
    ``run_as_thread`` re-runs it on an interval. Replica reductions
    terminate the highest indices first; crashed instances restart with
    exponential backoff capped at ``max_backoff_s``.
    """

    def __init__(self, specs: dict | None = None,
                 check_interval_s: float = 1.0,
                 base_backoff_s: float = 0.5, max_backoff_s: float = 30.0,
                 healthy_reset_s: float = 600.0, spawn=None):
        self.specs: dict[str, RoleSpec] = dict(specs or {})
        self.check_interval_s = check_interval_s
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        # A crash after this much healthy running resets the backoff
        # ladder (the k8s CrashLoopBackOff reset the docstring's parity
        # claim implies) — otherwise a once-a-day crasher escalates to
        # worst-case recovery latency forever.
        self.healthy_reset_s = healthy_reset_s
        self._spawn = spawn or self._spawn_subprocess
        self._instances: dict[tuple, _Instance] = {}  # (role, idx)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.events: list[tuple] = []  # (ts, kind, role, idx)

    @staticmethod
    def _spawn_subprocess(spec: RoleSpec, idx: int):
        import os

        env = dict(os.environ)
        # Children must never inherit the operator spec — a spec that
        # (mis)lists the operator role would otherwise fork-bomb.
        env.pop("PIXIE_TPU_OPERATOR_SPEC", None)
        env.update(dict(spec.env))
        env["PIXIE_TPU_REPLICA_INDEX"] = str(idx)
        return subprocess.Popen(
            spec.argv(), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def apply(self, specs: dict) -> None:
        """Replace the desired state (CR update); the next reconcile
        converges to it."""
        with self._lock:
            self.specs = dict(specs)

    _MAX_EVENTS = 1000

    def _record(self, kind: str, role: str, idx: int) -> None:
        self.events.append((time.time(), kind, role, idx))
        if len(self.events) > self._MAX_EVENTS:
            del self.events[: len(self.events) - self._MAX_EVENTS]

    def _backoff(self, inst, now: float) -> None:
        inst.backoff_until = now + min(
            self.base_backoff_s * (2 ** min(inst.restarts, 16)),
            self.max_backoff_s,
        )

    def reconcile(self) -> None:
        now = time.monotonic()
        to_reap, to_spawn = [], []
        with self._lock:
            desired = {
                (r, i)
                for r, spec in self.specs.items()
                for i in range(max(spec.replicas, 0))
            }
            # Scale down / removed roles: terminate extras (reaping
            # happens OUTSIDE the lock — SIGTERM-ignoring children must
            # not stall status()/apply() callers).
            for key in [k for k in self._instances if k not in desired]:
                inst = self._instances.pop(key)
                to_reap.append(inst.proc)
                self._record("terminated", *key)
            # Decide which instances need (re)spawning; the fork+exec
            # itself also happens OUTSIDE the lock.
            for key in sorted(desired):
                role, idx = key
                inst = self._instances.setdefault(key, _Instance())
                alive = inst.proc is not None and inst.proc.poll() is None
                if alive:
                    continue
                if inst.proc is not None:
                    # Record the crash ONCE; the dead Popen is dropped so
                    # backoff passes don't re-record it. A crash after a
                    # long healthy run resets the backoff ladder.
                    inst.last_exit = inst.proc.returncode
                    inst.proc = None
                    if (inst.started_at
                            and now - inst.started_at > self.healthy_reset_s):
                        inst.restarts = 0
                    self._record("crashed", role, idx)
                if now < inst.backoff_until:
                    continue
                # Claim the slot so a concurrent reconcile can't double-
                # spawn; the real backoff replaces this after the spawn.
                inst.backoff_until = now + 3600.0
                to_spawn.append((key, self.specs[role]))
        spawned = []
        for key, spec in to_spawn:
            try:
                spawned.append((key, self._spawn(spec, key[1])))
            except Exception:
                spawned.append((key, None))
        with self._lock:
            for key, proc in spawned:
                inst = self._instances.get(key)
                if inst is None:  # scaled away while spawning
                    if proc is not None:
                        to_reap.append(proc)
                    continue
                if proc is None:
                    # Bad command/spec: count it, back off — a silent
                    # hot retry loop would hide the misconfiguration.
                    inst.restarts += 1
                    self._record("spawn_failed", *key)
                    self._backoff(inst, now)
                    continue
                inst.proc = proc
                inst.started_at = now
                first = inst.restarts == 0 and inst.last_exit is None
                self._record("started" if first else "restarted", *key)
                if not first:
                    inst.restarts += 1
                self._backoff(inst, now)
        for proc in to_reap:
            _terminate_and_reap(proc)

    def status(self) -> list:
        """Per-instance health (the operator's status subresource)."""
        with self._lock:
            out = []
            for (role, idx), inst in sorted(self._instances.items()):
                alive = inst.proc is not None and inst.proc.poll() is None
                out.append({
                    "role": role, "replica": idx, "alive": alive,
                    "pid": getattr(inst.proc, "pid", None),
                    "restarts": inst.restarts,
                    "last_exit": inst.last_exit,
                })
            return out

    # -- lifecycle -----------------------------------------------------------
    def run_as_thread(self) -> threading.Thread:
        self._thread = threading.Thread(
            target=self._loop, name="operator", daemon=True
        )
        self._thread.start()
        return self._thread

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.reconcile()
            self._stop.wait(self.check_interval_s)

    def stop(self, terminate: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if terminate:
            with self._lock:
                procs = [inst.proc for inst in self._instances.values()]
            for proc in procs:
                _terminate_and_reap(proc)


def specs_from_config(cfg: dict) -> dict:
    """{role: replicas|{replicas, command, env}} -> {role: RoleSpec}.

    Raises ValueError with a readable message on malformed entries (a
    bare 'role:' line parses to None; replicas must be ints)."""
    out = {}
    for role, v in cfg.items():
        if isinstance(v, bool) or not isinstance(v, (int, dict)):
            raise ValueError(
                f"operator spec: role {role!r} must map to an int replica "
                f"count or a mapping, got {type(v).__name__}"
            )
        if isinstance(v, int):
            out[role] = RoleSpec(name=role, replicas=v)
        else:
            try:
                replicas = int(v.get("replicas", 1))
            except (TypeError, ValueError):
                raise ValueError(
                    f"operator spec: role {role!r} replicas must be an "
                    f"int, got {v.get('replicas')!r}"
                ) from None
            out[role] = RoleSpec(
                name=role,
                replicas=replicas,
                command=tuple(v["command"]) if v.get("command") else None,
                env=tuple((k, str(val)) for k, val in
                          (v.get("env") or {}).items()),
            )
    return out
