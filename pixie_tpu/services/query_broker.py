"""Query broker: compile, plan, dispatch, forward results.

Reference parity: ``src/vizier/services/query_broker`` — ExecuteScript
(``controllers/server.go:325``) compiles via the planner against the
live agent set, LaunchQuery publishes per-agent plans over the control
plane (``launch_query.go:36``), and a per-query QueryResultForwarder
(``query_result_forwarder.go:108,241,364``) streams results to the
client with producer/consumer watchdog timeouts and cancellation.
"""

from __future__ import annotations

import queue
import threading
import uuid

from ..exec.engine import QueryError
from ..planner import CompilerState, compile_mutations, compile_pxl
from ..planner.distributed import DistributedPlanner
from ..planner.distributed.coordinator import PlanningError
from ..udf.registry import Registry, default_registry
from .msgbus import MessageBus
from .tracker import AgentTracker


class QueryTimeout(QueryError):
    pass


class QueryResultForwarder:
    """Per-query result stream assembly with watchdog timeouts."""

    def __init__(self, bus: MessageBus):
        self.bus = bus
        self._lock = threading.Lock()
        self._active: dict[str, dict] = {}

    def register_query(self, qid: str, expected_data_agents: int):
        q: queue.Queue = queue.Queue()
        sub = self.bus.subscribe(f"query.{qid}.results", q.put)
        done_sub = self.bus.subscribe(f"query.{qid}.agent_done", q.put)
        with self._lock:
            self._active[qid] = {
                "queue": q,
                "subs": [sub, done_sub],
                "expected": expected_data_agents,
            }

    def wait(self, qid: str, timeout_s: float) -> dict:
        """Blocks until eos/error/timeout. Returns {table: HostBatch} plus
        per-agent exec stats; raises on error or watchdog expiry."""
        with self._lock:
            st = self._active[qid]
        outputs: dict = {}
        stats: dict = {}
        eos = False
        try:
            while True:
                if eos and len(stats) >= st["expected"]:
                    return {"tables": outputs, "agent_stats": stats}
                # After eos, per-agent stats may still be in flight on
                # their own dispatcher threads — drain with a short grace
                # window instead of returning a partial stats map.
                wait_s = min(timeout_s, 1.0) if eos else timeout_s
                try:
                    msg = st["queue"].get(timeout=wait_s)
                except queue.Empty:
                    if eos:
                        return {"tables": outputs, "agent_stats": stats}
                    # Watchdog fired (query_result_forwarder.go:241):
                    # cancel the query everywhere and fail the stream.
                    self.cancel(qid)
                    raise QueryTimeout(
                        f"query {qid} timed out after {timeout_s}s "
                        f"(stats so far: {sorted(stats)})"
                    ) from None
                if "error" in msg:
                    self.cancel(qid)
                    raise QueryError(msg["error"])
                if "exec_time_s" in msg:
                    stats[msg["agent"]] = {"exec_time_s": msg["exec_time_s"]}
                elif msg.get("eos"):
                    eos = True
                elif "table" in msg:
                    outputs[msg["table"]] = msg["batch"]
        finally:
            self._deregister(qid)

    def cancel(self, qid: str):
        self.bus.publish("query.cancel", {"qid": qid})

    def _deregister(self, qid: str):
        with self._lock:
            st = self._active.pop(qid, None)
        if st:
            for s in st["subs"]:
                s.unsubscribe()


class StreamHandle:
    """A live query's client handle: ``cancel()`` stops the agents'
    streaming cursors and detaches the subscriber."""

    def __init__(self, qid: str, broker: "QueryBroker", sub,
                 merge_agent: str = "", data_agents: tuple = ()):
        self.qid = qid
        self.merge_agent = merge_agent
        self.data_agents = tuple(data_agents)
        self._broker = broker
        self._sub = sub

    def cancel(self) -> None:
        self._broker._live_streams.pop(self.qid, None)
        self._broker.bus.publish("query.cancel", {"qid": self.qid})
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None


class QueryBroker:
    def __init__(
        self,
        bus: MessageBus,
        tracker: AgentTracker,
        registry: Registry | None = None,
        secret: str | None = None,
    ):
        from ..config import get_flag

        self.bus = bus
        self.tracker = tracker
        # Bearer-token check on served API requests (authcontext analog);
        # empty = auth disabled. Netbus connects are gated separately.
        self.secret = get_flag("bus_secret") if secret is None else secret
        from .vizier_funcs import bind_service_registry

        self.registry = bind_service_registry(
            registry or default_registry(), bus, "broker"
        )
        self.forwarder = QueryResultForwarder(bus)
        self.planner = DistributedPlanner(self.registry)
        # Dynamic-tracing support (the MutationExecutor dependency,
        # mutation_executor.go:84); wire a TracepointRegistry to enable.
        self.tracepoints = None
        # Every live stream's handle (qid -> StreamHandle): the stream
        # watchdog. A stream whose MERGE agent expires can never emit
        # again (data-agent loss re-merges from survivors instead), so
        # tracker expiry fails it loudly rather than leaving the client
        # on a forever-silent subscription (reference: the forwarder's
        # producer watchdog, query_result_forwarder.go).
        self._live_streams: dict = {}

        from .tracker import TOPIC_EXPIRED, TOPIC_REGISTER

        self._expiry_sub = self.bus.subscribe(
            TOPIC_EXPIRED,
            lambda msg: self._abort_streams_of(
                msg.get("agent_id"), "expired"
            ),
        )
        # A RE-registration of a PLANNED agent means a new incarnation
        # (restart): the old process's stream state — merge carries on
        # a kelvin, the streaming cursor + bridge on a data agent — is
        # gone even though the agent_id never expired (the operator
        # restarts faster than the tracker's expiry window). A restarted
        # data agent's slice would otherwise silently never rejoin the
        # view (a permanently partial live aggregate); aborting lets the
        # client re-plan against the new topology. The surviving-agent
        # resync case only follows an expiry, which already aborted
        # merge-dead streams and degraded data-dead ones visibly.
        self._register_sub = self.bus.subscribe(
            TOPIC_REGISTER,
            lambda msg: self._abort_streams_of(
                msg.get("agent_id"), "restarted (re-registered)",
                include_data_agents=True,
            ),
        )

    def _abort_streams_of(self, agent_id, why: str,
                          include_data_agents: bool = False) -> None:
        """Fail every live stream that planned ``agent_id`` as its merge
        agent (always) or as a data agent (``include_data_agents``):
        error to the client THEN cancel directly — cleanup must not
        depend on the client's on_update callback surviving (the bus
        swallows handler exceptions). The atomic pop makes the abort
        exactly-once even when expiry and re-registration race on
        separate dispatcher threads."""
        for qid, handle in list(self._live_streams.items()):
            if handle.merge_agent == agent_id:
                role = "merge agent"
            elif include_data_agents and agent_id in handle.data_agents:
                role = "data agent"
            else:
                continue
            if self._live_streams.pop(qid, None) is None:
                continue  # another aborter claimed it first
            self.bus.publish(
                f"query.{qid}.results",
                {"error": f"{role} {agent_id} {why}; "
                          f"live query {qid} aborted"},
            )
            handle.cancel()  # idempotent (entry already popped)

    def close(self) -> None:
        """Detach the broker from the bus: watchdog subscriptions, the
        served API topics (if serve() ran), and any still-live streams.
        Transient brokers on a shared bus must not keep reacting to
        agent lifecycle events after they're discarded."""
        for qid in list(self._live_streams):
            handle = self._live_streams.pop(qid, None)
            if handle is not None:
                handle.cancel()
        for sub in (self._expiry_sub, self._register_sub):
            sub.unsubscribe()
        for sub in getattr(self, "_serve_subs", []):
            sub.unsubscribe()

    def execute_script(
        self,
        query: str,
        timeout_s: float = 30.0,
        now_ns: int = 0,
        max_output_rows: int = 10_000,
        mutation_timeout_s: float = 10.0,
    ) -> dict:
        """The VizierService.ExecuteScript flow, end to end.

        Mutation phase first (MutationExecutor.Execute): pxtrace
        tracepoints deploy and the broker waits until their tables are
        schema-ready before compiling the query phase — so a script may
        query the very table its tracepoint creates.
        """
        compiler_state = CompilerState(
            schemas=self.tracker.schemas(),
            registry=self.registry,
            now_ns=now_ns,
            max_output_rows=max_output_rows,
        )
        mutation_states = None
        # Cheap gate: the mutation pass re-executes the script, so skip it
        # entirely unless the source can contain pxtrace at all.
        mutations = (
            compile_mutations(query, compiler_state)
            if "pxtrace" in query
            else []
        )
        if mutations:
            if self.tracepoints is None:
                raise QueryError(
                    "script contains pxtrace mutations but this broker has "
                    "no TracepointRegistry wired"
                )
            self.tracepoints.apply(mutations)
            from ..trace.spec import TracepointDeployment

            names = [
                m.name for m in mutations
                if isinstance(m, TracepointDeployment)
            ]
            mutation_states = self.tracepoints.wait_ready(
                names, timeout_s=mutation_timeout_s
            )
            failed = {n: s for n, s in mutation_states.items() if s != "RUNNING"}
            if failed:
                infos = {
                    n: (self.tracepoints.info(n) or {}).get("error", "")
                    for n in failed
                }
                raise QueryError(f"tracepoint deploy failed: {infos}")
            # Re-read schemas: the tracepoint tables now exist.
            compiler_state = CompilerState(
                schemas=self.tracker.schemas(),
                registry=self.registry,
                now_ns=now_ns,
                max_output_rows=max_output_rows,
            )
        state = self.tracker.distributed_state()  # fresh per query
        compiled = compile_pxl(query, compiler_state)
        if mutations and not compiled.outputs and not compiled.n_exports:
            return {
                "mutations": mutation_states,
                "tables": {},
                "agent_stats": {},
                "qid": None,
            }
        try:
            dplan = self.planner.plan(compiled.plan, state)
        except PlanningError as e:
            raise QueryError(str(e)) from e

        qid = uuid.uuid4().hex[:12]
        data_agents = list(dplan.data_agent_ids)
        if not dplan.kelvin_agent_ids:
            raise QueryError("no live agent available to run the query")
        merge_agent = dplan.kelvin_agent_ids[0]
        self.forwarder.register_query(qid, len(data_agents))

        # LaunchQuery: merge fragment first (so the router can accept
        # early bridge chunks), then the per-agent data fragments.
        self.bus.publish(
            f"agent.{merge_agent}.merge",
            {
                "qid": qid,
                "plan": dplan.merge_plan,
                "bridge_ids": [b.bridge_id for b in dplan.split.bridges],
                "data_agents": data_agents,
            },
        )
        for aid in data_agents:
            self.bus.publish(
                f"agent.{aid}.execute",
                {
                    "qid": qid,
                    "plan": dplan.split.before_blocking,
                    "merge_agent": merge_agent,
                },
            )
        result = self.forwarder.wait(qid, timeout_s)
        result["qid"] = qid
        result["distributed_plan"] = dplan
        if mutation_states is not None:
            result["mutations"] = mutation_states
        return result

    def execute_script_streaming(
        self,
        query: str,
        on_update,
        poll_interval_s: float = 0.25,
        now_ns: int = 0,
    ) -> "StreamHandle":
        """Live ExecuteScript (StreamResults analog,
        ``query_result_forwarder.go:470``): dispatch streaming fragments
        to the agents and deliver incremental result batches to
        ``on_update`` until ``handle.cancel()``.

        ``on_update`` receives dicts {table, batch, seq, mode, agent}
        where mode is "append" (new rows) or "replace" (full updated
        aggregate). Errors arrive as {error}.
        """
        compiler_state = CompilerState(
            schemas=self.tracker.schemas(),
            registry=self.registry,
            now_ns=now_ns,
            max_output_rows=1 << 62,  # live streams are unbounded
        )
        state = self.tracker.distributed_state()
        compiled = compile_pxl(query, compiler_state)
        try:
            dplan = self.planner.plan(compiled.plan, state)
        except PlanningError as e:
            raise QueryError(str(e)) from e
        # Validate streamability up front (one linear source chain): a
        # bad script should fail the call, not trickle errors later.
        from ..exec.streaming import _linearize

        _linearize(dplan.split.before_blocking)

        qid = uuid.uuid4().hex[:12]
        data_agents = list(dplan.data_agent_ids)
        if not dplan.kelvin_agent_ids:
            raise QueryError("no live agent available to run the query")
        merge_agent = dplan.kelvin_agent_ids[0]

        cell: dict = {}

        def _relay(msg):
            on_update(msg)
            if "error" in msg and cell.get("handle") is not None:
                # An errored stream never recovers: stop the agents'
                # polling loops instead of leaking them server-side.
                cell["handle"].cancel()

        sub = self.bus.subscribe(f"query.{qid}.results", _relay)
        handle = StreamHandle(qid, self, sub, merge_agent=merge_agent,
                              data_agents=data_agents)
        cell["handle"] = handle
        self._live_streams[qid] = handle
        # Close the planning window: if the merge agent expired between
        # the tracker snapshot and this registration, its one-shot
        # expiry event already fired — abort now instead of never (and
        # skip dispatch: no point starting cursors for a dead query).
        if not self.tracker.has_agent(merge_agent):
            self._abort_streams_of(merge_agent, "expired during planning")
            return handle
        self.bus.publish(
            f"agent.{merge_agent}.stream_merge",
            {
                "qid": qid,
                "plan": dplan.merge_plan,
                "bridge_ids": [b.bridge_id for b in dplan.split.bridges],
                "data_agents": data_agents,
            },
        )
        for aid in data_agents:
            self.bus.publish(
                f"agent.{aid}.stream_execute",
                {
                    "qid": qid,
                    "plan": dplan.split.before_blocking,
                    "merge_agent": merge_agent,
                    "poll_interval_s": poll_interval_s,
                },
            )
        return handle

    # -- bus API (the VizierService gRPC surface analog) ---------------------

    def serve(self) -> None:
        """Expose the broker on bus topics so remote clients (CLI/API over
        the framed-TCP netbus) can execute scripts and introspect the
        cluster — the api.vizierpb.VizierService analog
        (``src/api/proto/vizierpb/vizierapi.proto`` ExecuteScript).

        Topics (all request/reply via ``_reply_to``):
          broker.execute  {query, timeout_s?, max_output_rows?}
                          -> {ok, qid, tables, agent_stats} | {ok: False, error}
          broker.execute_stream {query, update_topic, poll_interval_s?}
                          -> {ok, qid}; incremental updates then flow to
                          ``update_topic`` as {table, batch, seq, mode}
                          (or {error}) until broker.stream_cancel {qid}
          broker.stream_cancel {qid} -> {ok}
          broker.schemas  {} -> {ok, schemas: {table: Relation}}
          broker.agents   {} -> {ok, agents: [agent info dict]}
          broker.scripts  {} -> {ok, scripts: [name]}
        """

        def _reply(msg, payload):
            inbox = msg.get("_reply_to")
            if inbox:
                self.bus.publish(inbox, payload)

        def _auth(msg):
            """Verify the request's bearer token; returns the AuthContext
            (threaded into handlers the way the reference's authcontext
            rides the gRPC metadata). No-op when auth is disabled."""
            from .auth import verify_token

            return verify_token(self.secret, msg.get("token"))

        def _guarded(handler):
            def wrapped(msg):
                from .auth import AuthError

                try:
                    msg["_auth"] = _auth(msg)
                except AuthError as e:
                    _reply(msg, {"ok": False, "error": f"AuthError: {e}"})
                    return
                handler(msg)

            return wrapped

        def _on_execute(msg):
            try:
                res = self.execute_script(
                    msg["query"],
                    timeout_s=float(msg.get("timeout_s", 30.0)),
                    now_ns=int(msg.get("now_ns", 0)),
                    max_output_rows=int(msg.get("max_output_rows", 10_000)),
                )
                _reply(msg, {
                    "ok": True,
                    "qid": res.get("qid"),
                    "tables": res.get("tables", {}),
                    "agent_stats": res.get("agent_stats", {}),
                    "mutations": res.get("mutations"),
                })
            except Exception as e:  # errors cross the wire as data
                _reply(msg, {"ok": False, "error": f"{type(e).__name__}: {e}"})

        def _on_execute_stream(msg):
            topic = msg.get("update_topic")
            try:
                if not topic:
                    raise QueryError("execute_stream needs an update_topic")

                def _push(u, _topic=topic):
                    # publish() reports delivery count: the client
                    # subscribed to its inbox before requesting, so zero
                    # receivers means it disconnected — reap the stream
                    # rather than polling for a ghost.
                    if self.bus.publish(_topic, u) == 0:
                        h = self._live_streams.pop(
                            handle_box.get("qid"), None
                        )
                        if h is not None:
                            h.cancel()

                handle_box: dict = {}
                handle = self.execute_script_streaming(
                    msg["query"],
                    on_update=_push,
                    poll_interval_s=float(msg.get("poll_interval_s", 0.25)),
                    now_ns=int(msg.get("now_ns", 0)),
                )
                handle_box["qid"] = handle.qid
                _reply(msg, {"ok": True, "qid": handle.qid})
            except Exception as e:
                _reply(msg, {"ok": False, "error": f"{type(e).__name__}: {e}"})

        def _on_stream_cancel(msg):
            handle = self._live_streams.pop(msg.get("qid"), None)
            if handle is not None:
                handle.cancel()
            _reply(msg, {"ok": True})

        def _on_schemas(msg):
            _reply(msg, {"ok": True, "schemas": self.tracker.schemas()})

        def _on_agents(msg):
            _reply(msg, {"ok": True, "agents": self.tracker.agents_info()})

        def _on_scripts(msg):
            from ..scripts import list_scripts

            _reply(msg, {"ok": True, "scripts": list_scripts()})

        self._serve_subs = [
            self.bus.subscribe("broker.execute", _guarded(_on_execute)),
            self.bus.subscribe(
                "broker.execute_stream", _guarded(_on_execute_stream)
            ),
            self.bus.subscribe(
                "broker.stream_cancel", _guarded(_on_stream_cancel)
            ),
            self.bus.subscribe("broker.schemas", _guarded(_on_schemas)),
            self.bus.subscribe("broker.agents", _guarded(_on_agents)),
            self.bus.subscribe("broker.scripts", _guarded(_on_scripts)),
        ]
