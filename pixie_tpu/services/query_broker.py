"""Query broker: compile, plan, dispatch, forward results.

Reference parity: ``src/vizier/services/query_broker`` — ExecuteScript
(``controllers/server.go:325``) compiles via the planner against the
live agent set, LaunchQuery publishes per-agent plans over the control
plane (``launch_query.go:36``), and a per-query QueryResultForwarder
(``query_result_forwarder.go:108,241,364``) streams results to the
client with producer/consumer watchdog timeouts and cancellation.
"""

from __future__ import annotations

import queue
import random
import threading
import time
import uuid

from ..exec import threadmap
from ..exec.engine import QueryError
from ..planner import CompilerState, compile_mutations, compile_pxl
from ..planner.distributed import DistributedPlanner
from ..planner.distributed.coordinator import PlanningError
from ..udf.registry import Registry, default_registry
from .msgbus import MessageBus
from .tracker import AgentTracker

#: Dispatch-retry backoff hard cap (seconds) — dispatch_backoff_ms
#: doubles per attempt up to here.
MAX_DISPATCH_BACKOFF_S = 2.0


class QueryTimeout(QueryError):
    pass


class AgentLost(QueryError):
    """A query participant died (expired / never acked its dispatch)
    while ``require_complete`` forbids degrading to partial results, or
    the participant was the un-substitutable merge agent."""


class QueryAbandoned(QueryError):
    """A broker-HA kill released this forwarder wait WITHOUT cancelling
    the agents' work: the fragments keep running so the successor
    leader can re-attach a fresh forwarder and complete the very same
    query. The served reply for an abandoned query is suppressed — the
    successor answers the caller's inbox (docs/RESILIENCE.md
    "Broker HA")."""


class AdmissionError(QueryError):
    """Admission control refused the query: its pxbound-predicted cost
    exceeds the per-engine budget (reject), or in-flight queries held
    the budget past the queue timeout. Carries the structured
    :class:`~pixie_tpu.analysis.diagnostics.Diagnostic` so clients see
    a compile-time-style refusal, not a run-time failure."""

    def __init__(self, diagnostic):
        self.diagnostic = diagnostic
        super().__init__(diagnostic.render())


class _Admission:
    """Tenant-aware predicted-cost admission control
    (``admission_bytes_budget_mb`` × ``admission_tenant_weights``).

    Per-tenant accounting over the SUM of in-flight queries' predicted
    staged bytes (pxbound ``predicted_cost.bytes_staged_hi``): each
    registered tenant owns a weighted slice of the budget
    (``services/tenancy.py tenant_shares``), so an over-share tenant's
    burst queues behind *its own* backlog while an under-share tenant
    admits without ever consulting the noisy one's state. ``admit``
    returns immediately when the budget is off or the prediction
    unknown (sketch-less plans are admitted, accounted at zero —
    conservative bounds must never turn into false rejections);
    rejects a query predicted over its tenant's WHOLE share; and
    queues one that merely doesn't fit NOW.

    The wait queue is ordered by (priority desc, earliest deadline
    first, arrival) — not arrival alone — and every ``release``
    re-runs the scheduler under the lock, waking admitted waiters
    through their own events immediately (release-to-admit latency is
    event-driven, not a poll slice). Priority classes are STRICT: a
    query only admits when no strictly-higher-priority query is in
    flight or waiting — on a saturated engine, work-conserving
    admission would keep a best-effort tenant's compute running
    back-to-back under an interactive tenant's queries and move their
    p99 however fair the byte shares are; yielding the whole admission
    slot is what actually protects the higher class's latency.
    (Default priority is 0 for everyone, so the discipline is pure
    weighted-fair until an operator assigns priorities; a starved
    low-priority query still resolves via its queue timeout or
    deadline.) ``admission_priority_holddown_ms`` extends the strict
    rule across a released query's inter-arrival gap: an admitted
    query's compute cannot be preempted (queries overlap on an engine
    since the pxlock unlock, but still contend for its cores/devices),
    so a lower-priority query admitted in the
    ~ms gap between two high-priority queries head-of-line blocks the
    next one at the agent — the hold-down keeps lower classes queued
    for a grace window after each higher-priority release, trading
    low-class throughput for high-class p99 (non-work-conserving by
    design; 0 disables). A waiter whose QUERY deadline lapses while queued is
    shed cheaply — an ``admission-shed`` Diagnostic, never dispatched;
    one that outlives ``admission_queue_s`` is rejected. ``release``
    is idempotent. Counters:
    ``pixie_admission_{queued,shed,rejected}_total{tenant}``.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._in_flight: dict[str, int] = {}  # qid -> predicted bytes
        self._tenant_of: dict[str, str] = {}  # qid -> resolved tenant
        self._prio_of: dict[str, int] = {}  # qid -> priority
        self._waiters: list[dict] = []
        self._seq = 0
        # Priority hold-down (admission_priority_holddown_ms): the
        # highest recently-released priority and when its grace window
        # lapses — strictly-lower waiters stay queued until then.
        self._held_prio: int | None = None
        self._held_until = 0.0

    def in_flight(self) -> dict:
        with self._cond:
            return dict(self._in_flight)

    def in_flight_by_tenant(self) -> dict:
        """{tenant: in-flight predicted bytes} — the queryz view."""
        with self._cond:
            out: dict = {}
            for qid, pred in self._in_flight.items():
                t = self._tenant_of.get(qid, "")
                out[t] = out.get(t, 0) + pred
            return out

    def queued(self) -> list:
        """Waiter snapshot in scheduling order (queryz / tests)."""
        with self._cond:
            return [
                {"qid": w["qid"], "tenant": w["tenant"],
                 "priority": w["priority"], "pred": w["pred"],
                 "deadline": w["deadline"]}
                for w in sorted(self._waiters, key=self._order)
            ]

    @staticmethod
    def _order(w: dict):
        return (
            -w["priority"],
            w["deadline"] if w["deadline"] is not None else float("inf"),
            w["seq"],
        )

    @staticmethod
    def _diag(message: str, code: str = "admission-reject") -> "object":
        from ..analysis.diagnostics import Diagnostic

        return Diagnostic(code=code, message=message, plan="distributed")

    @staticmethod
    def _count(kind: str, tenant: str) -> None:
        from .observability import default_counter
        from .tenancy import resolve_tenant

        # Idempotent for already-resolved names; makes the bounded-
        # cardinality guard airtight AT the labeling point (and keeps
        # the metrics-naming lint's no-baseline invariant: every
        # tenant label value is visibly resolver-derived).
        tenant = resolve_tenant(tenant)
        help_by_kind = {
            "queued": "Queries that waited in the admission queue "
                      "(tenant share full on arrival)",
            "shed": "Queued queries shed before dispatch because "
                    "their deadline lapsed (zero agent work)",
            "cancelled": "Queued queries cancelled (cancel_query / "
                         "px cancel) before dispatch (zero agent work)",
            "rejected": "Queries refused at admission (predicted over "
                        "the tenant share, or queued past "
                        "admission_queue_s)",
        }
        default_counter(
            f"pixie_admission_{kind}_total", help_by_kind[kind]
        ).labels(tenant=tenant).inc()

    def _schedule_locked(self, budget: float) -> None:
        """Admit every eligible waiter, best-ordered first. Caller
        holds ``self._cond``. A blocked tenant's waiters are skipped
        (they queue behind their own backlog) while later-ordered
        waiters of OTHER tenants still admit — weighted fairness, not
        head-of-line blocking."""
        if not self._waiters:
            return
        from .tenancy import tenant_shares

        shares = tenant_shares(budget)
        used: dict = {}
        running_prio = None
        if self._held_prio is not None:
            if time.monotonic() >= self._held_until:
                self._held_prio = None
            else:
                running_prio = self._held_prio
        for qid, pred in self._in_flight.items():
            t = self._tenant_of.get(qid, "")
            used[t] = used.get(t, 0) + pred
            p = self._prio_of.get(qid, 0)
            running_prio = p if running_prio is None else max(running_prio, p)
        blocked_prio = None
        blocked_tenants: set = set()
        for w in sorted(self._waiters, key=self._order):
            if running_prio is not None and w["priority"] < running_prio:
                break  # strict priority: yield to the running class
            if blocked_prio is not None and w["priority"] < blocked_prio:
                break  # ...and to a higher class still waiting
            if w["tenant"] in blocked_tenants:
                # FIFO within a tenant: once its best-ordered waiter is
                # blocked, later same-tenant waiters queue behind it —
                # a stream of small queries must not indefinitely
                # overtake (starve) a blocked larger one on budget the
                # larger query is waiting to accumulate.
                continue
            share = shares.get(w["tenant"], budget)
            if used.get(w["tenant"], 0) + w["pred"] <= share:
                self._waiters.remove(w)
                self._in_flight[w["qid"]] = w["pred"]
                self._tenant_of[w["qid"]] = w["tenant"]
                self._prio_of[w["qid"]] = w["priority"]
                used[w["tenant"]] = used.get(w["tenant"], 0) + w["pred"]
                running_prio = (
                    w["priority"] if running_prio is None
                    else max(running_prio, w["priority"])
                )
                w["admitted"] = True
                w["event"].set()
            else:
                blocked_tenants.add(w["tenant"])
                blocked_prio = (
                    w["priority"] if blocked_prio is None
                    else max(blocked_prio, w["priority"])
                )

    def admit(self, qid: str, predicted: dict | None,
              tenant: str | None = None, priority: int = 0,
              deadline: float | None = None) -> None:
        """Admit/queue/reject ``qid``. ``tenant`` is resolved through
        the registered set; ``deadline`` is an absolute
        ``time.monotonic()`` instant (the query's own deadline — a
        waiter past it is shed, never dispatched)."""
        from ..config import get_flag
        from .tenancy import resolve_tenant, tenant_shares

        budget = float(get_flag("admission_bytes_budget_mb")) * (1 << 20)
        if budget <= 0:
            return
        pred = (predicted or {}).get("bytes_staged_hi")
        if pred is None:
            return  # unknown cost: admit (never falsely reject)
        pred = int(pred)
        tenant = resolve_tenant(tenant)
        share = tenant_shares(budget).get(tenant, budget)
        if pred > share:
            self._count("rejected", tenant)
            raise AdmissionError(self._diag(
                f"query {qid} (tenant {tenant}) predicted {pred} staged "
                f"bytes (x{(predicted or {}).get('safety')} safety, "
                f"origin {(predicted or {}).get('origin')}) > the "
                f"tenant's admission share {int(share)} of budget "
                f"{int(budget)} (admission_bytes_budget_mb x "
                "admission_tenant_weights) — rejected at admission, "
                "not failed at run time"
            ))
        queue_s = float(get_flag("admission_queue_s"))
        give_up = time.monotonic() + max(queue_s, 0.0)
        w = {
            "qid": qid, "tenant": tenant, "pred": pred,
            "priority": int(priority), "deadline": deadline,
            "seq": 0, "event": threading.Event(), "admitted": False,
            "cancelled": False,
        }
        with self._cond:
            self._seq += 1
            w["seq"] = self._seq
            self._waiters.append(w)
            self._schedule_locked(budget)
            if w["admitted"]:
                return
        self._count("queued", tenant)
        while True:
            with self._cond:
                # A lapsed hold-down has no release event behind it, so
                # waiters re-run the scheduler themselves on every wake
                # (idempotent; releases still wake admitted waiters
                # directly through their events).
                self._schedule_locked(budget)
                if w["admitted"]:
                    return
                if w["cancelled"]:
                    # cancel() already removed us and rescheduled.
                    verdict = "cancelled"
                    break
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    self._waiters.remove(w)
                    # This waiter may have been the high-priority head
                    # blocking lower-priority waiters; with it gone the
                    # queue order changed, and no release event is
                    # coming — admit the newly eligible NOW.
                    self._schedule_locked(budget)
                    verdict = "shed"
                    break
                if now >= give_up:
                    self._waiters.remove(w)
                    self._schedule_locked(budget)
                    verdict = "timeout"
                    break
                stop = give_up if deadline is None else min(give_up, deadline)
                holddown_s = (
                    float(get_flag("admission_priority_holddown_ms")) / 1e3
                )
                if holddown_s > 0:
                    # A hold-down may be ARMED while this waiter sleeps
                    # (release() wakes only admitted waiters), and its
                    # lapse has no event behind it either — bounding
                    # every sleep slice at one hold window keeps the
                    # staleness within the same "one extra wake per
                    # window" budget the held-case bound below accepts.
                    stop = min(stop, now + holddown_s)
                if self._held_prio is not None:
                    # Wake at the grace-window lapse even if nothing
                    # releases in the meantime. Unconditional (not just
                    # for priorities the CURRENT hold blocks): a later
                    # release may re-arm the hold at a higher priority
                    # while this waiter sleeps, and if that was the
                    # final release there is no further event to wake
                    # anyone — re-observing within one grace window
                    # keeps the queue live (at most one extra wake per
                    # window per waiter).
                    stop = min(stop, self._held_until)
            w["event"].wait(timeout=max(stop - now, 0.0))
        if verdict == "cancelled":
            self._count("cancelled", tenant)
            raise AdmissionError(self._diag(
                f"query {qid} (tenant {tenant}, predicted {pred} staged "
                "bytes) cancelled while queued for admission — never "
                "dispatched, zero agent work",
                code="admission-cancelled",
            ))
        if verdict == "shed":
            self._count("shed", tenant)
            raise AdmissionError(self._diag(
                f"query {qid} (tenant {tenant}, predicted {pred} "
                f"staged bytes) shed from the admission queue: its "
                f"deadline lapsed while queued behind the tenant's "
                f"in-flight backlog — never dispatched, zero agent "
                "work", code="admission-shed",
            ))
        held = sorted(self.in_flight())
        self._count("rejected", tenant)
        raise AdmissionError(self._diag(
            f"query {qid} (tenant {tenant}) predicted {pred} staged "
            f"bytes queued past admission_queue_s={queue_s}s "
            f"behind in-flight {held} "
            f"(budget {int(budget)} bytes)"
        ))

    def cancel(self, qid: str) -> bool:
        """Cancel a QUEUED (not yet admitted) query — the queued-phase
        half of ``broker.cancel_query`` (a dispatched query takes the
        forwarder/agent path instead). The waiter is removed under the
        lock so the scheduler can never admit it afterwards; its
        ``admit()`` call raises a structured never-dispatched
        Diagnostic (``admission-cancelled``)."""
        from ..config import get_flag

        with self._cond:
            for w in self._waiters:
                if w["qid"] == qid and not w["admitted"]:
                    self._waiters.remove(w)
                    w["cancelled"] = True
                    w["event"].set()
                    # Same reschedule as shed: the departed waiter may
                    # have been priority-blocking eligible waiters.
                    self._schedule_locked(
                        float(get_flag("admission_bytes_budget_mb"))
                        * (1 << 20)
                    )
                    return True
        return False

    def release(self, qid: str) -> None:
        from ..config import get_flag

        with self._cond:
            self._tenant_of.pop(qid, None)
            prio = self._prio_of.pop(qid, None)
            if self._in_flight.pop(qid, None) is None:
                return
            holddown_s = (
                float(get_flag("admission_priority_holddown_ms")) / 1e3
            )
            if holddown_s > 0 and prio is not None:
                now = time.monotonic()
                if (self._held_prio is None or prio >= self._held_prio
                        or now >= self._held_until):
                    self._held_prio = prio
                    self._held_until = now + holddown_s
            # Freed budget admits the next eligible waiter NOW — its
            # event wakes it directly, no timeout slice involved.
            self._schedule_locked(
                float(get_flag("admission_bytes_budget_mb")) * (1 << 20)
            )


class QueryResultForwarder:
    """Per-query result stream assembly with watchdog timeouts,
    failure-driven failover, and partial-result accounting.

    A registered query knows its expected data-agent IDS (not just a
    count) and its merge agent; ``agent.expired`` events and
    dispatch-retry exhaustion (``query.{qid}.agent_lost``) feed the same
    wait loop as results, so a dying agent fails a query over
    immediately instead of waiting out the watchdog
    (query_result_forwarder.go's producer-streams teardown)."""

    def __init__(self, bus: MessageBus):
        self.bus = bus
        self._lock = threading.Lock()
        self._active: dict[str, dict] = {}

    def register_query(
        self,
        qid: str,
        expected_data_agents,
        merge_agent: str = "",
        require_complete: bool = False,
        trace=None,
    ):
        """``expected_data_agents`` is the iterable of agent IDs the
        query was planned onto — IDS, not a count: failover, the
        missing-set in timeout diagnostics, and per-agent dispatch
        state all key on them."""
        agents = list(expected_data_agents)
        from .tracker import TOPIC_EXPIRED

        q: queue.Queue = queue.Queue()

        def on_ack(m):
            # Record the ack HERE, on the subscription's dispatcher
            # thread, so the retry manager can observe it immediately
            # (acked_keys) without its own query.{qid}.ack subscription
            # — ONE ack dispatcher thread per query, not two. The
            # message still flows to the wait loop for dispatch-state
            # bookkeeping and the watchdog reset.
            with self._lock:
                st = self._active.get(qid)
                if st is not None:
                    st["acked"].add((m.get("agent"), m.get("ack")))
            q.put(m)

        subs = [
            self.bus.subscribe(f"query.{qid}.results", q.put),
            self.bus.subscribe(f"query.{qid}.agent_done", q.put),
            self.bus.subscribe(f"query.{qid}.ack", on_ack),
            self.bus.subscribe(f"query.{qid}.agent_lost", q.put),
            self.bus.subscribe(
                TOPIC_EXPIRED,
                lambda m: q.put({
                    "_expired": m.get("agent_id"),
                    "_reason": m.get("reason", "expired"),
                }),
            ),
        ]
        dispatch = {f"{aid}:execute": "dispatched" for aid in agents}
        if merge_agent:
            dispatch[f"{merge_agent}:merge"] = "dispatched"
        with self._lock:
            self._active[qid] = {
                "queue": q,
                "subs": subs,
                "expected": set(agents),
                "merge_agent": merge_agent,
                "require_complete": require_complete,
                "dispatch": dispatch,
                "acked": set(),  # {(agent, kind)} — retry manager reads
                "missing": {},  # aid -> reason
                "trace": trace,
            }

    def acked_keys(self, qid: str):
        """{(agent, kind)} acked so far for a registered query — what
        the broker's dispatch-retry loop polls instead of holding its
        own ``query.{qid}.ack`` subscription (and dispatcher thread).
        None once the query deregisters."""
        with self._lock:
            st = self._active.get(qid)
            return set(st["acked"]) if st is not None else None

    def wait(self, qid: str, timeout_s: float,
             deadline: float | None = None,
             deadline_reason: str = "deadline") -> dict:
        """Blocks until eos/error/timeout. Returns {table: HostBatch} plus
        per-agent exec stats and the partial-result marker; raises on
        error, merge-agent loss, require_complete violation, or watchdog
        expiry. The watchdog is an INACTIVITY timeout: any message
        resets it (the reference's producer watchdog).

        ``deadline`` (absolute ``time.monotonic()``) is the query's own
        deadline: when it passes mid-wait the query is cancelled
        everywhere (agents abort at their next window boundary) and
        whatever already arrived returns as a ``partial`` result with
        the unreported agents marked ``missing_reasons[...] =
        deadline_reason`` — a deadline is degradation, not failure (a
        successor broker adopting an in-flight query passes
        "broker_failover" so the attribution names the takeover, not
        the query). An ``interrupt()`` (the ``cancel_query`` path)
        takes the same exit with reason "cancelled"."""
        with self._lock:
            st = self._active[qid]
        outputs: dict = {}
        stats: dict = {}
        merge_stats: dict = {}  # merge-tier attribution (role="merge")
        eos = False
        grace_deadline = None
        # Inactivity watchdog: only QUERY-RELEVANT activity pushes the
        # deadline out — unrelated cluster churn (another query's agent
        # expiring) must not postpone a hung query's timeout forever.
        watchdog = time.monotonic() + timeout_s
        try:
            while True:
                if eos and self._complete(st, stats):
                    return self._result(st, outputs, stats, merge_stats)
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    return self._interrupted(
                        qid, st, outputs, stats, merge_stats,
                        deadline_reason,
                    )
                if eos:
                    # After eos, per-agent stats may still be in flight
                    # on their own dispatcher threads — drain them under
                    # ONE total grace budget (a per-message wait would
                    # let a trickle of stragglers extend the drain by
                    # ~1s × expected agents).
                    if grace_deadline is None:
                        grace_deadline = now + min(timeout_s, 1.0)
                    wait_s = grace_deadline - now
                    if wait_s <= 0:
                        return self._result(st, outputs, stats, merge_stats)
                else:
                    wait_s = watchdog - now
                    if wait_s <= 0:
                        self.cancel(qid)
                        raise QueryTimeout(
                            self._timeout_message(qid, st, stats, timeout_s)
                        )
                if deadline is not None:
                    wait_s = min(wait_s, deadline - now)
                try:
                    msg = st["queue"].get(timeout=max(wait_s, 0.0))
                except queue.Empty:
                    # Loop back: the top of the loop decides which
                    # limit actually fired (query deadline -> partial,
                    # post-eos grace -> result, watchdog -> the
                    # QueryTimeout above; query_result_forwarder.go:241).
                    continue
                if "_abandon" in msg:
                    # Broker-HA kill: free this waiter and its subs (the
                    # finally deregisters) WITHOUT publishing
                    # query.cancel — agents keep running for the
                    # successor's re-attached forwarder.
                    raise QueryAbandoned(
                        f"query {qid} abandoned: {msg['_abandon']}"
                    )
                if "_interrupt" in msg:
                    # cancel_query(): the same cooperative exit as a
                    # lapsed deadline, reason "cancelled".
                    return self._interrupted(
                        qid, st, outputs, stats, merge_stats,
                        str(msg["_interrupt"]),
                    )
                if "error" in msg:
                    self.cancel(qid)
                    raise QueryError(msg["error"])
                if "ack" in msg:
                    st["dispatch"][
                        f"{msg.get('agent')}:{msg['ack']}"
                    ] = "acked"
                elif "_expired" in msg:
                    aid = msg["_expired"]
                    if (
                        aid != st["merge_agent"]
                        and aid not in st["expected"]
                    ):
                        continue  # another query's churn: no reset
                    if not msg.get("_requeued"):
                        # One-shot deferral: the dead agent may have
                        # DELIVERED everything already, with its
                        # agent_done/eos still sitting in this queue
                        # (separate dispatcher threads enqueue in
                        # nondeterministic order). Re-enqueueing puts
                        # the expiry behind whatever was already in
                        # flight, so delivered data is never discarded.
                        st["queue"].put({**msg, "_requeued": True})
                        continue
                    if eos:
                        # The merge already emitted complete results; at
                        # most stop waiting for this agent's stats.
                        st["expected"].discard(aid)
                        continue
                    self._agent_lost(
                        qid, st, stats, aid,
                        msg.get("_reason", "expired"),
                    )
                elif "agent_lost" in msg:
                    if not msg.get("_requeued"):
                        # Same one-shot deferral as _expired: a late ack
                        # (or delivered results) may already sit in this
                        # queue behind the verdict.
                        st["queue"].put({**msg, "_requeued": True})
                        continue
                    # A retry-exhaustion verdict is advisory: if the
                    # ack DID reach this queue (the retry manager merely
                    # raced its own timeout under load), the agent
                    # demonstrably holds the fragment — keep waiting;
                    # real death is caught by expiry.
                    kind = msg.get("kind", "execute")
                    key = f"{msg['agent_lost']}:{kind}"
                    if (
                        msg.get("unacked")
                        and st["dispatch"].get(key) == "acked"
                    ):
                        continue
                    if eos:
                        st["expected"].discard(msg["agent_lost"])
                        continue
                    self._agent_lost(
                        qid, st, stats, msg["agent_lost"],
                        msg.get("reason", "lost"),
                    )
                elif "exec_time_s" in msg:
                    entry = {"exec_time_s": msg["exec_time_s"]}
                    if isinstance(msg.get("usage"), dict):
                        entry["usage"] = dict(msg["usage"])
                    if msg.get("role") == "merge":
                        # Merge-tier usage is attribution, not a data
                        # shard: kept out of agent_stats so expected-set
                        # completion (and existing consumers) see data
                        # agents only.
                        merge_stats[msg["agent"]] = entry
                    else:
                        stats[msg["agent"]] = entry
                elif msg.get("eos"):
                    eos = True
                elif "table" in msg:
                    outputs[msg["table"]] = msg["batch"]
                watchdog = time.monotonic() + timeout_s
        finally:
            self._deregister(qid)

    @staticmethod
    def _complete(st: dict, stats: dict) -> bool:
        return st["expected"] <= set(stats)

    def interrupt(self, qid: str, reason: str = "cancelled") -> bool:
        """Cooperatively stop a registered one-shot query: the wait
        loop returns a partial result with ``reason`` instead of an
        error (the ``cancel_query`` path). False when ``qid`` is not
        (or no longer) registered."""
        with self._lock:
            st = self._active.get(qid)
        if st is None:
            return False
        st["queue"].put({"_interrupt": reason})
        return True

    def abandon(self, qid: str, reason: str = "broker_failover") -> bool:
        """Release a registered query WITHOUT cancelling the agents'
        work: the wait loop raises :class:`QueryAbandoned` (freeing its
        subscriptions and threads) but no ``query.cancel`` is published
        — the fragments keep running so a broker-HA successor can
        re-attach a fresh forwarder and complete the same query. The
        killed leader's teardown path."""
        with self._lock:
            st = self._active.get(qid)
        if st is None:
            return False
        st["queue"].put({"_abandon": reason})
        return True

    def active_qids(self) -> list[str]:
        """Registered (in-flight) query ids — what a broker-HA kill
        abandons and a standby's mirror is reconciled against."""
        with self._lock:
            return sorted(self._active)

    def _interrupted(self, qid: str, st: dict, outputs: dict,
                     stats: dict, merge_stats: dict,
                     reason: str) -> dict:
        """Deadline/cancel exit: stop the agents (they abort at their
        next window boundary — the shed is cooperative, not advisory),
        mark every agent that hasn't reported as missing with
        ``reason``, and return what DID arrive as a partial result. A
        deadline-exceeded query is a degraded answer, not a failure."""
        self.cancel(qid)
        for aid in sorted(st["expected"] - set(stats)):
            st["missing"][aid] = reason
            st["dispatch"][f"{aid}:execute"] = f"interrupted ({reason})"
        res = self._result(st, outputs, stats, merge_stats)
        res["partial"] = True
        res["interrupted"] = reason
        if not res.get("missing_reasons"):
            # Every data agent reported (only eos/merge was pending):
            # still a partial answer — attribute it to the query itself.
            res["missing_reasons"] = {"_query": reason}
        return res

    def _agent_lost(self, qid: str, st: dict, stats: dict, aid: str,
                    reason: str) -> None:
        """One participant is gone: fail over (partial results), or fail
        fast when degradation is impossible (merge agent) or forbidden
        (require_complete)."""
        if aid == st["merge_agent"]:
            self.cancel(qid)
            raise AgentLost(
                f"merge agent {aid} {reason}; query {qid} failed"
            )
        if aid not in st["expected"] or aid in stats:
            return  # not a participant / already finished its fragment
        if st["require_complete"]:
            self.cancel(qid)
            raise AgentLost(
                f"data agent {aid} {reason} and require_complete is set; "
                f"missing_agents: ['{aid}']"
            )
        st["expected"].discard(aid)
        st["missing"][aid] = reason
        st["dispatch"][f"{aid}:execute"] = f"lost ({reason})"
        tr = st.get("trace")
        if tr is not None:
            with tr.span("failover") as sp:
                sp.attributes.update({"agent": aid, "reason": reason})
        if not st["expected"]:
            self.cancel(qid)
            raise AgentLost(
                f"all data agents lost for query {qid}: "
                f"{sorted(st['missing'])}"
            )
        # Tell the merge agent to finish from the survivors: without
        # this, _maybe_finish_merge waits forever on the dead agent's
        # bridge payloads.
        if st["merge_agent"]:
            self.bus.publish(
                f"agent.{st['merge_agent']}.merge_update",
                {"qid": qid, "data_agents": sorted(st["expected"])},
            )

    @staticmethod
    def _timeout_message(qid: str, st: dict, stats: dict,
                         timeout_s: float) -> str:
        missing = sorted(st["expected"] - set(stats))
        return (
            f"query {qid} timed out after {timeout_s}s "
            f"(reported: {sorted(stats)}; missing: {missing}; "
            f"dispatch: {dict(sorted(st['dispatch'].items()))})"
        )

    def _result(self, st: dict, outputs: dict, stats: dict,
                merge_stats: dict | None = None) -> dict:
        res = {
            "tables": outputs,
            "agent_stats": stats,
            "merge_stats": dict(merge_stats or {}),
            "partial": bool(st["missing"]),
            "missing_agents": sorted(st["missing"]),
        }
        if st["missing"]:
            res["missing_reasons"] = dict(st["missing"])
            from .observability import default_counter

            default_counter(
                "pixie_query_partial_total",
                "Distributed queries completed with partial results "
                "(>=1 data agent lost mid-query)",
            ).inc()
        return res

    def cancel(self, qid: str):
        self.bus.publish("query.cancel", {"qid": qid})

    def is_active(self, qid: str) -> bool:
        """True while ``qid`` is registered and not yet deregistered
        (the dispatch-retry loop's liveness check)."""
        with self._lock:
            return qid in self._active

    def _deregister(self, qid: str):
        with self._lock:
            st = self._active.pop(qid, None)
        if st:
            for s in st["subs"]:
                s.unsubscribe()


class StreamHandle:
    """A live query's client handle: ``cancel()`` stops the agents'
    streaming cursors and detaches the subscriber."""

    def __init__(self, qid: str, broker: "QueryBroker", sub,
                 merge_agent: str = "", data_agents: tuple = (),
                 require_complete: bool = False):
        self.qid = qid
        self.merge_agent = merge_agent
        self.data_agents = tuple(data_agents)
        self.require_complete = require_complete
        self.missing_agents: tuple = ()
        self._broker = broker
        self._sub = sub

    def cancel(self) -> None:
        self._broker._live_streams.pop(self.qid, None)
        self._broker.bus.publish("query.cancel", {"qid": self.qid})
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None


class QueryBroker:
    def __init__(
        self,
        bus: MessageBus,
        tracker: AgentTracker,
        registry: Registry | None = None,
        secret: str | None = None,
    ):
        from ..config import get_flag

        self.bus = bus
        self.tracker = tracker
        # Bearer-token check on served API requests (authcontext analog);
        # empty = auth disabled. Netbus connects are gated separately.
        self.secret = get_flag("bus_secret") if secret is None else secret
        from .vizier_funcs import bind_service_registry

        self.registry = bind_service_registry(
            registry or default_registry(), bus, "broker"
        )
        self.forwarder = QueryResultForwarder(bus)
        self.planner = DistributedPlanner(self.registry)
        # Predicted-cost admission control (pxbound predicted_cost vs
        # admission_bytes_budget_mb; off by default).
        self.admission = _Admission()
        # Broker-side query-lifecycle traces (exec/trace.py Tracer):
        # dispatch / retry / failover spans per distributed query,
        # served as /debug/queryz on the broker role.
        from ..exec.trace import Tracer

        self.tracer = Tracer()
        # Cluster-stitched distributed traces (/debug/tracez): the
        # broker's own dispatch spans + the span summaries agents
        # publish on telemetry.spans, grouped by trace id.
        from .telemetry import ClusterTraceView, ObservedCostIndex

        self.trace_view = ClusterTraceView(bus, tracer=self.tracer)
        # Observed per-script-hash cost history (the __queries__
        # feedback loop at the broker): every finished distributed
        # trace's merged usage is indexed so admission control can
        # floor sketch predictions at observed reality
        # (admission_observed_floor).
        self.observed_costs = ObservedCostIndex(tracer=self.tracer)
        # Watermark-validated merged-result cache (exec/result_cache.py;
        # result_cache_mb flag, 0 = off): repeats of an unchanged-
        # watermark script are served BEFORE admission/compile/dispatch.
        from ..exec.result_cache import ResultCache

        self.result_cache = ResultCache()
        # Dynamic-tracing support (the MutationExecutor dependency,
        # mutation_executor.go:84); wire a TracepointRegistry to enable.
        self.tracepoints = None
        # Every live stream's handle (qid -> StreamHandle): the stream
        # watchdog. A stream whose MERGE agent expires can never emit
        # again (data-agent loss re-merges from survivors instead), so
        # tracker expiry fails it loudly rather than leaving the client
        # on a forever-silent subscription (reference: the forwarder's
        # producer watchdog, query_result_forwarder.go).
        self._live_streams: dict = {}
        # Serializes degrade decisions: two agents expiring at once (on
        # separate dispatcher threads) must not lose each other's
        # handle.data_agents update — a lost update would leave a dead
        # agent in the merge's keep-set and stall the view forever.
        self._degrade_lock = threading.Lock()

        from .tracker import TOPIC_EXPIRED, TOPIC_REGISTER

        self._expiry_sub = self.bus.subscribe(
            TOPIC_EXPIRED, self._on_agent_expired
        )
        # A RE-registration of a PLANNED agent means a new incarnation
        # (restart): the old process's stream state — merge carries on
        # a kelvin, the streaming cursor + bridge on a data agent — is
        # gone even though the agent_id never expired (the operator
        # restarts faster than the tracker's expiry window). A restarted
        # data agent's slice would otherwise silently never rejoin the
        # view (a permanently partial live aggregate); aborting lets the
        # client re-plan against the new topology. The surviving-agent
        # resync case only follows an expiry, which already aborted
        # merge-dead streams and degraded data-dead ones visibly.
        self._register_sub = self.bus.subscribe(
            TOPIC_REGISTER, self._on_agent_registered
        )

        # Broker-HA hooks (services/broker_ha.py wires these; all three
        # default to the plain single-broker behavior). epoch_fn stamps
        # the leader's fencing epoch on every dispatch envelope;
        # state_log streams compact control-plane events to standbys;
        # broker_id identifies which broker answered (px agents).
        self.broker_id = ""
        self.epoch_fn = None    # () -> int; None = epochless
        self.state_log = None   # (event: str, data: dict) -> None
        # Set by BrokerReplica.kill(): this broker is dead, its served
        # ERROR replies are suppressed (they'd be artifacts of the kill
        # itself — fenced dispatches, abandoned waits — and would race
        # the successor's real answer for the caller's one-shot inbox).
        self.ha_suppress_errors = False

    def _log_state(self, event: str, data: dict) -> None:
        """Emit one broker.state replication event when this broker is
        an HA leader; no-op otherwise. Replication must never fail the
        query path."""
        log = self.state_log
        if log is not None:
            try:
                log(event, data)
            except Exception:
                pass

    def _on_agent_registered(self, msg: dict) -> None:
        self._abort_streams_of(
            msg.get("agent_id"), "restarted (re-registered)",
            include_data_agents=True,
        )
        # Agent-set change: a merged cached result no longer covers the
        # same shards (and the cluster watermark alone can't always see
        # that), so a repeat must re-execute — and degrade through the
        # partial-results machinery exactly like a live query.
        # ResultCache serializes internally (its own Lock), so the
        # cross-dispatcher clear() is safe without a broker-side lock.
        self.result_cache.clear()  # pxlint: disable=thread-shared-state
        self._log_state("agent", {
            "op": "registered", "agent_id": msg.get("agent_id"),
        })
        self._log_state("cache_invalidate", {"why": "agent-registered"})

    def _abort_streams_of(self, agent_id, why: str,
                          include_data_agents: bool = False) -> None:
        """Fail every live stream that planned ``agent_id`` as its merge
        agent (always) or as a data agent (``include_data_agents``):
        error to the client THEN cancel directly — cleanup must not
        depend on the client's on_update callback surviving (the bus
        swallows handler exceptions). The atomic pop makes the abort
        exactly-once even when expiry and re-registration race on
        separate dispatcher threads."""
        for qid, handle in list(self._live_streams.items()):
            if handle.merge_agent == agent_id:
                role = "merge agent"
            elif include_data_agents and agent_id in handle.data_agents:
                role = "data agent"
            else:
                continue
            if self._live_streams.pop(qid, None) is None:
                continue  # another aborter claimed it first
            self.bus.publish(
                f"query.{qid}.results",
                {"error": f"{role} {agent_id} {why}; "
                          f"live query {qid} aborted"},
            )
            handle.cancel()  # idempotent (entry already popped)

    def _on_agent_expired(self, msg: dict) -> None:
        """Tracker expiry: merge-agent death aborts the stream (its
        state is unrecoverable); data-agent death degrades the stream to
        the survivors (or aborts, under require_complete). One-shot
        queries get the same event through their forwarder
        registration."""
        aid = msg.get("agent_id")
        self._abort_streams_of(aid, "expired")
        self._degrade_streams_of(aid, msg.get("reason", "expired"))
        # A lost agent's shard is gone from the merged view: cached
        # results that covered it must not serve as-if-complete.
        # ResultCache serializes internally (see _on_agent_registered).
        self.result_cache.clear()  # pxlint: disable=thread-shared-state
        self._log_state("agent", {
            "op": "expired", "agent_id": aid,
            "reason": msg.get("reason", "expired"),
        })
        self._log_state("cache_invalidate", {"why": "agent-expired"})

    def _degrade_streams_of(self, agent_id, why: str) -> None:
        with self._degrade_lock:
            for qid, handle in list(self._live_streams.items()):
                self._degrade_one_locked(qid, handle, agent_id, why)

    def _degrade_one_stream(self, qid: str, agent_id, why: str) -> None:
        """Qid-scoped degrade (the per-query dispatch-loss path: the
        verdict only says THIS query's dispatch went missing, so other
        live streams on the same agent must be untouched)."""
        with self._degrade_lock:
            handle = self._live_streams.get(qid)
            if handle is not None:
                self._degrade_one_locked(qid, handle, agent_id, why)

    def _degrade_one_locked(self, qid: str, handle, agent_id,
                            why: str) -> None:
        if (
            agent_id not in handle.data_agents
            or handle.merge_agent == agent_id
        ):
            return
        survivors = tuple(
            a for a in handle.data_agents if a != agent_id
        )
        if handle.require_complete or not survivors:
            # Nothing to degrade to (or degradation forbidden): a
            # sourceless live stream would sit silent forever —
            # error it out like a merge-agent death instead.
            # Caller holds _degrade_lock (both degrade entry points);
            # the lint is intraprocedural.
            # pxlint: disable=thread-shared-state
            if self._live_streams.pop(qid, None) is None:
                return
            cause = (
                "require_complete" if handle.require_complete
                else "no data agents left"
            )
            self.bus.publish(
                f"query.{qid}.results",
                {"error": f"data agent {agent_id} {why}; live query "
                          f"{qid} aborted ({cause})"},
            )
            handle.cancel()
            return
        handle.data_agents = survivors
        handle.missing_agents = handle.missing_agents + (agent_id,)
        # Shrink the live merge's expected set so re-merges keep
        # flowing from the survivors (and the dead agent's stale
        # last state is dropped, not frozen into the view forever).
        self.bus.publish(
            f"agent.{handle.merge_agent}.merge_update",
            {"qid": qid, "data_agents": list(handle.data_agents)},
        )
        self.bus.publish(
            f"query.{qid}.results",
            {"stream_degraded": True, "partial": True, "qid": qid,
             "missing_agents": sorted(handle.missing_agents),
             "reason": f"data agent {agent_id} {why}"},
        )

    def _check_dispatch_sets(self, dplan, dispatches: dict,
                             merge_agent) -> None:
        """Static cross-check before any message leaves the broker: the
        agents the merge fragment will WAIT for must be exactly the
        agents an execute fragment is SENT to (pixie_tpu/analysis
        verify_dispatch_sets). An asymmetry is a planner/dispatch bug
        that would otherwise surface as a query timeout listing agents
        that were never dispatched — fail at plan time instead."""
        from ..analysis.verifier import verify_dispatch_sets

        merge_expected: list = []
        dispatched = []
        for (aid, kind), (_topic, payload) in dispatches.items():
            if kind in ("merge", "stream_merge"):
                merge_expected = payload.get("data_agents", [])
            else:
                dispatched.append(aid)
        diags = verify_dispatch_sets(
            dplan, merge_expected, dispatched, merge_agent=merge_agent
        )
        if diags:
            raise QueryError(
                "dispatch verification failed: "
                + "; ".join(d.render() for d in diags)
            )

    def _dispatch_with_retry(self, qid: str, dispatches: dict,
                             trace=None, on_lost=None,
                             live=None) -> None:
        """Publish every dispatch in ``dispatches`` ({(aid, kind):
        (topic, msg)}, in order), then — on a background thread —
        re-publish any still un-acked with capped exponential backoff +
        jitter (``dispatch_retries`` × ``dispatch_backoff_ms``). A
        dispatch that never acks publishes ``query.{qid}.agent_lost``
        (the forwarder turns it into failover or fail-fast) or, when
        ``on_lost(aid, kind)`` is given (streaming path), calls that
        instead. ``live()`` gates the loop; default: the forwarder
        registration is still active.

        Ack observation: a forwarder-REGISTERED query (the
        execute_script path) already holds a ``query.{qid}.ack``
        subscription whose callback records every ack — the retry
        manager observes THAT state (``forwarder.acked_keys``) instead
        of spawning a second subscription + dispatcher thread per query.
        Only the streaming path (which never registers) keeps its own
        dedicated ack subscription."""
        from ..config import get_flag

        retries = int(get_flag("dispatch_retries"))
        base_s = float(get_flag("dispatch_backoff_ms")) / 1e3
        use_forwarder_acks = live is None and self.forwarder.is_active(qid)
        if live is None:
            live = lambda: self.forwarder.is_active(qid)  # noqa: E731
        acked: set = set()
        all_acked = threading.Event()
        keys = set(dispatches)
        ack_sub = None
        if use_forwarder_acks:
            def wait_acked(wait_s: float) -> bool:
                # Poll the forwarder's ack state on a short cadence
                # (bounded by the wait budget): the acks were recorded
                # on the forwarder's ack dispatcher the instant they
                # arrived, so freshness matches the old subscription.
                deadline = time.monotonic() + wait_s
                while True:
                    got = self.forwarder.acked_keys(qid)
                    if got is None:
                        return True  # deregistered: query over, stand down
                    acked.clear()
                    acked.update(got)
                    if keys <= acked:
                        return True
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    time.sleep(min(left, 0.05))
        else:
            def on_ack(m):
                acked.add((m.get("agent"), m.get("ack")))
                if keys <= acked:
                    all_acked.set()

            ack_sub = self.bus.subscribe(f"query.{qid}.ack", on_ack)
            wait_acked = all_acked.wait
        for topic, msg in dispatches.values():
            self.bus.publish(topic, msg)

        def run():
            rng = random.Random()  # jitter only shapes timing
            try:
                for attempt in range(retries + 1):
                    wait_s = min(
                        base_s * (2 ** attempt), MAX_DISPATCH_BACKOFF_S
                    ) * (1.0 + 0.25 * rng.random())
                    if wait_acked(wait_s):
                        return
                    if not live():
                        return  # query already finished/failed
                    if attempt >= retries:
                        break
                    from .observability import default_counter

                    retries_total = default_counter(
                        "pixie_dispatch_retries_total",
                        "Un-acked fragment dispatches re-published by "
                        "the broker",
                    )
                    for (aid, kind) in keys - acked:
                        topic, msg = dispatches[(aid, kind)]
                        self.bus.publish(topic, msg)
                        retries_total.inc()
                        if trace is not None:
                            with trace.span("dispatch.retry") as sp:
                                sp.attributes.update({
                                    "agent": aid, "kind": kind,
                                    "attempt": attempt + 1,
                                })
                for (aid, kind) in sorted(keys - acked):
                    if on_lost is not None:
                        on_lost(aid, kind)
                        continue
                    self.bus.publish(
                        f"query.{qid}.agent_lost",
                        {"agent_lost": aid, "kind": kind, "unacked": True,
                         "reason": f"{kind} dispatch un-acked after "
                                   f"{retries} retries"},
                    )
            finally:
                if ack_sub is not None:
                    ack_sub.unsubscribe()

        threading.Thread(
            target=run, name=f"dispatch-{qid}", daemon=True
        ).start()

    def close(self) -> None:
        """Detach the broker from the bus: watchdog subscriptions, the
        served API topics (if serve() ran), and any still-live streams.
        Transient brokers on a shared bus must not keep reacting to
        agent lifecycle events after they're discarded."""
        for qid in list(self._live_streams):
            # GIL-atomic pop: exactly-once vs a racing aborter, same
            # protocol as _abort_streams_of (see baseline.json).
            handle = self._live_streams.pop(qid, None)  # pxlint: disable=thread-shared-state
            if handle is not None:
                handle.cancel()
        for sub in (self._expiry_sub, self._register_sub):
            sub.unsubscribe()
        for sub in getattr(self, "_serve_subs", []):
            sub.unsubscribe()
        self._serve_subs = []  # a re-serve() after close starts fresh
        if getattr(self, "_exec_gate", None) is not None:
            # In-flight request workers finish their current query
            # (replies are best-effort) but drain no further backlog;
            # daemon threads never block interpreter exit.
            with self._exec_gate:
                self._exec_closed = True
                self._exec_backlog.clear()
        self.trace_view.close()

    def stop_serving(self) -> None:
        """Withdraw the served bus API only (the broker-HA step-down
        path): new ``broker.*`` requests flow to whichever broker now
        serves them, while THIS broker's in-flight queries keep
        completing and replying, and its lifecycle subscriptions stay.
        ``serve()`` may run again on re-election."""
        for sub in getattr(self, "_serve_subs", []):
            sub.unsubscribe()
        self._serve_subs = []

    # -- profiling tier ------------------------------------------------------
    def profile_rows(
        self,
        agent_id: str | None = None,
        tenant: str | None = None,
        script_hash: str | None = None,
    ) -> list[dict]:
        """Cluster-merged folded-stack profile: the tracker's heartbeat
        summaries across agents PLUS this broker process's own profiler
        (deploy.py routes the broker's sampler through the same
        ``__stacks__`` fold, agent_id "broker"), merged per (stack,
        attribution) key, hottest first — what /debug/pprof,
        /debug/flamez and the ``broker.profile`` topic serve."""
        rows = self.tracker.profile(
            agent_id=agent_id, tenant=tenant, script_hash=script_hash
        )
        from ..ingest.profiler import profile_summary

        local = (
            profile_summary(agent_id="broker", top=0)
            if agent_id in (None, "broker") else []
        )
        if not local:
            return rows
        merged: dict[tuple, int] = {}
        for r in rows + [
            r for r in local
            if (tenant is None or r.get("tenant", "") == tenant)
            and (script_hash is None
                 or r.get("script_hash", "") == script_hash)
        ]:
            key = (
                r.get("stack", ""), r.get("qid", ""),
                r.get("script_hash", ""), r.get("tenant", ""),
                r.get("phase", ""),
            )
            merged[key] = merged.get(key, 0) + int(r.get("count", 0))
        out = [
            {
                "stack": k[0], "count": n, "qid": k[1],
                "script_hash": k[2], "tenant": k[3], "phase": k[4],
            }
            for k, n in merged.items()
        ]
        out.sort(key=lambda r: (-r["count"], r["stack"]))
        return out

    def busz(self) -> dict:
        """Cluster transport snapshot for ``/debug/busz``: the
        tracker's per-agent + merged heartbeat bus summaries, plus this
        broker process's own bus (its dispatch/ack/heartbeat traffic —
        present whenever the bus carries stats; deploy adds the
        BusServer's per-connection wire accounting on top)."""
        t = self.tracker.bus_stats()
        out = {
            "scope": "cluster",
            "agents": t["agents"],
            "merged": t["merged"],
        }
        local = getattr(self.bus, "busz", None)
        if local is not None:
            out["local"] = local()
        return out

    def profile_agents(self) -> list[str]:
        """Agents contributing stacks to the merged profile (the
        broker's own sampler counts when it has samples)."""
        from ..ingest.profiler import profile_summary

        agents = self.tracker.profile_agents()
        if profile_summary(agent_id="broker", top=1):
            agents = sorted(set(agents) | {"broker"})
        return agents

    def cancel_query(self, qid: str) -> bool:
        """Cooperatively cancel a running query (`px cancel` /
        ``broker.cancel``): live streams tear down their cursors, a
        one-shot query returns a partial result with reason
        "cancelled", and ``query.cancel`` tells every agent to abort at
        its next window boundary — the same path a lapsed deadline
        takes, which is what makes load shedding safe rather than
        advisory. Returns True when a registered query was found."""
        # GIL-atomic pop: exactly-once vs a racing aborter, same
        # protocol as _abort_streams_of (see baseline.json).
        handle = self._live_streams.pop(qid, None)  # pxlint: disable=thread-shared-state
        if handle is not None:
            handle.cancel()
            return True
        # A query still WAITING for admission (its qid is visible in
        # `px debug queries` / /debug/queryz, inviting exactly this
        # cancel) has no forwarder registration yet — cancel it at the
        # queue, before any dispatch exists to stop.
        if self.admission.cancel(qid):
            return True
        hit = self.forwarder.interrupt(qid, "cancelled")
        # Belt and braces: even a query the forwarder no longer tracks
        # (or one raced between registration steps) gets its agents
        # stopped — agents drop cancels for unknown qids.
        self.bus.publish("query.cancel", {"qid": qid})
        return hit

    def execute_script(
        self,
        query: str,
        timeout_s: float = 30.0,
        now_ns: int = 0,
        max_output_rows: int = 10_000,
        mutation_timeout_s: float = 10.0,
        require_complete: bool | None = None,
        tenant: str | None = None,
        priority: int = 0,
        deadline_ms: float | None = None,
        reply_to: str | None = None,
    ) -> dict:
        """The VizierService.ExecuteScript flow, end to end.

        Mutation phase first (MutationExecutor.Execute): pxtrace
        tracepoints deploy and the broker waits until their tables are
        schema-ready before compiling the query phase — so a script may
        query the very table its tracepoint creates.

        ``require_complete`` (default: the flag): True fails the query
        as soon as a data agent is lost; False completes from the
        survivors with ``partial=True`` + ``missing_agents``.

        Multi-tenancy (services/tenancy.py): ``tenant`` scopes the
        query to a registered tenant's admission share (unknown/None ->
        the shared tenant), ``priority`` (higher first) and
        ``deadline_ms`` (relative, from now) order the admission wait
        queue. The deadline also rides every dispatch: agents abort
        past-deadline work at window boundaries and the client gets a
        ``partial`` result with ``missing_reasons=...: "deadline"``
        instead of dead compute.
        """
        from ..config import get_flag
        from .tenancy import resolve_tenant

        if require_complete is None:
            require_complete = bool(get_flag("require_complete"))
        tenant = resolve_tenant(tenant)
        deadline_mono = deadline_unix = None
        if deadline_ms is not None and float(deadline_ms) > 0:
            deadline_mono = time.monotonic() + float(deadline_ms) / 1e3
            deadline_unix = time.time() + float(deadline_ms) / 1e3
        trace = self.tracer.begin_query(script=query, kind="distributed")
        trace.tenant = tenant
        # Profiler attribution (exec/threadmap.py): broker-side CPU on
        # this thread — compile, planning, dispatch, merge coordination
        # — samples under the query's qid/tenant/script hash.
        tm_token = threadmap.bind(trace=trace, phase="host")
        try:
            result = self._execute_script_inner(
                query, timeout_s, now_ns, max_output_rows,
                mutation_timeout_s, require_complete, trace,
                tenant, int(priority), deadline_mono, deadline_unix,
                reply_to,
            )
        except Exception as e:
            self.tracer.end_query(
                trace, status="error",
                error=f"{type(e).__name__}: {e}"[:300],
            )
            raise
        finally:
            threadmap.unbind(tm_token)
        self.tracer.end_query(
            trace,
            status="partial" if result.get("partial") else "ok",
        )
        return result

    def _execute_script_inner(
        self,
        query: str,
        timeout_s: float,
        now_ns: int,
        max_output_rows: int,
        mutation_timeout_s: float,
        require_complete: bool,
        trace,
        tenant: str,
        priority: int,
        deadline_mono: float | None,
        deadline_unix: float | None,
        reply_to: str | None = None,
    ) -> dict:
        from ..exec import result_cache as rc

        # Result cache (exec/result_cache.py): the lookup sits BEFORE
        # admission, compile and dispatch — a hit pays none of them
        # (the entry carries its scanned-table set, so validity is one
        # watermark read per table, no compile). Mutation scripts
        # bypass: their execution has side effects a cache must not
        # swallow.
        cache_status = ""
        if self.result_cache.enabled():
            if "pxtrace" in query:
                cache_status = rc.BYPASS
            else:
                cluster_stats = self.tracker.table_stats()

                def _cluster_wm(t, _stats=cluster_stats):
                    fresh = _stats.get(t, {}).get("freshness") or {}
                    wm = fresh.get("watermark")
                    return None if wm is None or int(wm) < 0 else int(wm)

                status, entry, lag_ms = self.result_cache.lookup(
                    query, now_ns, max_output_rows, _cluster_wm
                )
                if status == rc.HIT:
                    trace.cache = rc.HIT
                    trace.qid = entry.result.get("qid") or ""
                    trace.usage.freshness_lag_ms = lag_ms
                    result = dict(entry.result)
                    result["cache"] = rc.HIT
                    result["freshness_lag_ms"] = lag_ms
                    return result
                cache_status = status
        trace.cache = cache_status
        compiler_state = CompilerState(
            schemas=self.tracker.schemas(),
            registry=self.registry,
            now_ns=now_ns,
            max_output_rows=max_output_rows,
            # Cluster-wide ingest-sketch summary (agents ship it with
            # heartbeats): seeds the planner's NDV sizing AND pxbound's
            # predicted query cost — the admission-control signal.
            table_stats=self.tracker.table_stats(),
        )
        mutation_states = None
        # Cheap gate: the mutation pass re-executes the script, so skip it
        # entirely unless the source can contain pxtrace at all.
        mutations = (
            compile_mutations(query, compiler_state)
            if "pxtrace" in query
            else []
        )
        if mutations:
            if self.tracepoints is None:
                raise QueryError(
                    "script contains pxtrace mutations but this broker has "
                    "no TracepointRegistry wired"
                )
            self.tracepoints.apply(mutations)
            from ..trace.spec import TracepointDeployment

            names = [
                m.name for m in mutations
                if isinstance(m, TracepointDeployment)
            ]
            mutation_states = self.tracepoints.wait_ready(
                names, timeout_s=mutation_timeout_s
            )
            failed = {n: s for n, s in mutation_states.items() if s != "RUNNING"}
            if failed:
                infos = {
                    n: (self.tracepoints.info(n) or {}).get("error", "")
                    for n in failed
                }
                raise QueryError(f"tracepoint deploy failed: {infos}")
            # Re-read schemas: the tracepoint tables now exist.
            compiler_state = CompilerState(
                schemas=self.tracker.schemas(),
                registry=self.registry,
                now_ns=now_ns,
                max_output_rows=max_output_rows,
                table_stats=self.tracker.table_stats(),
            )
        state = self.tracker.distributed_state()  # fresh per query
        with trace.span("compile"):
            compiled = compile_pxl(query, compiler_state)
        if mutations and not compiled.outputs and not compiled.n_exports:
            return {
                "mutations": mutation_states,
                "tables": {},
                "agent_stats": {},
                "qid": None,
            }
        try:
            dplan = self.planner.plan(
                compiled.plan, state,
                schemas=compiler_state.schemas,
                table_stats=compiler_state.table_stats,
            )
        except PlanningError as e:
            raise QueryError(str(e)) from e

        qid = uuid.uuid4().hex[:12]
        trace.qid = qid
        data_agents = list(dplan.data_agent_ids)
        if not dplan.kelvin_agent_ids:
            raise QueryError("no live agent available to run the query")
        merge_agent = dplan.kelvin_agent_ids[0]

        # Predicted cost (pxbound): the logical plan's resource envelope
        # + the split's bridge wire bound. Stamped on the broker trace
        # (predicted-vs-observed in `px debug queries`), attached to
        # every dispatch, and the admission decision's input.
        from ..analysis.bounds import merged_cost
        from ..config import get_flag

        predicted = merged_cost(
            getattr(compiled.plan, "resource_report", None),
            getattr(dplan, "resource_report", None),
        )
        # Calibration (admission_observed_floor): floor the plan-time
        # prediction at this script hash's OBSERVED staged-byte history
        # — a sketch-less unknown becomes the observed bytes (admitted
        # against reality instead of accounted at zero), and a
        # prediction below past observations is raised to them. The
        # floored dict flows everywhere predicted_cost does: the trace
        # (`px debug queries` pred + pred/obs columns), every dispatch,
        # the client result, and the admission decision below. Gated on
        # admission actually being ON: with no budget the floor would
        # only replace the auditable pxbound prediction (and blank the
        # pred/obs calibration ratio) without anyone consuming it.
        if (
            get_flag("admission_observed_floor")
            and float(get_flag("admission_bytes_budget_mb")) > 0
        ):
            predicted = self.observed_costs.floor_predicted(
                predicted, trace.script_hash
            )
        trace.predicted = predicted

        # LaunchQuery: merge fragment first (so the router can accept
        # early bridge chunks), then the per-agent data fragments —
        # every dispatch acked on receipt and retried with backoff
        # before the agent is declared lost. The tenant + absolute
        # deadline ride every dispatch: agents stamp the tenant onto
        # their fragment traces (per-agent __queries__ attribution) and
        # trip the deadline at window boundaries (exec/pipeline.py
        # DeadlineEvent) so dead work stops instead of completing.
        envelope = {"tenant": tenant}
        if deadline_unix is not None:
            envelope["deadline_unix_s"] = deadline_unix
        if self.epoch_fn is not None:
            # Broker-HA epoch fencing: agents reject dispatches stamped
            # below the highest epoch they've seen, so a deposed
            # leader's (re)dispatches die instead of double-executing.
            envelope["epoch"] = int(self.epoch_fn())
        dispatches: dict = {
            (merge_agent, "merge"): (
                f"agent.{merge_agent}.merge",
                {
                    "qid": qid,
                    "plan": dplan.merge_plan,
                    "bridge_ids": [
                        b.bridge_id for b in dplan.split.bridges
                    ],
                    "data_agents": data_agents,
                    "predicted_cost": predicted,
                    **envelope,
                },
            ),
        }
        for aid in data_agents:
            dispatches[(aid, "execute")] = (
                f"agent.{aid}.execute",
                {
                    "qid": qid,
                    "plan": dplan.split.before_blocking,
                    "merge_agent": merge_agent,
                    "predicted_cost": predicted,
                    **envelope,
                },
            )
        # Admission control: reject/queue/shed BEFORE any registration
        # or dispatch — a refused query must leak nothing. admit()
        # either records the query's predicted bytes against its
        # tenant's share (released in the finally below) or raises
        # without recording; a queued query whose deadline lapses is
        # shed here with zero agent work.
        self.admission.admit(
            qid, predicted, tenant=tenant, priority=priority,
            deadline=deadline_mono,
        )
        try:
            # Verify BEFORE registering the query: a failing check must
            # not leak the forwarder's subscriptions/dispatcher threads
            # (they are only released through wait()'s deregister).
            self._check_dispatch_sets(dplan, dispatches, merge_agent)
            self.forwarder.register_query(
                qid, data_agents, merge_agent=merge_agent,
                require_complete=require_complete, trace=trace,
            )
            # Replication (broker HA): the admission grant + dispatch
            # expectations, enough for a standby to reconcile and
            # resolve this query if this broker dies mid-flight.
            self._log_state("inflight", {
                "qid": qid, "tenant": tenant,
                "expected": list(data_agents),
                "merge_agent": merge_agent,
                "reply_to": reply_to or "",
                "require_complete": bool(require_complete),
                "predicted": predicted,
                "deadline_unix_s": deadline_unix,
            })
            with trace.span("dispatch") as sp:
                sp.attributes.update({
                    "data_agents": ",".join(data_agents),
                    "merge_agent": merge_agent,
                })
                # Trace stitching: every dispatch carries the dispatch
                # span's context envelope, so each agent's fragment/merge
                # trace parents under THIS span — one distributed trace,
                # broker -> N agents -> merge (exec/tracectx.py). Stamped
                # into the stored message dicts so background RETRIES of a
                # dispatch carry the same context.
                from ..exec import tracectx

                ctx = trace.ctx(sp)
                for key, (topic, msg) in list(dispatches.items()):
                    dispatches[key] = (topic, tracectx.attach(msg, ctx))
                self._dispatch_with_retry(qid, dispatches, trace=trace)
            result = self.forwarder.wait(
                qid, timeout_s, deadline=deadline_mono
            )
        finally:
            # The query's predicted bytes stop counting against the
            # admission budget the moment it finishes or fails.
            self.admission.release(qid)
            self._log_state("release", {"qid": qid})
        result["qid"] = qid
        result["distributed_plan"] = dplan
        result["predicted_cost"] = predicted
        result["tenant"] = tenant
        # Fold per-agent resource records into the broker's trace: the
        # distributed query's cost with per-agent attribution (served by
        # broker.debug_queries / `px debug queries` / /debug/queryz).
        # Built locally and assigned ONCE: the trace is already visible
        # to concurrent debug surfaces (to_dict iterates agent_usage),
        # so in-place insertion would race their snapshot.
        agent_usage = {}
        for aid, entry in {**result.get("agent_stats", {}),
                           **result.get("merge_stats", {})}.items():
            u = entry.get("usage")
            if isinstance(u, dict):
                agent_usage[aid] = dict(u)
                trace.usage.merge(u)
        trace.agent_usage = agent_usage
        # Result staleness (storage tier): the worst scanned-table
        # watermark lag any agent reported — how stale this answer is,
        # the validity predicate a result cache would check.
        result["freshness_lag_ms"] = round(
            trace.usage.freshness_lag_ms, 3
        )
        # Prime the result cache. Never a partial/interrupted result (a
        # degraded answer must not masquerade as a complete one on the
        # next repeat) and never a mutation script. The watermark
        # snapshot is the PRE-dispatch compiler_state one —
        # conservative: ingest that landed mid-execution makes the
        # stored watermark older than reality, so the next lookup sees
        # the advance and re-validates instead of over-trusting.
        if (
            self.result_cache.enabled()
            and cache_status != rc.BYPASS
            and not result.get("partial")
            and not result.get("interrupted")
        ):
            def _snap_wm(t, _stats=compiler_state.table_stats):
                fresh = (_stats or {}).get(t, {}).get("freshness") or {}
                wm = fresh.get("watermark")
                return None if wm is None or int(wm) < 0 else int(wm)

            cached = {
                k: v for k, v in result.items() if k != "distributed_plan"
            }
            cache_status = self.result_cache.store(
                query, compiler_state.now_ns, max_output_rows,
                compiled.plan, cached, _snap_wm,
            )
            trace.cache = cache_status
        if cache_status:
            result["cache"] = cache_status
        if mutation_states is not None:
            result["mutations"] = mutation_states
        return result

    def execute_script_streaming(
        self,
        query: str,
        on_update,
        poll_interval_s: float = 0.25,
        now_ns: int = 0,
        require_complete: bool | None = None,
    ) -> "StreamHandle":
        """Live ExecuteScript (StreamResults analog,
        ``query_result_forwarder.go:470``): dispatch streaming fragments
        to the agents and deliver incremental result batches to
        ``on_update`` until ``handle.cancel()``.

        ``on_update`` receives dicts {table, batch, seq, mode, agent}
        where mode is "append" (new rows) or "replace" (full updated
        aggregate). Errors arrive as {error}. When a data agent dies
        mid-stream the view degrades to the survivors and a
        {stream_degraded, partial, missing_agents} update is delivered
        (unless ``require_complete``, which aborts with {error}).
        """
        from ..config import get_flag

        if require_complete is None:
            require_complete = bool(get_flag("require_complete"))
        compiler_state = CompilerState(
            schemas=self.tracker.schemas(),
            registry=self.registry,
            now_ns=now_ns,
            max_output_rows=1 << 62,  # live streams are unbounded
            # Sketch stats for the planner's NDV sizing + pxbound
            # presize. Live streams bypass ADMISSION (their lifetime
            # cost is open-ended; per-execution predictions don't
            # model a polling cursor) but still get right-sized
            # buffers.
            table_stats=self.tracker.table_stats(),
        )
        state = self.tracker.distributed_state()
        compiled = compile_pxl(query, compiler_state)
        try:
            dplan = self.planner.plan(compiled.plan, state)
        except PlanningError as e:
            raise QueryError(str(e)) from e
        # Validate streamability up front (one linear source chain): a
        # bad script should fail the call, not trickle errors later.
        from ..exec.streaming import _linearize

        _linearize(dplan.split.before_blocking)

        qid = uuid.uuid4().hex[:12]
        data_agents = list(dplan.data_agent_ids)
        if not dplan.kelvin_agent_ids:
            raise QueryError("no live agent available to run the query")
        merge_agent = dplan.kelvin_agent_ids[0]

        cell: dict = {}

        def _relay(msg):
            on_update(msg)
            if "error" in msg and cell.get("handle") is not None:
                # An errored stream never recovers: stop the agents'
                # polling loops instead of leaking them server-side.
                cell["handle"].cancel()

        sub = self.bus.subscribe(f"query.{qid}.results", _relay)
        handle = StreamHandle(qid, self, sub, merge_agent=merge_agent,
                              data_agents=data_agents,
                              require_complete=require_complete)
        cell["handle"] = handle
        # Registered under the degrade lock: an agent-expiry degrade
        # sweep iterating _live_streams on another dispatcher thread
        # must either see this stream or run before it exists — an
        # unlocked insert could land mid-sweep and miss the degrade.
        with self._degrade_lock:
            self._live_streams[qid] = handle
        # Close the planning window: if the merge agent expired between
        # the tracker snapshot and this registration, its one-shot
        # expiry event already fired — abort now instead of never (and
        # skip dispatch: no point starting cursors for a dead query).
        if not self.tracker.has_agent(merge_agent):
            self._abort_streams_of(merge_agent, "expired during planning")
            return handle
        envelope: dict = {}
        if self.epoch_fn is not None:
            # Same epoch fencing as one-shot dispatch (broker HA).
            envelope["epoch"] = int(self.epoch_fn())
        dispatches: dict = {
            (merge_agent, "stream_merge"): (
                f"agent.{merge_agent}.stream_merge",
                {
                    "qid": qid,
                    "plan": dplan.merge_plan,
                    "bridge_ids": [
                        b.bridge_id for b in dplan.split.bridges
                    ],
                    "data_agents": data_agents,
                    **envelope,
                },
            ),
        }
        for aid in data_agents:
            dispatches[(aid, "stream_execute")] = (
                f"agent.{aid}.stream_execute",
                {
                    "qid": qid,
                    "plan": dplan.split.before_blocking,
                    "merge_agent": merge_agent,
                    "poll_interval_s": poll_interval_s,
                    **envelope,
                },
            )

        def _stream_dispatch_lost(aid, kind):
            # Scoped to THIS qid: the verdict only says this query's
            # dispatch went missing — other live streams on the same
            # agent are demonstrably fine (they acked theirs).
            why = f"unreachable ({kind} dispatch un-acked)"
            if kind == "stream_merge":
                # No merge installed = the stream can never produce:
                # abort loudly rather than degrade.
                h = self._live_streams.pop(qid, None)
                if h is None:
                    return
                self.bus.publish(
                    f"query.{qid}.results",
                    {"error": f"merge agent {aid} {why}; live query "
                              f"{qid} aborted"},
                )
                h.cancel()
            else:
                self._degrade_one_stream(qid, aid, why)

        try:
            self._check_dispatch_sets(dplan, dispatches, merge_agent)
        except QueryError:
            # The stream is already registered (the planning-window
            # close above needs it); a failing check must unwind it or
            # the phantom stream leaks its results subscription and
            # stays visible to degrade sweeps forever.
            with self._degrade_lock:
                self._live_streams.pop(qid, None)
            sub.unsubscribe()
            raise
        self._dispatch_with_retry(
            qid, dispatches, on_lost=_stream_dispatch_lost,
            live=lambda: qid in self._live_streams,
        )
        # Close the DATA-agent planning window symmetrically: an agent
        # that expired between the tracker snapshot and the stream
        # registration fired its one-shot expiry event before we could
        # hear it — degrade (or abort) now instead of leaving the live
        # merge waiting on a dead agent's states forever.
        for aid in list(handle.data_agents):
            if not self.tracker.has_agent(aid):
                self._degrade_streams_of(aid, "expired during planning")
        return handle

    # -- bus API (the VizierService gRPC surface analog) ---------------------

    def serve(self) -> None:
        """Expose the broker on bus topics so remote clients (CLI/API over
        the framed-TCP netbus) can execute scripts and introspect the
        cluster — the api.vizierpb.VizierService analog
        (``src/api/proto/vizierpb/vizierapi.proto`` ExecuteScript).

        Topics (all request/reply via ``_reply_to``):
          broker.execute  {query, timeout_s?, max_output_rows?, tenant?,
                          priority?, deadline_ms?}
                          -> {ok, qid, tables, agent_stats} | {ok: False, error}
          broker.cancel   {qid} -> {ok, cancelled} — cooperative
                          cancellation (px cancel); the query returns
                          partial with reason "cancelled"
          broker.execute_stream {query, update_topic, poll_interval_s?}
                          -> {ok, qid}; incremental updates then flow to
                          ``update_topic`` as {table, batch, seq, mode}
                          (or {error}) until broker.stream_cancel {qid}
          broker.stream_cancel {qid} -> {ok}
          broker.schemas  {} -> {ok, schemas: {table: Relation}}
          broker.agents   {} -> {ok, agents: [agent info dict]}
          broker.scripts  {} -> {ok, scripts: [name]}
          broker.debug_queries {limit?} -> {ok, in_flight, queries}
                          recent distributed-query traces with resource
                          usage + per-agent attribution (px debug queries)
        """
        # Idempotent: a second serve() would double-subscribe every
        # topic (each request handled twice — duplicate replies,
        # double-spawned workers, double-counted metrics).
        if getattr(self, "_serve_subs", None):
            return

        def _reply(msg, payload):
            inbox = msg.get("_reply_to")
            if inbox:
                self.bus.publish(inbox, payload)

        def _auth(msg):
            """Verify the request's bearer token; returns the AuthContext
            (threaded into handlers the way the reference's authcontext
            rides the gRPC metadata). No-op when auth is disabled."""
            from .auth import verify_token

            return verify_token(self.secret, msg.get("token"))

        def _guarded(handler):
            def wrapped(msg):
                from .auth import AuthError

                try:
                    msg["_auth"] = _auth(msg)
                except AuthError as e:
                    _reply(msg, {"ok": False, "error": f"AuthError: {e}"})
                    return
                handler(msg)

            return wrapped

        def _run_execute(msg):
            try:
                rc = msg.get("require_complete")
                dl = msg.get("deadline_ms")
                res = self.execute_script(
                    msg["query"],
                    timeout_s=float(msg.get("timeout_s", 30.0)),
                    now_ns=int(msg.get("now_ns", 0)),
                    max_output_rows=int(msg.get("max_output_rows", 10_000)),
                    require_complete=None if rc is None else bool(rc),
                    tenant=msg.get("tenant"),
                    priority=int(msg.get("priority", 0)),
                    deadline_ms=None if dl is None else float(dl),
                    # Broker HA: replicated with the in-flight record so
                    # a successor leader can answer this caller's inbox.
                    reply_to=msg.get("_reply_to"),
                )
                _reply(msg, {
                    "ok": True,
                    "qid": res.get("qid"),
                    "tables": res.get("tables", {}),
                    "agent_stats": res.get("agent_stats", {}),
                    "partial": res.get("partial", False),
                    "missing_agents": res.get("missing_agents", []),
                    "missing_reasons": res.get("missing_reasons", {}),
                    "interrupted": res.get("interrupted"),
                    "mutations": res.get("mutations"),
                    "predicted_cost": res.get("predicted_cost"),
                    "tenant": res.get("tenant"),
                    "freshness_lag_ms": res.get("freshness_lag_ms"),
                    "cache": res.get("cache", ""),
                })
            except QueryAbandoned:
                # Broker-HA kill released this wait without cancelling
                # the agents: the successor leader re-attaches and
                # answers the caller's inbox — replying here would race
                # (and beat) the real answer.
                return
            except Exception as e:  # errors cross the wire as data
                if self.ha_suppress_errors:
                    # Killed broker: its dispatches are epoch-fenced, so
                    # failures here (un-acked retries -> AgentLost) are
                    # artifacts of its own death. The query was mirrored
                    # before dispatch; the successor answers the inbox —
                    # an error reply now would consume the caller's
                    # one-shot inbox and beat the real answer.
                    return
                _reply(msg, {"ok": False, "error": f"{type(e).__name__}: {e}"})

        # One DAEMON worker thread per in-flight request, capped PER
        # TENANT: the broker.execute topic has a SINGLE bus dispatcher
        # thread, so an admission-queued (or merely slow) query handled
        # inline would head-of-line block every other tenant's
        # requests — and a single GLOBAL pool merely moves that
        # blocking up a level (one tenant's requests parked in
        # admission waits would hold every worker while other tenants'
        # requests rot in a shared FIFO). Per-tenant caps keep the
        # isolation contract at the front door: tenant A's backlog
        # queues behind A's own cap, B's requests spawn their own
        # workers. Total thread count stays bounded because tenants
        # are a REGISTERED set (resolve_tenant folds unknowns into
        # "shared"): <= broker_execute_threads x (registered tenants).
        # Daemon threads (vs ThreadPoolExecutor): a slow in-flight
        # query must not block interpreter exit for its whole timeout.
        from collections import deque

        from ..config import get_flag
        from .tenancy import resolve_tenant

        # Preserve worker accounting across a stop_serving()/serve()
        # cycle (broker-HA step-down then re-election): live workers
        # hold closures over these attributes, so replacing the gate or
        # the live-count dict while a worker is draining would corrupt
        # its decrement on exit.
        if getattr(self, "_exec_gate", None) is None:
            self._exec_gate = threading.Lock()
            self._exec_live: dict = {}     # tenant -> live worker count
            self._exec_backlog: dict = {}  # tenant -> deque of messages
        self._exec_closed = False

        # Backlog bound: per tenant, this many waiting requests ride
        # behind the cap before the front door fails fast (each parked
        # message holds query text + a reply handle — unbounded growth
        # at the exact overload moment this layer defends against).
        _BACKLOG_PER_WORKER = 8

        def _execute_worker(msg, tenant):
            while msg is not None:
                _run_execute(msg)
                msg = None
                while msg is None:
                    with self._exec_gate:
                        backlog = self._exec_backlog.get(tenant)
                        if backlog and not self._exec_closed:
                            msg, enq_t, give_up = backlog.popleft()
                        else:
                            self._exec_live[tenant] -= 1
                            if not self._exec_live[tenant]:
                                del self._exec_live[tenant]
                            return
                    if time.monotonic() >= give_up:
                        # The client's own request timeout elapsed
                        # while this waited behind the tenant's cap:
                        # executing it now is dead agent work for a
                        # caller that already gave up.
                        _reply(msg, {
                            "ok": False,
                            "error": "BrokerOverloaded: request "
                                     f"expired after {time.monotonic() - enq_t:.1f}s "
                                     "in the tenant's front-door "
                                     "backlog (broker_execute_threads)",
                        })
                        msg = None

        def _on_execute(msg):
            tenant = resolve_tenant(msg.get("tenant"), count_unknown=False)
            cap = max(1, int(get_flag("broker_execute_threads")))
            with self._exec_gate:
                if self._exec_closed:
                    return
                if self._exec_live.get(tenant, 0) >= cap:
                    backlog = self._exec_backlog.setdefault(
                        tenant, deque()
                    )
                    if len(backlog) >= cap * _BACKLOG_PER_WORKER:
                        full = True
                    else:
                        full = False
                        now = time.monotonic()
                        backlog.append((
                            msg, now,
                            now + float(msg.get("timeout_s", 30.0)),
                        ))
                else:
                    full = None
                    self._exec_live[tenant] = (
                        self._exec_live.get(tenant, 0) + 1
                    )
            if full:  # fail fast OUTSIDE the gate: publish can be slow
                _reply(msg, {
                    "ok": False,
                    "error": "BrokerOverloaded: tenant front-door "
                             "backlog full (broker_execute_threads x "
                             f"{_BACKLOG_PER_WORKER} waiting requests)",
                })
            elif full is None:
                threading.Thread(
                    target=_execute_worker, args=(msg, tenant),
                    name="broker-execute", daemon=True,
                ).start()

        def _on_cancel(msg):
            qid = msg.get("qid")
            _reply(msg, {
                "ok": True,
                "cancelled": bool(qid) and self.cancel_query(str(qid)),
            })

        def _on_execute_stream(msg):
            topic = msg.get("update_topic")
            try:
                if not topic:
                    raise QueryError("execute_stream needs an update_topic")

                def _push(u, _topic=topic):
                    # publish() reports delivery count: the client
                    # subscribed to its inbox before requesting, so zero
                    # receivers means it disconnected — reap the stream
                    # rather than polling for a ghost.
                    if self.bus.publish(_topic, u) == 0:
                        h = self._live_streams.pop(
                            handle_box.get("qid"), None
                        )
                        if h is not None:
                            h.cancel()

                rc = msg.get("require_complete")
                handle_box: dict = {}
                handle = self.execute_script_streaming(
                    msg["query"],
                    on_update=_push,
                    poll_interval_s=float(msg.get("poll_interval_s", 0.25)),
                    now_ns=int(msg.get("now_ns", 0)),
                    require_complete=None if rc is None else bool(rc),
                )
                handle_box["qid"] = handle.qid
                _reply(msg, {"ok": True, "qid": handle.qid})
            except Exception as e:
                _reply(msg, {"ok": False, "error": f"{type(e).__name__}: {e}"})

        def _on_stream_cancel(msg):
            # GIL-atomic pop: exactly-once vs a racing aborter, same
            # protocol as _abort_streams_of (see baseline.json).
            handle = self._live_streams.pop(msg.get("qid"), None)  # pxlint: disable=thread-shared-state
            if handle is not None:
                handle.cancel()
            _reply(msg, {"ok": True})

        def _on_schemas(msg):
            _reply(msg, {"ok": True, "schemas": self.tracker.schemas()})

        def _on_agents(msg):
            # "broker" names which replica answered (`px agents` prints
            # it) — meaningful under broker HA, empty on a plain broker.
            _reply(msg, {
                "ok": True,
                "agents": self.tracker.agents_info(),
                "broker": self.broker_id,
            })

        def _on_scripts(msg):
            from ..scripts import list_scripts

            _reply(msg, {"ok": True, "scripts": list_scripts()})

        def _on_profile(msg):
            # `px profile` / api.Client.profile: the cluster-merged
            # folded-stack CPU profile (tracker heartbeat summaries +
            # the broker's own profiler), optionally filtered.
            try:
                n = max(1, min(int(msg.get("limit", 64)), 4096))
            except (TypeError, ValueError):
                n = 64
            rows = self.profile_rows(
                agent_id=msg.get("agent") or None,
                tenant=msg.get("tenant") or None,
                script_hash=msg.get("script") or None,
            )
            _reply(msg, {
                "ok": True,
                "agents": self.profile_agents(),
                "stacks": rows[:n],
            })

        def _on_debug_queries(msg):
            # `px debug queries`: the broker's recent distributed-query
            # traces — status, duration, resource usage with per-agent
            # attribution (QueryTrace.to_dict carries usage/agent_usage).
            try:
                n = max(1, min(int(msg.get("limit", 50)), 500))
            except (TypeError, ValueError):
                n = 50
            _reply(msg, {
                "ok": True,
                "in_flight": self.tracer.in_flight(),
                "queries": self.tracer.recent()[:n],
                # Admission-scheduler view: per-tenant in-flight
                # predicted bytes + the ordered wait queue.
                "admission": {
                    "in_flight_by_tenant":
                        self.admission.in_flight_by_tenant(),
                    "queued": self.admission.queued(),
                },
            })

        self._serve_subs = [
            self.bus.subscribe("broker.execute", _guarded(_on_execute)),
            self.bus.subscribe("broker.cancel", _guarded(_on_cancel)),
            self.bus.subscribe(
                "broker.execute_stream", _guarded(_on_execute_stream)
            ),
            self.bus.subscribe(
                "broker.stream_cancel", _guarded(_on_stream_cancel)
            ),
            self.bus.subscribe("broker.schemas", _guarded(_on_schemas)),
            self.bus.subscribe("broker.agents", _guarded(_on_agents)),
            self.bus.subscribe("broker.scripts", _guarded(_on_scripts)),
            self.bus.subscribe(
                "broker.debug_queries", _guarded(_on_debug_queries)
            ),
            self.bus.subscribe("broker.profile", _guarded(_on_profile)),
        ]
