"""Chaos soak: faults x tenancy x staleness x concurrency x broker-kill.

Each resilience layer in this repo has its own gate (fault seeds,
tenancy isolation, staleness floors, backpressure) — this module
exercises them TOGETHER, the way a real incident does: a mixed-tenant
load runs against an N-agent, M-broker-replica cluster while a seeded
fault schedule drops/delays/duplicates bus traffic, partitions agents,
kills data agents mid-query, and crashes the leader broker outright
(``BrokerReplica.kill`` — a standby takes over within one lease
window, docs/RESILIENCE.md "Broker HA").

The soak's contract, asserted by :func:`run_chaos_soak` and enforced
as a tier-1 gate by ``run_tests.sh --soak``:

- **Zero lost queries.** Every submitted query resolves — complete,
  ``partial`` (with a reason), a structured admission shed/refusal, or
  a failover retry that lands on the next leader. No hangs, no reply
  that never comes (a per-query ledger audits every outcome).
- **Zero leaked threads.** The cluster tears down to its pre-soak
  thread count: forwarder waits, failover adopters, lease watchers and
  agent heartbeats all exit.
- **Isolation holds under fire.** The victim tenant's p99 during the
  chaos phase stays within the PR-13 bound (1.25x its solo baseline,
  plus a small absolute floor for sub-100ms baselines) while the noisy
  tenant saturates and the fault schedule runs.

CLI::

    python -m pixie_tpu.services.chaos --agents 32 --brokers 2 --seed 0
    python -m pixie_tpu.services.chaos --agents 128 --brokers 3 --full

A (seed, topology) pair replays the same fault schedule — the RNG is
the injector's, and the kill points are wall-clock offsets into the
load phase, so outcome COUNTS may vary slightly across machines but
the exercised paths do not.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .broker_ha import BrokerReplica
from .faults import FaultInjector
from .load_tester import TenantStream, run_load, run_mixed_load
from .msgbus import BusTimeout, MessageBus

VICTIM_QUERY = (
    "import px\n"
    "df = px.DataFrame(table='http_events')\n"
    "df = df.groupby('service').agg(\n"
    "    n=('latency_ns', px.count), mean=('latency_ns', px.mean))\n"
    "px.display(df, 'out')\n"
)

NOISY_QUERY = (
    "import px\n"
    "df = px.DataFrame(table='http_events')\n"
    "df = df.groupby(['service', 'resp_status']).agg(\n"
    "    n=('latency_ns', px.count), mean=('latency_ns', px.mean))\n"
    "px.display(df, 'out')\n"
)

# Outcomes that count as "resolved" for the zero-lost-queries gate:
# structured refusals the platform ISSUED on purpose. Anything else in
# an error reply is a lost query.
_REFUSALS = ("admission-shed", "admission-reject", "BrokerOverloaded",
             "cancelled")


class _Ledger:
    """Per-query outcome audit, independent of LoadReport aggregation:
    the zero-lost gate needs the error MESSAGES (to tell a structured
    refusal from a genuine loss), which LoadReport folds into type
    names."""

    def __init__(self):
        self.lock = threading.Lock()
        self.submitted = 0
        self.outcomes: dict[str, int] = {}
        self.lost: list[str] = []
        self.failover_retries = 0

    def record(self, outcome: str, detail: str = "") -> None:
        with self.lock:
            self.submitted += 1
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            if outcome == "lost":
                self.lost.append(detail[:200])

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "submitted": self.submitted,
                "outcomes": dict(self.outcomes),
                "lost": list(self.lost),
                "failover_retries": self.failover_retries,
            }


def failover_executor(bus, ledger: _Ledger | None = None,
                      max_attempts: int = 6, backoff_s: float = 0.15):
    """``run_load``-shaped executor that discovers the leader implicitly
    (only the leader subscribes ``broker.execute``) and retries through
    a failover window: a :class:`BusTimeout` during takeover means "no
    broker answered" — the request was not executed, so resubmitting a
    read-only script to the next leader is safe."""

    def execute(query, timeout_s, **kw):
        req = {"query": query, "timeout_s": timeout_s}
        req.update((k, v) for k, v in kw.items() if v is not None)
        last: Exception | None = None
        for attempt in range(max_attempts):
            try:
                res = bus.request(
                    "broker.execute", req, timeout_s=timeout_s + 5,
                )
            except BusTimeout as e:
                last = e
                if ledger is not None:
                    with ledger.lock:
                        ledger.failover_retries += 1
                time.sleep(backoff_s * (attempt + 1))
                continue
            if not res.get("ok"):
                err = str(res.get("error", "unknown broker error"))
                if ledger is not None:
                    resolved = any(m in err for m in _REFUSALS)
                    ledger.record("refused" if resolved else "lost", err)
                raise RuntimeError(err)
            if ledger is not None:
                ledger.record("partial" if res.get("partial") else "ok")
            return res
        if ledger is not None:
            ledger.record("lost", f"no broker answered: {last}")
        raise last  # type: ignore[misc]

    return execute


@dataclass
class ChaosReport:
    agents: int = 0
    brokers: int = 0
    seed: int = 0
    wall_s: float = 0.0
    baseline_p99_ms: float = 0.0
    victim_p99_ms: float = 0.0
    victim_p99_bound_ms: float = 0.0
    isolation_ok: bool = True
    ledger: dict = field(default_factory=dict)
    lost: list = field(default_factory=list)
    faults_fired: int = 0
    leader_kills: int = 0
    failovers: int = 0
    agent_kills: int = 0
    partitions_healed: int = 0
    threads_before: int = 0
    threads_after: int = 0
    thread_leak: bool = False
    streams: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return (
            not self.lost and not self.thread_leak and self.isolation_ok
            and (self.leader_kills == 0 or self.failovers > 0)
        )

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "agents": self.agents,
            "brokers": self.brokers,
            "seed": self.seed,
            "wall_s": round(self.wall_s, 2),
            "baseline_p99_ms": round(self.baseline_p99_ms, 2),
            "victim_p99_ms": round(self.victim_p99_ms, 2),
            "victim_p99_bound_ms": round(self.victim_p99_bound_ms, 2),
            "isolation_ok": self.isolation_ok,
            "ledger": self.ledger,
            "lost": self.lost,
            "faults_fired": self.faults_fired,
            "leader_kills": self.leader_kills,
            "failovers": self.failovers,
            "agent_kills": self.agent_kills,
            "partitions_healed": self.partitions_healed,
            "threads_before": self.threads_before,
            "threads_after": self.threads_after,
            "thread_leak": self.thread_leak,
            "streams": self.streams,
        }


def _current_leader(replicas):
    for r in replicas:
        if not r._dead and r.role == "leader":
            return r
    return None


def _wait_for(pred, timeout_s: float, interval_s: float = 0.05) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def run_chaos_soak(
    n_agents: int = 32,
    n_brokers: int = 2,
    seed: int = 0,
    rows: int = 400,
    per_worker: int = 4,
    noisy_workers: int = 2,
    timeout_s: float = 20.0,
    kill_leader: bool = True,
    p99_floor_s: float = 2.0,
) -> ChaosReport:
    """Build the cluster, run the soak, tear down, audit. See module
    docstring for the asserted contract."""
    import numpy as np

    from ..config import override_flag
    from .agent import KelvinAgent, PEMAgent

    report = ChaosReport(agents=n_agents, brokers=n_brokers, seed=seed)
    report.threads_before = threading.active_count()
    t0 = time.perf_counter()

    with override_flag("broker_lease_interval_s", 0.1), \
            override_flag("broker_lease_expiry_s", 0.5), \
            override_flag("broker_reconcile_wait_s", 0.4), \
            override_flag("broker_reattach_timeout_s", 8.0):
        bus = MessageBus()
        inj = FaultInjector(seed)
        tracker_kw = dict(expiry_s=60.0, check_interval_s=60.0,
                          flap_threshold=3, flap_window_s=60.0,
                          quarantine_s=1.0)
        replicas = [
            BrokerReplica(bus, f"broker-{i}", tracker_kw=tracker_kw,
                          leader=(i == 0))
            for i in range(n_brokers)
        ]
        n_kelvin = max(1, n_agents // 16)
        agents = []
        rng = np.random.default_rng(seed)
        for i in range(n_agents - n_kelvin):
            pem = PEMAgent(bus, f"pem-{i}", heartbeat_interval_s=5.0)
            n = max(rows // 4, 64) if i % 7 == 0 else rows
            pem.engine.append_data("http_events", {
                # Wall-clock-anchored timestamps: the freshness column
                # reports real watermark lag, not epoch-zero nonsense.
                "time_": np.int64(time.time_ns())
                + np.arange(n, dtype=np.int64),
                "latency_ns": rng.integers(1_000, 1_000_000, n),
                "resp_status": rng.choice(
                    np.array([200, 200, 200, 500], dtype=np.int64), n
                ),
                "service": [f"svc-{j % 8}" for j in range(n)],
            })
            agents.append(pem.start())
        for i in range(n_kelvin):
            agents.append(
                KelvinAgent(
                    bus, f"kelvin-{i}", heartbeat_interval_s=5.0
                ).start()
            )
        leader = replicas[0]
        if not _wait_for(
            lambda: len(leader.tracker.agent_ids()) == len(agents)
            and "http_events" in leader.tracker.schemas(),
            timeout_s=15.0,
        ):
            raise RuntimeError(
                "chaos cluster never converged: "
                f"{len(leader.tracker.agent_ids())}/{len(agents)} agents"
            )

        ledger = _Ledger()
        execute = failover_executor(bus, ledger)

        # Warm-up (uncounted, ledger-free executor): both phases then
        # run with the XLA compile cache hot, so the baseline/chaos p99
        # comparison measures the cluster, not the first query's
        # compile.
        warm = failover_executor(bus)
        for q in (VICTIM_QUERY, NOISY_QUERY):
            try:
                warm(q, timeout_s)
            except Exception:
                pass  # the measured phases will report the failure mode

        # Phase A: the victim's SOLO baseline on the healthy cluster —
        # the denominator of the PR-13 isolation bound.
        base = run_load(
            execute, VICTIM_QUERY, workers=2, per_worker=per_worker,
            timeout_s=timeout_s, tenant="dash",
        )
        report.baseline_p99_ms = base.percentile(99) * 1e3

        # Phase B: mixed tenants + the fault schedule. Background noise
        # rules are low-probability and count-capped so retries absorb
        # them (an exhausted dispatch retry would read as a lost query
        # — that's the AGENT-kill path's job to exercise, attributably).
        bus.fault_injector = inj
        inj.drop("agent.*.ack", prob=0.05, count=10)
        inj.delay("agent.*.bridge", 0.05, prob=0.1, count=30)
        inj.duplicate("agent.*.execute", prob=0.05, count=10)

        stop = threading.Event()

        def _chaos_driver():
            # Wall-clock offsets into the load phase; each step bails
            # if the load finished first.
            if stop.wait(0.5):
                return
            # Partition one mid-fleet PEM from the control plane, heal
            # shortly after: in-window queries go partial/expired or
            # ride retries, NOTHING hangs.
            inj.partition("pem-3", "broker")
            if stop.wait(0.6):
                report.partitions_healed += inj.heal()
                return
            report.partitions_healed += inj.heal()
            # Kill a data agent outright mid-query: force-expired so
            # failure detection is deterministic.
            victim_agent = next(
                (a for a in agents if a.agent_id == "pem-5"), None
            )
            lead = _current_leader(replicas)
            if victim_agent is not None and lead is not None:
                victim_agent.stop()
                lead.tracker.force_expire(
                    victim_agent.agent_id, reason="chaos kill"
                )
                report.agent_kills += 1
            if stop.wait(0.5):
                return
            # The headline event: crash the leader with queries in
            # flight. A standby claims the next epoch within one lease
            # window and adopts the mirror.
            if kill_leader:
                lead = _current_leader(replicas)
                if lead is not None and len(replicas) > 1:
                    lead.kill()
                    report.leader_kills += 1

        driver = threading.Thread(
            target=_chaos_driver, daemon=True, name="chaos-driver"
        )
        streams = [
            TenantStream(tenant="dash", query=VICTIM_QUERY, workers=2,
                         per_worker=per_worker * 2, priority=1,
                         timeout_s=timeout_s),
            TenantStream(tenant="batch", query=NOISY_QUERY,
                         workers=noisy_workers,
                         per_worker=per_worker * 2,
                         timeout_s=timeout_s),
        ]
        # The budget is the isolation MECHANISM, so it must be sized to
        # the workload, not generous: the batch tenant's quarter-share
        # should admit roughly ONE of its queries at a time (predicted
        # staged bytes scale with total fleet rows), so its burst
        # QUEUES behind its own share instead of either saturating the
        # core (budget too big) or being hard-rejected at the door
        # before any pressure exists (budget too small).
        budget_mb = max(4.0, 6.0 * (n_agents / 32.0) * (rows / 400.0))
        with override_flag("admission_tenant_weights", "dash:3,batch:1"), \
                override_flag("admission_bytes_budget_mb", budget_mb), \
                override_flag("admission_queue_s", 10.0):
            driver.start()
            reports = run_mixed_load(execute, streams)
        stop.set()
        driver.join(timeout=10.0)
        inj.heal()

        report.victim_p99_ms = reports["dash"].percentile(99) * 1e3
        # The PR-13 multiplier plus an absolute floor: one failover
        # window (lease expiry + reconcile + a retry ladder) can land
        # whole on a tail query, which would swamp a sub-100ms baseline
        # under a bare 1.25x. The floor absorbs exactly that; the check
        # still catches isolation COLLAPSE (victim p99 at timeout
        # scale). The precise 1.25x tenancy bound stays --tenancy's.
        bound_s = 1.25 * (report.baseline_p99_ms / 1e3) + p99_floor_s
        report.victim_p99_bound_ms = bound_s * 1e3
        report.isolation_ok = (
            report.victim_p99_ms <= report.victim_p99_bound_ms
        )
        report.streams = {k: r.to_dict() for k, r in reports.items()}
        report.faults_fired = inj.fired()
        report.failovers = sum(r.failovers for r in replicas)
        report.ledger = ledger.snapshot()
        report.lost = report.ledger["lost"]

        # Teardown, then audit the thread count: every lease watcher,
        # forwarder wait, failover adopter and heartbeat must exit.
        bus.fault_injector = None
        for a in agents:
            a.stop()
        for r in replicas:
            if not r._dead:
                r.close()
        bus.close()
    settled = _wait_for(
        lambda: threading.active_count() <= report.threads_before + 1,
        timeout_s=12.0, interval_s=0.2,
    )
    report.threads_after = threading.active_count()
    report.thread_leak = not settled
    report.wall_s = time.perf_counter() - t0
    return report


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m pixie_tpu.services.chaos",
        description=(
            "Combined chaos soak: mixed-tenant load against an N-agent "
            "M-broker cluster under a seeded fault schedule including a "
            "leader-broker kill. Exit 0 iff zero lost queries, zero "
            "leaked threads, and the victim tenant's p99 held its "
            "isolation bound."
        ),
    )
    ap.add_argument("--agents", type=int, default=32)
    ap.add_argument("--brokers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rows", type=int, default=400)
    ap.add_argument("--per-worker", type=int, default=4)
    ap.add_argument("--no-leader-kill", action="store_true",
                    help="skip the leader-crash event (agent faults "
                         "and partitions only)")
    ap.add_argument("--full", action="store_true",
                    help="the long soak: more offered load per worker")
    args = ap.parse_args(argv)

    report = run_chaos_soak(
        n_agents=args.agents,
        n_brokers=args.brokers,
        seed=args.seed,
        rows=args.rows,
        per_worker=args.per_worker * (3 if args.full else 1),
        kill_leader=not args.no_leader_kill,
    )
    print(json.dumps(report.to_dict(), indent=2))
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
