"""Query load tester: concurrency sweep against a broker.

Reference parity: the vizier query load tester
(``/root/reference/src/vizier/utils/loadtester``) — N concurrent
clients, M queries each, latency percentiles and error counts. Works
against an in-process ``QueryBroker`` or a remote broker over the
netbus (``RemoteBus`` + the ``broker.execute`` topic).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class LoadReport:
    queries: int = 0
    errors: int = 0
    partials: int = 0  # queries that returned with partial=True
    errors_by_type: dict = field(default_factory=dict)
    latencies_s: list = field(default_factory=list)
    wall_s: float = 0.0
    # Per-run view through the SERVER's pixie_query_duration_seconds
    # histogram (the tracer records every finished query there): the
    # concurrency-bench axis — what the serving process itself measured
    # between this run's start and end, vs the client-side latencies
    # above which include bus round trips. None when the histogram is
    # not in this process (remote broker) or saw no observations.
    hist_quantiles_s: dict | None = None
    hist_count: int = 0

    @property
    def failure_rate(self) -> float:
        return self.errors / self.queries if self.queries else 0.0

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile: ceil(p/100 * N)-th smallest."""
        if not self.latencies_s:
            return float("nan")
        import math

        xs = sorted(self.latencies_s)
        i = max(math.ceil(p / 100.0 * len(xs)) - 1, 0)
        return xs[min(i, len(xs) - 1)]

    def to_dict(self) -> dict:
        out = {
            "queries": self.queries,
            "errors": self.errors,
            "failure_rate": round(self.failure_rate, 4),
            "errors_by_type": dict(self.errors_by_type),
            "partials": self.partials,
            "qps": (
                round(self.queries / self.wall_s, 2) if self.wall_s else 0.0
            ),
            "p50_ms": round(self.percentile(50) * 1e3, 2),
            "p95_ms": round(self.percentile(95) * 1e3, 2),
            "p99_ms": round(self.percentile(99) * 1e3, 2),
            "wall_s": round(self.wall_s, 2),
        }
        if self.hist_quantiles_s is not None:
            out["hist_count"] = self.hist_count
            for q, v in sorted(self.hist_quantiles_s.items()):
                out[f"hist_p{int(q * 100)}_ms"] = round(v * 1e3, 2)
        return out


def run_load(
    execute,
    query: str,
    workers: int = 4,
    per_worker: int = 10,
    timeout_s: float = 30.0,
) -> LoadReport:
    """Fire ``workers * per_worker`` queries through ``execute``.

    ``execute(query, timeout_s)`` is any callable that raises on failure —
    ``broker_executor`` / ``remote_executor`` below adapt the two broker
    surfaces to it.
    """
    report = LoadReport()
    lock = threading.Lock()

    def worker():
        for _ in range(per_worker):
            t0 = time.perf_counter()
            err = None
            partial = False
            try:
                res = execute(query, timeout_s)
                partial = bool(
                    isinstance(res, dict) and res.get("partial")
                )
            except Exception as e:
                err = type(e).__name__
            dt = time.perf_counter() - t0
            with lock:
                report.queries += 1
                if err is None:
                    report.latencies_s.append(dt)
                    if partial:
                        report.partials += 1
                else:
                    report.errors += 1
                    report.errors_by_type[err] = (
                        report.errors_by_type.get(err, 0) + 1
                    )

    # Snapshot the server-side latency histogram around the run so the
    # report carries per-run quantiles from the SERVING process's own
    # measurement (delta interpolation over cumulative buckets).
    from .observability import default_registry, delta_quantiles

    hist_before = default_registry.histogram_state(
        "pixie_query_duration_seconds"
    )
    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    report.wall_s = time.perf_counter() - t_start
    hist_after = default_registry.histogram_state(
        "pixie_query_duration_seconds"
    )
    if hist_before is None and hist_after is not None:
        # The histogram registers lazily on the FIRST finished query —
        # a missing before-snapshot in a fresh process means zero
        # observations, not "no data": synthesize the empty state so
        # the first run still reports its quantiles.
        bounds, counts, _total, _sum = hist_after
        hist_before = (bounds, [0] * len(counts), 0, 0.0)
    report.hist_quantiles_s = delta_quantiles(hist_before, hist_after)
    if hist_before is not None and hist_after is not None:
        report.hist_count = hist_after[2] - hist_before[2]
    return report


def broker_executor(broker):
    """Adapter for an in-process QueryBroker."""

    def execute(query, timeout_s):
        return broker.execute_script(query, timeout_s=timeout_s)

    return execute


def remote_executor(host: str, port: int):
    """Adapter for a served broker over the netbus (one shared conn)."""
    from .netbus import RemoteBus

    bus = RemoteBus(host, port)

    def execute(query, timeout_s):
        res = bus.request(
            "broker.execute",
            {"query": query, "timeout_s": timeout_s},
            timeout_s=timeout_s + 5,
        )
        if not res.get("ok"):
            raise RuntimeError(res.get("error", "unknown broker error"))
        return res

    execute.close = bus.close  # type: ignore[attr-defined]
    return execute
