"""Query load tester: concurrency sweep against a broker.

Reference parity: the vizier query load tester
(``/root/reference/src/vizier/utils/loadtester``) — N concurrent
clients, M queries each, latency percentiles and error counts. Works
against an in-process ``QueryBroker`` or a remote broker over the
netbus (``RemoteBus`` + the ``broker.execute`` topic).

CLI (the ROADMAP's concurrency bench seam — the measurement for the
``Engine._exec_guard`` narrowing, see docs/ANALYSIS.md "pxlock"):

    python -m pixie_tpu.services.load_tester --concurrency 1,2,4 \\
        [--broker HOST:PORT | --local] [--script q.pxl] [--per-worker N]

runs the same offered load at each client-thread count N and reports
qps + p50/p95/p99 per N — client-side latencies plus the per-run
quantiles from the serving process's own ``pixie_query_duration_seconds``
histogram deltas. Flat qps from 1 -> N client threads means the serving
path serializes; scaling qps is the concurrency unlock, measured.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class LoadReport:
    queries: int = 0
    errors: int = 0
    partials: int = 0  # queries that returned with partial=True
    # Queries shed at admission before any dispatch (the structured
    # "admission-shed" refusal: deadline lapsed while queued). Counted
    # separately from errors_by_type so tenancy gates can assert "the
    # victim tenant shed ZERO queries" directly.
    sheds: int = 0
    errors_by_type: dict = field(default_factory=dict)
    latencies_s: list = field(default_factory=list)
    # Worst result staleness any query of the run reported
    # (freshness_lag_ms: the same number the agents fold into the
    # __queries__ column — worst scanned-table watermark lag at execute
    # time). A staleness regression (ingest stalling under load, a PEM
    # falling behind) shows up here even when latency holds.
    max_freshness_lag_ms: float = 0.0
    wall_s: float = 0.0
    # Per-run view through the SERVER's pixie_query_duration_seconds
    # histogram (the tracer records every finished query there): the
    # concurrency-bench axis — what the serving process itself measured
    # between this run's start and end, vs the client-side latencies
    # above which include bus round trips. None when the histogram is
    # not in this process (remote broker) or saw no observations.
    hist_quantiles_s: dict | None = None
    hist_count: int = 0
    # Repeat-mode only: result-cache disposition counts over the run
    # ({"hit": 37, "miss": 1, ...} from the broker reply's ``cache``
    # key / the engine trace). Empty outside --repeat-script runs.
    cache_counts: dict = field(default_factory=dict)
    # Per-tenant CPU-seconds burned during the run, from the serving
    # process's pixie_cpu_samples_total{tenant} counter deltas scaled
    # by the profiler's sampling period (ingest/profiler.py) — the
    # tenancy gate's "the noisy tenant's burn is VISIBLE" assertion
    # next to qps/p99. Empty when self-profiling is off or the
    # profiler runs in another process (remote broker).
    cpu_seconds_by_tenant: dict = field(default_factory=dict)
    # Transport-tier view of the run: p99 dispatcher lag through the
    # serving process's pixie_bus_dispatch_lag_seconds histogram
    # (delta-bracketed like hist_quantiles_s) and the worst
    # pixie_bus_queue_high_water gauge across topic classes. Queueing
    # INSIDE the bus — a subscriber falling behind the offered load —
    # shows up here before it widens the end-to-end latency columns.
    # None/0 when the bus runs in another process or bus_telemetry is
    # off.
    bus_lag_p99_ms: float | None = None
    bus_queue_high_water: int = 0

    @property
    def failure_rate(self) -> float:
        return self.errors / self.queries if self.queries else 0.0

    @property
    def cache_hit_rate(self) -> float:
        """hit + view over all queries that reported a disposition
        (a "view" answer IS a repeat served from sketch state)."""
        total = sum(self.cache_counts.values())
        if not total:
            return 0.0
        served = (self.cache_counts.get("hit", 0)
                  + self.cache_counts.get("view", 0))
        return served / total

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile: ceil(p/100 * N)-th smallest."""
        if not self.latencies_s:
            return float("nan")
        import math

        xs = sorted(self.latencies_s)
        i = max(math.ceil(p / 100.0 * len(xs)) - 1, 0)
        return xs[min(i, len(xs) - 1)]

    def to_dict(self) -> dict:
        out = {
            "queries": self.queries,
            "errors": self.errors,
            "failure_rate": round(self.failure_rate, 4),
            "errors_by_type": dict(self.errors_by_type),
            "partials": self.partials,
            "sheds": self.sheds,
            "qps": (
                round(self.queries / self.wall_s, 2) if self.wall_s else 0.0
            ),
            "p50_ms": round(self.percentile(50) * 1e3, 2),
            "p95_ms": round(self.percentile(95) * 1e3, 2),
            "p99_ms": round(self.percentile(99) * 1e3, 2),
            "max_freshness_lag_ms": round(self.max_freshness_lag_ms, 1),
            "wall_s": round(self.wall_s, 2),
        }
        if self.hist_quantiles_s is not None:
            out["hist_count"] = self.hist_count
            for q, v in sorted(self.hist_quantiles_s.items()):
                out[f"hist_p{int(q * 100)}_ms"] = round(v * 1e3, 2)
        if self.cache_counts:
            out["cache_counts"] = dict(self.cache_counts)
            out["cache_hit_rate"] = round(self.cache_hit_rate, 4)
        if self.cpu_seconds_by_tenant:
            out["cpu_seconds_by_tenant"] = dict(self.cpu_seconds_by_tenant)
        if self.bus_lag_p99_ms is not None:
            out["bus_lag_p99_ms"] = round(self.bus_lag_p99_ms, 3)
        if self.bus_queue_high_water:
            out["bus_queue_high_water"] = self.bus_queue_high_water
        return out


@dataclass
class TenantStream:
    """One tenant's offered load in a mixed-tenant run: ``workers``
    concurrent clients each firing ``per_worker`` queries of ``query``
    under this tenant/priority/deadline."""

    tenant: str
    query: str
    workers: int = 1
    per_worker: int = 10
    priority: int = 0
    deadline_ms: float | None = None
    timeout_s: float = 30.0


def _worker_loop(execute, query: str, per_worker: int, timeout_s: float,
                 report: LoadReport, lock: threading.Lock,
                 exec_kw: dict | None = None) -> None:
    """Shared per-worker query loop for the plain and mixed modes."""
    kw = exec_kw or {}
    for _ in range(per_worker):
        t0 = time.perf_counter()
        err = None
        shed = False
        partial = False
        fresh_ms = 0.0
        try:
            res = execute(query, timeout_s, **kw)
            partial = bool(isinstance(res, dict) and res.get("partial"))
            # Broker replies carry the staleness as a dict key;
            # api.ScriptResults (a dict of TABLES) as an attribute.
            v = res.get("freshness_lag_ms") if isinstance(res, dict) else None
            if v is None:
                v = getattr(res, "freshness_lag_ms", None)
            fresh_ms = float(v or 0.0)
        except Exception as e:
            err = type(e).__name__
            # The admission scheduler's structured deadline shed (never
            # dispatched) is a distinct outcome from a failure.
            shed = "admission-shed" in str(e)
        dt = time.perf_counter() - t0
        with lock:
            report.queries += 1
            if err is None:
                report.latencies_s.append(dt)
                report.max_freshness_lag_ms = max(
                    report.max_freshness_lag_ms, fresh_ms
                )
                if partial:
                    report.partials += 1
            else:
                report.errors += 1
                if shed:
                    report.sheds += 1
                report.errors_by_type[err] = (
                    report.errors_by_type.get(err, 0) + 1
                )


def _hist_snapshot():
    from .observability import default_registry

    return default_registry.histogram_state("pixie_query_duration_seconds")


def _bus_hist_snapshot():
    from .observability import default_registry

    return default_registry.histogram_state(
        "pixie_bus_dispatch_lag_seconds"
    )


def _attach_bus_delta(report: LoadReport, before) -> None:
    """Transport-tier bracket: this run's dispatcher-lag p99 (delta
    over the cumulative bus histogram, all topic classes) and the worst
    queue high-water gauge. The gauge is monotonic per process, so no
    before-snapshot — the end value IS the worst ever seen, which is
    the number the capacity question ("did anything queue?") needs."""
    from .observability import default_registry, delta_quantiles

    after = _bus_hist_snapshot()
    if after is not None:
        if before is None:
            bounds, counts, _total, _sum = after
            before = (bounds, [0] * len(counts), 0, 0.0)
        q = delta_quantiles(before, after)
        if q:
            report.bus_lag_p99_ms = q.get(0.99, 0.0) * 1e3
    hw = default_registry.values("pixie_bus_queue_high_water")
    if hw:
        report.bus_queue_high_water = int(max(hw.values()))


def _cpu_samples_snapshot(tenants) -> dict:
    """{tenant: cumulative pixie_cpu_samples_total value} for the run's
    tenants (resolved through the registered set, like every label)."""
    from .observability import default_counter
    from .tenancy import resolve_tenant

    counter = default_counter(
        "pixie_cpu_samples_total",
        "Profiler stack samples attributed to each tenant "
        "(samples * sampling period = CPU-seconds)",
    )
    out: dict = {}
    for raw in tenants:
        tenant = resolve_tenant(raw, count_unknown=False)
        out[tenant] = counter.labels(tenant=tenant).value()
    return out


def _attach_cpu_delta(report: LoadReport, before: dict, after: dict) -> None:
    """Per-tenant CPU-seconds for the run: counter delta scaled by the
    profiler's sampling period (count * period = CPU-seconds)."""
    from ..ingest.profiler import PerfProfilerConnector

    period = PerfProfilerConnector.default_sampling_period_s
    for tenant, v in after.items():
        d = v - before.get(tenant, 0.0)
        if d > 0:
            report.cpu_seconds_by_tenant[tenant] = round(d * period, 3)


def _attach_hist_delta(report: LoadReport, before, after) -> None:
    from .observability import delta_quantiles

    if before is None and after is not None:
        # The histogram registers lazily on the FIRST finished query —
        # a missing before-snapshot in a fresh process means zero
        # observations, not "no data": synthesize the empty state so
        # the first run still reports its quantiles.
        bounds, counts, _total, _sum = after
        before = (bounds, [0] * len(counts), 0, 0.0)
    report.hist_quantiles_s = delta_quantiles(before, after)
    if before is not None and after is not None:
        report.hist_count = after[2] - before[2]


def run_load(
    execute,
    query: str,
    workers: int = 4,
    per_worker: int = 10,
    timeout_s: float = 30.0,
    tenant: str | None = None,
    priority: int = 0,
    deadline_ms: float | None = None,
) -> LoadReport:
    """Fire ``workers * per_worker`` queries through ``execute``.

    ``execute(query, timeout_s, **tenancy_kw)`` is any callable that
    raises on failure — ``broker_executor`` / ``remote_executor`` below
    adapt the two broker surfaces to it. The optional tenancy kwargs
    scope every query of the run to one tenant/priority/deadline.
    """
    report = LoadReport()
    lock = threading.Lock()
    kw: dict = {}
    if tenant is not None:
        kw["tenant"] = tenant
    if priority:
        kw["priority"] = priority
    if deadline_ms is not None:
        kw["deadline_ms"] = deadline_ms

    # Snapshot the server-side latency histogram around the run so the
    # report carries per-run quantiles from the SERVING process's own
    # measurement (delta interpolation over cumulative buckets). Same
    # bracket for the profiler's per-tenant CPU counter: the delta is
    # this run's attributed burn.
    hist_before = _hist_snapshot()
    bus_before = _bus_hist_snapshot()
    cpu_before = _cpu_samples_snapshot([tenant] if tenant else [])
    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=_worker_loop, args=(
            execute, query, per_worker, timeout_s, report, lock, kw,
        ))
        for _ in range(workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    report.wall_s = time.perf_counter() - t_start
    _attach_hist_delta(report, hist_before, _hist_snapshot())
    _attach_bus_delta(report, bus_before)
    _attach_cpu_delta(
        report, cpu_before,
        _cpu_samples_snapshot([tenant] if tenant else []),
    )
    return report


def run_mixed_load(execute, streams) -> dict:
    """Mixed-tenant mode: run every :class:`TenantStream` CONCURRENTLY
    against one broker and report a ``LoadReport`` per stream — the
    measurement seam for the p99-isolation contract (a saturating noisy
    tenant queues behind its own backlog; the victim tenant's latency
    distribution and shed count must hold at its solo baseline;
    ``run_tests.sh --tenancy``).
    """
    # One report PER STREAM: two streams may share a tenant (same
    # tenant at different priorities/deadlines) and their latency
    # distributions must not silently merge — duplicates get a
    # positional suffix ("dash", "dash#1", ...).
    keys, seen = [], {}
    for s in streams:
        n = seen.get(s.tenant, 0)
        seen[s.tenant] = n + 1
        keys.append(s.tenant if n == 0 else f"{s.tenant}#{n}")
    reports = {k: LoadReport() for k in keys}
    locks = {k: threading.Lock() for k in keys}
    threads = []
    for key, s in zip(keys, streams):
        kw = {"tenant": s.tenant, "priority": s.priority,
              "deadline_ms": s.deadline_ms}
        threads.extend(
            threading.Thread(target=_worker_loop, args=(
                execute, s.query, s.per_worker, s.timeout_s,
                reports[key], locks[key], kw,
            ))
            for _ in range(s.workers)
        )
    tenants = sorted({s.tenant for s in streams if s.tenant})
    cpu_before = _cpu_samples_snapshot(tenants)
    bus_before = _bus_hist_snapshot()
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    cpu_after = _cpu_samples_snapshot(tenants)
    from .tenancy import resolve_tenant

    for key, s in zip(keys, streams):
        reports[key].wall_s = wall
        # Per-TENANT burn, not per-stream: two streams sharing a tenant
        # ("dash", "dash#1") each report the tenant's total — the CPU
        # counter only carries the tenant label, and splitting it would
        # fake a precision the sampler doesn't have.
        own = resolve_tenant(s.tenant, count_unknown=False)
        _attach_cpu_delta(
            reports[key],
            {own: cpu_before.get(own, 0.0)},
            {own: cpu_after.get(own, 0.0)},
        )
        # The bus is shared across streams: every report carries the
        # run's WHOLE transport view (per-stream attribution would need
        # a tenant label the bus histogram deliberately doesn't carry).
        _attach_bus_delta(reports[key], bus_before)
    return reports


def run_concurrency_sweep(
    execute,
    query: str,
    concurrencies=(1, 2, 4),
    per_worker: int = 10,
    timeout_s: float = 30.0,
    warmup: int = 1,
    **tenancy_kw,
) -> dict:
    """The ``--concurrency`` axis: the same per-worker offered load at
    each client-thread count N, sequentially, against one engine/broker.
    Returns {N: LoadReport}. ``warmup`` queries run first (uncounted) so
    sweep point 1 doesn't pay the XLA compile that later points then
    amortize — the N=1 row is the serial baseline the scaling rows are
    read against."""
    for _ in range(max(0, int(warmup))):
        try:
            execute(query, timeout_s)
        except Exception:
            break  # the measured runs will report the failure mode
    out = {}
    for n in concurrencies:
        out[int(n)] = run_load(
            execute, query, workers=int(n), per_worker=per_worker,
            timeout_s=timeout_s, **tenancy_kw,
        )
    return out


def run_repeat_load(
    execute,
    query: str,
    qps: float = 10.0,
    count: int = 50,
    timeout_s: float = 30.0,
    status_fn=None,
    **tenancy_kw,
) -> LoadReport:
    """The ``--repeat-script`` axis: ONE client firing the SAME script
    ``count`` times at a fixed ``qps`` — the dashboard-refresh shape the
    result cache exists for. Each reply's cache disposition (broker
    reply ``cache`` key, or ``status_fn(res)`` for executors that don't
    carry one) is tallied into ``report.cache_counts``; latencies and
    the serving-histogram delta are recorded exactly like ``run_load``,
    so two runs of this under cache-on/cache-off flags are directly
    comparable (``run_repeat_ab``)."""
    report = LoadReport()
    kw = {k: v for k, v in tenancy_kw.items() if v is not None}
    interval = 1.0 / qps if qps > 0 else 0.0
    hist_before = _hist_snapshot()
    t_start = time.perf_counter()
    next_t = t_start
    for _ in range(max(1, int(count))):
        # Fixed-rate pacing on the SCHEDULE, not the completion: a slow
        # query eats into the next slot instead of silently lowering
        # the offered rate (open-loop load, the dashboard's behavior).
        if interval:
            now = time.perf_counter()
            if now < next_t:
                time.sleep(next_t - now)
            next_t += interval
        t0 = time.perf_counter()
        err = None
        status = ""
        fresh_ms = 0.0
        partial = False
        try:
            res = execute(query, timeout_s, **kw)
            partial = bool(isinstance(res, dict) and res.get("partial"))
            if status_fn is not None:
                status = status_fn(res) or ""
            elif isinstance(res, dict):
                status = res.get("cache", "") or ""
            v = res.get("freshness_lag_ms") if isinstance(res, dict) else None
            if v is None:
                v = getattr(res, "freshness_lag_ms", None)
            fresh_ms = float(v or 0.0)
        except Exception as e:
            err = type(e).__name__
        dt = time.perf_counter() - t0
        report.queries += 1
        if err is None:
            report.latencies_s.append(dt)
            report.max_freshness_lag_ms = max(
                report.max_freshness_lag_ms, fresh_ms
            )
            report.cache_counts[status] = (
                report.cache_counts.get(status, 0) + 1
            )
            if partial:
                report.partials += 1
        else:
            report.errors += 1
            report.errors_by_type[err] = (
                report.errors_by_type.get(err, 0) + 1
            )
    report.wall_s = time.perf_counter() - t_start
    _attach_hist_delta(report, hist_before, _hist_snapshot())
    return report


def run_repeat_ab(
    execute,
    query: str,
    qps: float = 10.0,
    count: int = 50,
    timeout_s: float = 30.0,
    cache_mb: int = 64,
    status_fn=None,
    **tenancy_kw,
) -> dict:
    """Cache-off vs cache-on A/B of the same repeated script against
    one IN-PROCESS engine/broker (the flag overrides only reach this
    process — a remote broker keeps its own configuration; use a plain
    ``run_repeat_load`` there and read the hit rate). Returns
    ``{"cache_off": LoadReport, "cache_on": LoadReport}`` — each phase
    carries its own serving-histogram delta, so cache_on's p50/p99
    against cache_off's IS the repeat-serving speedup, measured where
    the queries were served."""
    from ..config import override_flag

    with override_flag("result_cache_mb", 0), \
            override_flag("view_auto_min_runs", 0):
        off = run_repeat_load(
            execute, query, qps=qps, count=count, timeout_s=timeout_s,
            status_fn=status_fn, **tenancy_kw,
        )
    with override_flag("result_cache_mb", int(cache_mb)):
        on = run_repeat_load(
            execute, query, qps=qps, count=count, timeout_s=timeout_s,
            status_fn=status_fn, **tenancy_kw,
        )
    return {"cache_off": off, "cache_on": on}


def broker_executor(broker):
    """Adapter for an in-process QueryBroker."""

    def execute(query, timeout_s, **kw):
        kw = {k: v for k, v in kw.items() if v is not None}
        return broker.execute_script(query, timeout_s=timeout_s, **kw)

    return execute


def remote_executor(host: str, port: int):
    """Adapter for a served broker over the netbus (one shared conn)."""
    from .netbus import RemoteBus

    bus = RemoteBus(host, port)

    def execute(query, timeout_s, **kw):
        req = {"query": query, "timeout_s": timeout_s}
        req.update((k, v) for k, v in kw.items() if v is not None)
        res = bus.request(
            "broker.execute", req, timeout_s=timeout_s + 5,
        )
        if not res.get("ok"):
            raise RuntimeError(res.get("error", "unknown broker error"))
        return res

    execute.close = bus.close  # type: ignore[attr-defined]
    return execute


# -- CLI ----------------------------------------------------------------------

_LOCAL_QUERY = (
    "import px\n"
    "df = px.DataFrame(table='http_events')\n"
    "df = df.groupby('service').agg(\n"
    "    n=('latency_ns', px.count), mean=('latency_ns', px.mean))\n"
    "px.display(df, 'out')\n"
)


def local_executor(rows: int = 200_000, window_rows: int = 1 << 15,
                   seed: int = 7):
    """In-process single-engine executor over a seeded synthetic table
    (the ``--local`` mode: measures the ENGINE's concurrency, no
    broker/bus in the path)."""
    import numpy as np

    from ..exec.engine import Engine

    rng = np.random.default_rng(seed)
    engine = Engine(window_rows=window_rows)
    engine.append_data("http_events", {
        "time_": np.arange(rows, dtype=np.int64),
        "latency_ns": rng.integers(1_000, 1_000_000, rows),
        "service": [f"svc-{i % 8}" for i in range(rows)],
    })

    def execute(query, timeout_s, **kw):
        return engine.execute_query(query)

    execute.engine = engine  # type: ignore[attr-defined]
    return execute


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m pixie_tpu.services.load_tester",
        description=(
            "Concurrency load sweep: N client threads against one "
            "engine/broker, qps + p50/p95/p99 per N (client-side and "
            "serving-histogram deltas)."
        ),
    )
    ap.add_argument("--broker", metavar="HOST:PORT",
                    help="remote broker over the netbus")
    ap.add_argument("--local", action="store_true",
                    help="in-process engine over a synthetic table")
    ap.add_argument("--script", help=".pxl file (default: a groupby "
                                     "over the local synthetic table)")
    ap.add_argument("--concurrency", default="1,2,4",
                    help="comma-separated client-thread counts")
    ap.add_argument("--repeat-script", action="store_true",
                    help="repeat mode: fire --script (or the local "
                         "default) at a fixed --qps from one client and "
                         "report the cache hit rate plus a cache-on/off "
                         "p50/p99 A/B (in-process modes only)")
    ap.add_argument("--qps", type=float, default=10.0,
                    help="repeat-mode offered rate")
    ap.add_argument("--count", type=int, default=50,
                    help="repeat-mode queries per phase")
    ap.add_argument("--cache-mb", type=int, default=64,
                    help="repeat-mode cache budget for the ON phase")
    ap.add_argument("--per-worker", type=int, default=10)
    ap.add_argument("--timeout-s", type=float, default=30.0)
    ap.add_argument("--rows", type=int, default=200_000,
                    help="--local synthetic table size")
    ap.add_argument("--tenant", default=None)
    ap.add_argument("--priority", type=int, default=0)
    ap.add_argument("--deadline-ms", type=float, default=None)
    args = ap.parse_args(argv)

    if bool(args.broker) == bool(args.local):
        ap.error("exactly one of --broker or --local is required")
    if args.local and (
        args.tenant is not None or args.priority
        or args.deadline_ms is not None
    ):
        # The local executor calls the engine directly — no broker, no
        # admission path. Silently dropping these would print
        # tenancy-shaped numbers that never exercised tenancy.
        ap.error("--tenant/--priority/--deadline-ms require --broker "
                 "(the local engine has no admission path)")
    if args.script:
        with open(args.script) as f:
            query = f.read()
    else:
        if not args.local:
            ap.error("--script is required with --broker")
        query = _LOCAL_QUERY
    try:
        concurrencies = [
            int(c) for c in str(args.concurrency).split(",") if c.strip()
        ]
    except ValueError:
        ap.error(f"bad --concurrency {args.concurrency!r}")
    if args.local:
        execute = local_executor(rows=args.rows)
    else:
        host, _, port = args.broker.rpartition(":")
        execute = remote_executor(host or "127.0.0.1", int(port))
    try:
        if args.repeat_script:
            status_fn = None
            if args.local:
                # The engine returns bare result tables; the trace
                # carries the disposition of the query just served.
                eng = execute.engine  # type: ignore[attr-defined]
                status_fn = lambda res: (  # noqa: E731
                    getattr(eng.tracer.last(), "cache", "")
                )
            if args.broker:
                # Flag overrides don't cross the bus: measure the
                # remote broker AS CONFIGURED, hit rate included.
                rep = run_repeat_load(
                    execute, query, qps=args.qps, count=args.count,
                    timeout_s=args.timeout_s, tenant=args.tenant,
                    priority=args.priority or None,
                    deadline_ms=args.deadline_ms,
                )
                print(json.dumps({"configured": rep.to_dict()}, indent=2))
                return 0 if rep.errors == 0 else 1
            ab = run_repeat_ab(
                execute, query, qps=args.qps, count=args.count,
                timeout_s=args.timeout_s, cache_mb=args.cache_mb,
                status_fn=status_fn,
            )
            out = {k: r.to_dict() for k, r in ab.items()}
            off_p50 = ab["cache_off"].percentile(50)
            on_p50 = ab["cache_on"].percentile(50)
            if on_p50 and off_p50 == off_p50 and on_p50 == on_p50:
                out["p50_speedup"] = round(off_p50 / on_p50, 2)
            print(json.dumps(out, indent=2))
            return 0 if all(r.errors == 0 for r in ab.values()) else 1
        reports = run_concurrency_sweep(
            execute, query, concurrencies=concurrencies,
            per_worker=args.per_worker, timeout_s=args.timeout_s,
            tenant=args.tenant, priority=args.priority,
            deadline_ms=args.deadline_ms,
        )
        print(json.dumps(
            {str(n): r.to_dict() for n, r in reports.items()}, indent=2
        ))
        return 0 if all(r.errors == 0 for r in reports.values()) else 1
    finally:
        close = getattr(execute, "close", None)
        if close is not None:
            close()


if __name__ == "__main__":
    raise SystemExit(main())
