"""Deterministic fault injection for the message bus and netbus.

Reference parity: the reference exercises failure paths with embedded
fake NATS connections and dropped gRPC streams in its broker tests
(``query_result_forwarder_test.go``, ``agent_topic_listener_test.go``);
chaos tooling in distributed query serving (and the Taurus-style
best-effort scatter-gather literature, PAPERS.md) treats *reproducible*
component failure as a first-class test input. This module is that
input: a rule table keyed by topic pattern, driven by one seeded RNG,
attached to a ``MessageBus`` (or ``RemoteBus``) via its
``fault_injector`` attribute.

Faults:

- ``drop(pattern)``        the message is never delivered
- ``delay(pattern, s)``    delivery is deferred ``s`` seconds
- ``duplicate(pattern)``   every planned delivery happens twice
- ``on_match(pattern, fn)``  trigger hook: run ``fn(topic, msg)`` when
  a matching message is published (BEFORE delivery) — the kill-an-agent
  / sever-a-connection trigger point
- ``kill_agent(pattern, agent, tracker)``  convenience trigger: stop
  the agent and force-expire it from the tracker
- ``sever(pattern, remote_bus)``  convenience trigger: hard-cut a
  netbus connection (mid-flight partition)
- ``partition(pattern_a, pattern_b)`` / ``heal()``  bidirectional drop
  of traffic crossing two peer sets (agent-id patterns; ``"broker"``
  names the control-plane side)

All rules support ``prob`` (applied via the seeded RNG), ``count``
(max applications), ``after`` (skip the first N matches) and ``where``
(a message predicate). A given (seed, workload) replays identically —
the property ``tests/test_fault_injection.py`` and the
``run_tests.sh --faults`` seed matrix rely on.
"""

from __future__ import annotations

import fnmatch
import random
import threading
from typing import Callable


def _peer_of_topic(topic: str) -> str:
    """Destination peer of a topic: ``agent.{id}.{kind}`` names the
    agent; everything else (registration, query.*, leases, inboxes)
    terminates at the control plane — ``"broker"``."""
    parts = topic.split(".")
    if parts[0] == "agent" and len(parts) >= 3:
        return parts[1]
    return "broker"


def _peer_of_msg(msg: dict) -> str:
    """Origin peer of a message, from its agent-id fields; messages
    carrying none (dispatches, probes, client requests) originate at
    the control plane — ``"broker"``."""
    for k in ("from_agent", "agent", "agent_id"):
        v = msg.get(k)
        if v:
            return str(v)
    return "broker"


class _Rule:
    __slots__ = (
        "pattern", "action", "prob", "count", "delay_s", "after",
        "fn", "where", "matched", "fired",
    )

    def __init__(
        self,
        pattern: str,
        action: str,
        *,
        prob: float = 1.0,
        count: int | None = None,
        delay_s: float = 0.0,
        after: int = 0,
        fn: Callable | None = None,
        where: Callable | None = None,
    ):
        self.pattern = pattern
        self.action = action  # "drop"|"delay"|"duplicate"|"call"|"partition"
        self.prob = prob
        self.count = count  # max applications; None = unlimited
        self.delay_s = delay_s
        self.after = after  # skip the first `after` matching messages
        self.fn = fn
        self.where = where
        self.matched = 0  # messages matching pattern+where
        self.fired = 0  # times the action actually applied


class FaultInjector:
    """Seeded, rule-based fault hook for ``MessageBus``/``RemoteBus``.

    Attach with ``bus.fault_injector = injector``; the bus calls
    ``intercept(topic, msg)`` on every publish and follows the returned
    delivery plan (a list of per-copy delays in seconds; empty list =
    dropped). ``log`` records every applied fault as ``(action, topic)``
    for test assertions.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self._rules: list[_Rule] = []
        self._partition_rules: list[_Rule] = []
        self._lock = threading.Lock()
        self.log: list[tuple[str, str]] = []

    # -- rule construction ---------------------------------------------------
    def _add(self, rule: _Rule) -> "FaultInjector":
        with self._lock:
            self._rules.append(rule)
        return self

    def drop(self, pattern: str, *, prob: float = 1.0,
             count: int | None = None, after: int = 0,
             where: Callable | None = None) -> "FaultInjector":
        return self._add(_Rule(pattern, "drop", prob=prob, count=count,
                               after=after, where=where))

    def delay(self, pattern: str, delay_s: float, *, prob: float = 1.0,
              count: int | None = None, after: int = 0,
              where: Callable | None = None) -> "FaultInjector":
        return self._add(_Rule(pattern, "delay", prob=prob, count=count,
                               delay_s=delay_s, after=after, where=where))

    def duplicate(self, pattern: str, *, prob: float = 1.0,
                  count: int | None = None, after: int = 0,
                  where: Callable | None = None) -> "FaultInjector":
        return self._add(_Rule(pattern, "duplicate", prob=prob, count=count,
                               after=after, where=where))

    def on_match(self, pattern: str, fn: Callable, *, count: int | None = 1,
                 after: int = 0,
                 where: Callable | None = None) -> "FaultInjector":
        """Run ``fn(topic, msg)`` when a matching message is published.
        Fires BEFORE delivery (and outside the injector lock, so ``fn``
        may itself publish, stop agents, or expire trackers)."""
        return self._add(_Rule(pattern, "call", fn=fn, count=count,
                               after=after, where=where))

    def kill_agent(self, pattern: str, agent, tracker=None, *,
                   after: int = 0,
                   where: Callable | None = None) -> "FaultInjector":
        """Kill ``agent`` when a matching message is published: stop it
        (no more heartbeats or handlers) and — with a ``tracker`` —
        force-expire it immediately so failure detection is
        deterministic rather than waiting out the expiry window."""

        def _kill(_topic, _msg):
            agent.stop()
            if tracker is not None:
                tracker.force_expire(
                    agent.agent_id, reason="fault-injected kill"
                )

        return self.on_match(pattern, _kill, after=after, where=where)

    def sever(self, pattern: str, remote_bus, *, after: int = 0,
              where: Callable | None = None) -> "FaultInjector":
        """Hard-cut a netbus connection when a matching message is
        published (``RemoteBus.sever``) — a mid-flight partition."""
        return self.on_match(
            pattern, lambda _t, _m: remote_bus.sever(), after=after,
            where=where,
        )

    def partition(self, pattern_a: str, pattern_b: str, *,
                  prob: float = 1.0,
                  count: int | None = None) -> "FaultInjector":
        """Bidirectional sever of two peer sets until :meth:`heal`.

        Peers are named by fnmatch patterns over agent ids; the id
        ``"broker"`` stands for the control-plane side (tracker, broker,
        forwarder — any participant that is not an ``agent.{id}.*``
        endpoint). A message is dropped when its origin peer matches one
        side and its destination peer matches the other, in EITHER
        direction; intra-set traffic flows. Granularity is the bus's:
        origin comes from the message's agent-id fields
        (``from_agent``/``agent``/``agent_id``), destination from an
        ``agent.{id}.*`` topic — fan-out topics without a single
        destination (``query.cancel``, leases) count as broker-side.
        """

        def _crosses(topic: str, msg: dict) -> bool:
            src = _peer_of_msg(msg)
            dst = _peer_of_topic(topic)
            a_src = fnmatch.fnmatchcase(src, pattern_a)
            b_src = fnmatch.fnmatchcase(src, pattern_b)
            a_dst = fnmatch.fnmatchcase(dst, pattern_a)
            b_dst = fnmatch.fnmatchcase(dst, pattern_b)
            return (a_src and b_dst) or (b_src and a_dst)

        rule = _Rule("*", "partition", prob=prob, count=count, fn=_crosses)
        with self._lock:
            self._rules.append(rule)
            self._partition_rules.append(rule)
        return self

    def heal(self) -> int:
        """Remove every :meth:`partition` rule (both directions of every
        cut); all other rules stay. Returns how many cuts were healed."""
        with self._lock:
            for r in self._partition_rules:
                try:
                    self._rules.remove(r)
                except ValueError:
                    pass
            healed = len(self._partition_rules)
            self._partition_rules = []
        return healed

    # -- the bus hook --------------------------------------------------------
    def intercept(self, topic: str, msg: dict) -> list:
        """Delivery plan for one publish: a list of per-copy delays in
        seconds ([0.0] = deliver now, [] = dropped). Rules apply in
        registration order to the running plan; trigger hooks fire after
        the plan is decided, outside the lock."""
        plan = [0.0]
        triggers = []
        with self._lock:
            for r in self._rules:
                if not fnmatch.fnmatchcase(topic, r.pattern):
                    continue
                if r.where is not None and not r.where(msg):
                    continue
                if r.action == "partition" and not r.fn(topic, msg):
                    continue  # not a cut-crossing message
                r.matched += 1
                if r.matched <= r.after:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                if r.prob < 1.0 and self.rng.random() >= r.prob:
                    continue
                r.fired += 1
                self.log.append((r.action, topic))
                if r.action in ("drop", "partition"):
                    plan = []
                elif r.action == "delay":
                    plan = [d + r.delay_s for d in plan]
                elif r.action == "duplicate":
                    plan = plan * 2
                elif r.action == "call":
                    triggers.append(r.fn)
        for fn in triggers:
            fn(topic, msg)
        return plan

    def fired(self, action: str | None = None) -> int:
        """How many faults applied (optionally filtered by action)."""
        with self._lock:
            return sum(
                1 for a, _t in self.log if action is None or a == action
            )
