"""Tenant model for broker overload protection.

Reference parity: the source system isolates tenants at the control
plane — Vizier's query broker serves many independent dashboard users
through one admission point, and a noisy tenant's burst must queue
behind *its own* backlog, not everyone's. This module is the identity
half of that contract: a **registered tenant set** with per-tenant
weights (``admission_tenant_weights`` flag), a resolver that folds any
unregistered tenant string into the shared default tenant, and the
budget-share arithmetic weighted-fair admission (``_Admission`` in
``services/query_broker.py``) schedules on.

Why a registered set: tenant names label Prometheus series
(``pixie_admission_{queued,shed,rejected}_total{tenant=...}``) and
telemetry-table columns. Labeling with raw client-supplied strings
would make series cardinality unbounded — a self-inflicted overload of
the observability plane while defending the query plane. The runtime
guard is :func:`resolve_tenant` (unknown -> ``shared``, counted once
in the unlabeled ``pixie_admission_unknown_tenant_total``); the static
guard is the ``metrics-naming`` pxlint rule, which rejects
``.labels(tenant=...)`` call sites whose value does not come from a
resolver-derived binding (docs/ANALYSIS.md).
"""

from __future__ import annotations

from ..config import get_flag

#: Every query without an explicit (registered) tenant runs as this
#: tenant — existing callers keep working unchanged, sharing one
#: default slice of the admission budget.
DEFAULT_TENANT = "shared"


#: Memoized parse of the weights spec, keyed on the raw flag string:
#: tenant_weights() runs on hot paths — per metric increment, per
#: served request, and per _schedule_locked pass UNDER the admission
#: lock — and the spec is effectively constant. Benign data race on
#: rebind (worst case: a redundant parse); callers must treat the
#: returned dict as read-only.
_WEIGHTS_MEMO: "tuple[str, dict[str, float]] | None" = None


def tenant_weights() -> dict[str, float]:
    """{tenant: weight} from ``admission_tenant_weights`` ("a:2,b:1").

    The default tenant is always present (weight 1.0 unless listed
    explicitly), so unregistered traffic always has a slice. A missing
    or malformed weight parses as 1.0; negative weights clamp to 0
    (a tenant an operator wants OFF still stays a registered name, so
    its traffic is identifiable rather than folded into ``shared``).
    Returns a shared memoized dict — do not mutate.
    """
    global _WEIGHTS_MEMO
    spec = str(get_flag("admission_tenant_weights")).strip()
    memo = _WEIGHTS_MEMO
    if memo is not None and memo[0] == spec:
        return memo[1]
    out: dict[str, float] = {}
    if spec:
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, _, w = entry.partition(":")
            name = name.strip()
            if not name:
                continue
            try:
                weight = float(w) if w.strip() else 1.0
            except ValueError:
                weight = 1.0
            out[name] = max(weight, 0.0)
    out.setdefault(DEFAULT_TENANT, 1.0)
    _WEIGHTS_MEMO = (spec, out)
    return out


def resolve_tenant(name, count_unknown: bool = True) -> str:
    """Fold ``name`` into the registered tenant set.

    Registered names pass through; empty/None/unregistered names
    resolve to :data:`DEFAULT_TENANT`. This is the bounded-cardinality
    guard: every tenant string that reaches a metric label or a
    telemetry column went through here first. ``count_unknown=False``
    skips the unknown-tenant counter — for resolution points UPSTREAM
    of the one that owns the count (the served front door resolves for
    worker accounting before execute_script resolves the same request
    for admission; counting both would double every served unknown).
    """
    if not name:
        return DEFAULT_TENANT
    name = str(name)
    if name in tenant_weights():
        return name
    if count_unknown:
        from .observability import default_counter

        default_counter(
            "pixie_admission_unknown_tenant_total",
            "Queries whose tenant was not in the registered set "
            "(admission_tenant_weights) and ran as the shared tenant",
        ).inc()
    return DEFAULT_TENANT


def tenant_shares(budget: float) -> dict[str, float]:
    """{tenant: byte share} — ``budget`` split by registered weight.

    Shares partition the budget (they sum to it), so per-tenant
    accounting alone bounds the global in-flight sum: an over-share
    tenant queues behind its own backlog while an under-share tenant's
    admission decision never even reads the other tenants' state.
    """
    weights = tenant_weights()
    total = sum(weights.values()) or 1.0
    return {t: budget * w / total for t, w in weights.items()}
