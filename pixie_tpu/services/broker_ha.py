"""Broker high availability: leader leases, a replicated control-plane
state log, and failover of in-flight queries.

The reference deployment runs one query broker per cluster — a single
point of failure for the whole serving path. This module runs N
:class:`BrokerReplica` peers on one bus:

- **Leases, not consensus.** The leader publishes ``broker.lease``
  heartbeats carrying a monotonically-increasing **epoch**
  (``broker_lease_interval_s`` cadence). Standbys watch; when the lease
  goes silent past ``broker_lease_expiry_s`` the lowest-id live standby
  claims ``max(seen epochs) + 1`` and publishes its own lease
  immediately. The bus is the arbiter: a split claim resolves on the
  next lease exchange (higher epoch wins; equal epochs tie-break on
  broker id), and every dispatch is stamped with the leader's epoch so
  agents FENCE a deposed leader's backlog (``ExecutionAgent._epoch_ok``)
  — two half-leaders can race leases, but only one epoch's work runs.

- **Replicated control-plane state.** The leader streams a compact
  ``broker.state`` log — in-flight query records (admission
  grants/releases), observed-cost updates, agent lifecycle events,
  result-cache invalidations — and each standby folds it into a
  mirror. This is the arXiv:2506.20010 shape (control-plane log
  replicated separately from the compute it describes): the log carries
  broker *decisions*, never table data.

- **Failover of in-flight queries.** On takeover the new leader
  replays its mirror: re-registers a forwarder for every mirrored
  in-flight query (closing the event-loss window first), probes the
  fleet with ``broker.reconcile`` to learn which fragments still run,
  then resolves each query — still-running ones complete normally
  through the re-attached forwarder, unrecoverable ones resolve as
  ``partial`` with ``missing_reasons: "broker_failover"``. Every
  mirrored query answers its caller's inbox; nothing hangs.

Clients never address a broker directly: ``broker.execute`` (and every
served topic) is subscribed only by the current leader, and
``broker.leader`` is answered by every replica, so `api.Client` /
`px` fail over by re-resolving. See docs/RESILIENCE.md "Broker HA".
"""

from __future__ import annotations

import threading
import time
import uuid

from .msgbus import MessageBus
from .observability import default_counter
from .query_broker import QueryBroker
from .tracker import AgentTracker

TOPIC_LEASE = "broker.lease"          # leader heartbeats + standby presence
TOPIC_STATE = "broker.state"          # leader -> standbys control-plane log
TOPIC_LEADER = "broker.leader"        # request/reply: who leads?
TOPIC_RECONCILE = "broker.reconcile"  # takeover probe -> agents answer


class _Mirror:
    """A standby's fold of the leader's ``broker.state`` log. Plain
    dicts guarded by the replica's lock — the mirror is only ever read
    whole at takeover."""

    def __init__(self):
        self.inflight: dict[str, dict] = {}   # qid -> inflight record
        self.costs: dict[str, dict] = {}      # script_hash -> cost entry
        self.agent_events = 0
        self.cache_invalidations = 0


class BrokerReplica:
    """One broker peer: an :class:`AgentTracker` + :class:`QueryBroker`
    pair wrapped in lease-based leader election. Exactly one replica
    serves the ``broker.*`` API at a time; the rest mirror its state
    log and race to take over when its lease lapses."""

    def __init__(
        self,
        bus: MessageBus,
        broker_id: str,
        registry=None,
        secret: str | None = None,
        lease_interval_s: float | None = None,
        lease_expiry_s: float | None = None,
        tracker_kw: dict | None = None,
        leader: bool = False,
    ):
        from ..config import get_flag

        self.bus = bus
        self.broker_id = broker_id
        self.lease_interval_s = (
            float(get_flag("broker_lease_interval_s"))
            if lease_interval_s is None else float(lease_interval_s)
        )
        self.lease_expiry_s = (
            float(get_flag("broker_lease_expiry_s"))
            if lease_expiry_s is None else float(lease_expiry_s)
        )
        self.reconcile_wait_s = float(get_flag("broker_reconcile_wait_s"))
        self.reattach_timeout_s = float(get_flag("broker_reattach_timeout_s"))

        # Standby trackers observe heartbeats but publish NOTHING — two
        # active trackers would double-ack registrations and race
        # expiry/quarantine decisions.
        self.tracker = AgentTracker(
            bus, passive=not leader, **dict(tracker_kw or {})
        )
        self.broker = QueryBroker(bus, self.tracker, registry=registry,
                                  secret=secret)
        self.broker.broker_id = broker_id
        self.broker.epoch_fn = lambda: self.epoch

        self._lock = threading.Lock()
        self.role = "leader" if leader else "standby"
        self.epoch = 1 if leader else 0
        self._state_seq = 0        # leader: last published state-log seq
        self._applied_seq = 0      # standby: last folded state-log seq
        self._leader_state_seq = 0  # standby: leader's seq per its lease
        self.mirror = _Mirror()
        self._known_leader = broker_id if leader else ""
        self._last_lease_t = time.monotonic()  # grace from construction
        self._last_lease: dict = {}
        self._peers: dict[str, float] = {}     # standby id -> last seen
        self._wired = False        # cost-trace listener added once
        self._dead = False
        self._stop = threading.Event()
        self.failovers = 0

        self._subs = [
            bus.subscribe(TOPIC_LEASE, self._on_lease),
            bus.subscribe(TOPIC_STATE, self._on_state),
            bus.subscribe(TOPIC_LEADER, self._on_leader),
        ]
        if leader:
            self._wire_leader()
            self.broker.serve()
            self._publish_lease()
        self._watch = threading.Thread(
            target=self._watch_loop, daemon=True,
            name=f"broker-ha-{broker_id}",
        )
        self._watch.start()

    # -- lease protocol ------------------------------------------------------
    def _publish_lease(self) -> None:
        with self._lock:
            if self._dead:
                return
            payload = {
                "broker": self.broker_id,
                "role": self.role,
                "epoch": self.epoch,
                "state_seq": self._state_seq,
            }
            is_leader = self.role == "leader"
            if is_leader:
                # Our own lease doubles as the freshness record so a
                # just-deposed leader measures staleness the same way.
                self._last_lease = dict(payload)
                self._last_lease_t = time.monotonic()
        self.bus.publish(TOPIC_LEASE, payload)

    def _on_lease(self, msg: dict) -> None:
        if self._dead:
            return
        b = str(msg.get("broker", ""))
        ep = int(msg.get("epoch", 0) or 0)
        if msg.get("role") == "standby":
            if b and b != self.broker_id:
                with self._lock:
                    self._peers[b] = time.monotonic()
            return
        if b == self.broker_id:
            return
        step_down = False
        with self._lock:
            if ep < self.epoch:
                return  # deposed leader's stale lease: ignore
            self._last_lease = dict(msg)
            self._last_lease_t = time.monotonic()
            self._leader_state_seq = int(msg.get("state_seq", 0) or 0)
            self._known_leader = b
            if self.role == "leader" and (
                ep > self.epoch or (ep == self.epoch and b < self.broker_id)
            ):
                # A peer leads at a higher epoch (or won the equal-epoch
                # tie-break): yield. Our queued dispatches carry the old
                # epoch and die at the agents' fence.
                step_down = True
            self.epoch = max(self.epoch, ep)
        if step_down:
            self._step_down()

    def _step_down(self) -> None:
        with self._lock:
            self.role = "standby"
            self._known_leader = ""
        self.broker.stop_serving()
        self.broker.state_log = None
        default_counter(
            "pixie_broker_stepdowns_total",
            "Leaders that yielded to a higher-epoch peer",
        ).inc()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.lease_interval_s):
            if self._dead:
                return
            if self.role == "leader":
                self._publish_lease()
                continue
            # Standby: advertise presence (rank input for peers), then
            # check the leader's lease.
            self.bus.publish(TOPIC_LEASE, {
                "broker": self.broker_id, "role": "standby",
                "epoch": self.epoch,
            })
            now = time.monotonic()
            with self._lock:
                age = now - self._last_lease_t
                live = sorted(
                    [self.broker_id]
                    + [p for p, t in self._peers.items()
                       if now - t < self.lease_expiry_s]
                )
                rank = live.index(self.broker_id)
            # Ranked claim windows stagger the standbys: the lowest id
            # claims first; a higher-ranked one only moves if the
            # preferred claimant is ALSO gone for its whole window.
            if age > self.lease_expiry_s + rank * self.lease_interval_s:
                self._claim()

    def _claim(self) -> None:
        with self._lock:
            if self._dead or self.role == "leader":
                return
            seen = int(self._last_lease.get("epoch", 0) or 0)
            self.epoch = max(self.epoch, seen) + 1
            self.role = "leader"
            # Continue the state log where the mirror left off so other
            # standbys' replay-lag stays monotone across successions.
            self._state_seq = max(self._state_seq, self._applied_seq)
            self._known_leader = self.broker_id
            self.failovers += 1
        default_counter(
            "pixie_broker_failovers_total",
            "Lease-expiry takeovers by a standby broker",
        ).inc()
        self._publish_lease()  # fence the deposed leader's epoch NOW
        self._takeover()

    # -- state log -----------------------------------------------------------
    def _wire_leader(self) -> None:
        self.broker.state_log = self._publish_state
        if not self._wired:
            self._wired = True
            self.broker.tracer.add_listener(self._on_cost_trace)

    def _publish_state(self, event: str, data: dict) -> None:
        with self._lock:
            if self._dead or self.role != "leader":
                return
            self._state_seq += 1
            payload = {
                "broker": self.broker_id,
                "epoch": self.epoch,
                "seq": self._state_seq,
                "event": event,
                "data": data,
            }
        self.bus.publish(TOPIC_STATE, payload)

    def _on_cost_trace(self, trace) -> None:
        """Tracer listener: replicate the observed-cost history the
        admission floor calibrates on (arXiv:2102.02440 feedback loop)
        so a successor doesn't re-learn it from zero."""
        if self._dead or self.role != "leader":
            return
        if getattr(trace, "kind", "") != "distributed":
            return
        if trace.status not in ("ok", "partial"):
            return
        u = trace.usage
        self._publish_state("cost", {
            "script_hash": trace.script_hash,
            "bytes_staged": int(u.bytes_staged),
            "rows_in": int(u.rows_in),
        })

    def _on_state(self, msg: dict) -> None:
        if self._dead:
            return
        with self._lock:
            if self.role == "leader":
                return
            event = msg.get("event", "")
            data = msg.get("data") or {}
            if event == "inflight":
                qid = data.get("qid", "")
                if qid:
                    self.mirror.inflight[qid] = dict(data)
            elif event == "release":
                self.mirror.inflight.pop(data.get("qid", ""), None)
            elif event == "cost":
                h = data.get("script_hash", "")
                ent = self.mirror.costs.setdefault(
                    h, {"bytes_staged": 0, "rows_in": 0, "runs": 0}
                )
                ent["bytes_staged"] = max(
                    ent["bytes_staged"], int(data.get("bytes_staged", 0))
                )
                ent["rows_in"] = max(
                    ent["rows_in"], int(data.get("rows_in", 0))
                )
                ent["runs"] += 1
            elif event == "agent":
                self.mirror.agent_events += 1
            elif event == "cache_invalidate":
                self.mirror.cache_invalidations += 1
            self._applied_seq = int(msg.get("seq", 0) or 0)

    # -- leader discovery ----------------------------------------------------
    def _on_leader(self, msg: dict) -> None:
        if self._dead:
            return
        inbox = msg.get("_reply_to")
        if not inbox:
            return
        with self._lock:
            leader = (
                self.broker_id if self.role == "leader"
                else self._known_leader
            )
            payload = {
                "ok": bool(leader),
                "broker": leader,
                "epoch": self.epoch,
                "role": self.role,
                "answered_by": self.broker_id,
            }
        if not payload["ok"]:
            return  # mid-failover: stay silent, the claimant answers
        self.bus.publish(inbox, payload)

    # -- takeover ------------------------------------------------------------
    def _takeover(self) -> None:
        with self._lock:
            inflight = dict(self.mirror.inflight)
            costs = dict(self.mirror.costs)
        self.tracker.activate()
        self.broker.observed_costs.seed(costs)
        self._wire_leader()
        self.broker.serve()
        if inflight:
            self._reconcile(inflight)

    def _reconcile(self, inflight: dict) -> None:
        """Resolve every mirrored in-flight query: re-attach a fresh
        forwarder (FIRST — closes the event-loss window), probe the
        fleet for still-running fragments, then complete the live ones
        normally and interrupt the dead ones into
        partial/``broker_failover``. Every record answers its caller."""
        fw = self.broker.forwarder
        waiters: dict[str, threading.Thread] = {}
        for qid, info in inflight.items():
            expected = [str(a) for a in (info.get("expected") or [])]
            fw.register_query(
                qid, expected,
                merge_agent=str(info.get("merge_agent") or ""),
                require_complete=False,
            )
            t = threading.Thread(
                target=self._finish_failover, args=(qid, dict(info)),
                daemon=True, name=f"broker-failover-{qid[:8]}",
            )
            waiters[qid] = t
            t.start()

        # Probe: agents answer with their running fragment set + the
        # unmet merge expectations. The probe carries the NEW epoch, so
        # it also fences agents that never saw our first lease.
        answers: list[dict] = []
        inbox = f"broker.reconcile.{uuid.uuid4().hex[:12]}"
        sub = self.bus.subscribe(inbox, answers.append)
        with self._lock:
            epoch = self.epoch
        self.bus.publish(TOPIC_RECONCILE, {
            "_reply_to": inbox, "epoch": epoch,
        })
        # Collect for the reconcile window, refreshing the lease so a
        # slow probe never reads as a second leader death.
        deadline = time.monotonic() + self.reconcile_wait_s
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            time.sleep(min(left, self.lease_interval_s))
            self._publish_lease()
        sub.unsubscribe()

        running: set[str] = set()          # qids some agent still runs
        for a in answers:
            running.update(str(q) for q in (a.get("running") or []))
            running.update(str(q) for q in (a.get("streaming") or []))
            running.update(str(q) for q in (a.get("pending_merges") or {}))
        for qid in inflight:
            if qid not in running:
                # Nobody owns a fragment: the work died with the old
                # leader (or finished before we re-attached). Interrupt
                # resolves the wait as partial/broker_failover instead
                # of letting it ride the inactivity watchdog.
                fw.interrupt(qid, "broker_failover")
        default_counter(
            "pixie_broker_reconciled_queries_total",
            "In-flight queries resolved by a takeover reconcile",
        ).inc(len(inflight))

    def _finish_failover(self, qid: str, info: dict) -> None:
        """Complete one adopted query and answer its caller's inbox in
        the exact served-reply shape (`_run_execute`).

        The re-attach window is a hard DEADLINE, not an inactivity
        watchdog: fragment results published into the takeover gap
        (after the old leader died, before this forwarder re-
        subscribed) are gone from the bus, so an adopted query can
        have a claimed owner — e.g. a merge agent holding unmet bridge
        expectations — yet never produce another report. When the
        window lapses, whatever DID re-report returns as a structured
        ``partial``/``broker_failover`` reply; an error here would read
        to the caller (and the chaos soak's ledger) as a lost query."""
        fw = self.broker.forwarder
        try:
            res = fw.wait(
                qid, self.reattach_timeout_s,
                deadline=time.monotonic() + self.reattach_timeout_s,
                deadline_reason="broker_failover",
            )
            payload = {
                "ok": True,
                "qid": qid,
                "tables": res.get("tables", {}),
                "agent_stats": res.get("agent_stats", {}),
                "partial": res.get("partial", False),
                "missing_agents": res.get("missing_agents", []),
                "missing_reasons": res.get("missing_reasons", {}),
                "interrupted": res.get("interrupted"),
                "mutations": None,
                "predicted_cost": info.get("predicted"),
                "tenant": info.get("tenant"),
                "freshness_lag_ms": None,
                "cache": "",
                "failover": True,
            }
        except Exception as e:  # errors cross the wire as data
            payload = {
                "ok": False,
                "qid": qid,
                "error": f"{type(e).__name__}: {e}",
                "failover": True,
            }
        reply_to = info.get("reply_to") or ""
        if reply_to:
            self.bus.publish(reply_to, payload)
        with self._lock:
            self.mirror.inflight.pop(qid, None)

    # -- introspection -------------------------------------------------------
    def statusz(self) -> dict:
        """Role, epoch, lease age, and state-log replay lag — merged
        into /debug/statusz by deploy.run_broker."""
        now = time.monotonic()
        with self._lock:
            lag = (
                0 if self.role == "leader"
                else max(0, self._leader_state_seq - self._applied_seq)
            )
            return {
                "broker": self.broker_id,
                "role": self.role,
                "epoch": self.epoch,
                "leader": (
                    self.broker_id if self.role == "leader"
                    else self._known_leader
                ),
                "lease_age_s": round(now - self._last_lease_t, 3),
                "state_seq": self._state_seq,
                "applied_seq": self._applied_seq,
                "replay_lag": lag,
                "mirror_inflight": len(self.mirror.inflight),
                "failovers": self.failovers,
            }

    # -- teardown ------------------------------------------------------------
    def kill(self) -> None:
        """Crash this replica (chaos / failover tests): drop off the
        bus without cancelling the agents' in-flight work, so a
        standby can adopt and complete it. Forwarder waits are
        released via :class:`QueryAbandoned` — their served replies
        are suppressed; the successor answers each caller's inbox."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        self.broker.ha_suppress_errors = True
        fw = self.broker.forwarder
        for qid in fw.active_qids():
            fw.abandon(qid, "broker_failover")
        self.broker.close()
        self.tracker.close()
        if threading.current_thread() is not self._watch:
            self._watch.join(timeout=2 * self.lease_interval_s + 1.0)

    def close(self) -> None:
        """Graceful shutdown: in-flight queries finish and reply
        normally (no abandon); the lease simply stops renewing and a
        standby takes over with an empty reconcile set."""
        with self._lock:
            if self._dead:
                return
            self._dead = True
        self._stop.set()
        for s in self._subs:
            s.unsubscribe()
        self._subs = []
        self.broker.close()
        self.tracker.close()
        if threading.current_thread() is not self._watch:
            self._watch.join(timeout=2 * self.lease_interval_s + 1.0)
