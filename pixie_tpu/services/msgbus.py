"""In-process message bus with NATS pub/sub semantics.

Reference parity: ``src/common/event/nats.h:36-60`` (C++ NATS connector)
and the Go ``msgbus`` wrapper (``src/shared/services/msgbus``) — topics,
fan-out to every subscriber, asynchronous delivery. Each subscription
owns a queue + dispatcher thread so a slow handler never blocks
publishers or sibling subscribers (NATS's per-subscription pending
buffer). Swapping in a real NATS/gRPC transport means reimplementing
this one class against sockets; everything above it is transport-blind.

Transport-tier telemetry (``bus_telemetry`` flag, services/busstats.py):
the bus stamps per-topic-class publish/deliver/byte counters,
publish-to-handler-entry dispatcher-lag and handler service-time
histograms, per-subscription queue-depth high-water marks (the
backpressure signal), handler-error counts, and a slow-handler log —
monotonic clock reads only on the hot path, served via ``busz()`` /
``/debug/busz`` and folded into the ``__bus__`` telemetry ring.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Callable

from ..config import get_flag
from ..exec import tracectx
from .busstats import BusStats, HANDLER_ERROR_RING, topic_class


class Subscription:
    def __init__(self, bus: "MessageBus", topic: str, fn: Callable):
        self.bus = bus
        self.topic = topic
        self.fn = fn
        self._q: queue.Queue = queue.Queue()
        self._alive = True
        # Stamping is decided once at subscribe time (the bus's stats
        # object never changes after construction), so queue items are
        # uniformly raw messages or (msg, enqueue_monotonic) pairs.
        self._cls = topic_class(topic)
        self._hw = 0
        # Named for observability (and the ack-thread regression test):
        # one dispatcher thread per subscription, identifiable by topic.
        self._thread = threading.Thread(
            target=self._run, name=f"bus-sub-{topic}", daemon=True
        )
        self._thread.start()

    def _run(self):
        st = self.bus.stats
        while True:
            item = self._q.get()
            if item is _CLOSE:
                return
            if st is not None:
                msg, enq_t = item
                t0 = time.monotonic()
                lag_s = t0 - enq_t
            else:
                msg = item
            err = False
            try:
                # Distributed-trace propagation: bind the message's
                # context envelope (if any) around the handler so work
                # it triggers — including Engine query traces — parents
                # under the publisher's span (tracectx.py).
                with tracectx.bound(tracectx.extract(msg)):
                    self.fn(msg)
            except Exception as e:  # handler errors must not kill delivery
                err = True
                self.bus._on_handler_error(self.topic, e)
            if st is not None:
                st.on_handled(
                    self._cls, self.topic, lag_s,
                    time.monotonic() - t0, error=err,
                )

    def _deliver(self, msg, nbytes: int = 0):
        if not self._alive:
            return
        st = self.bus.stats
        if st is not None:
            depth = self._q.qsize() + 1
            if depth > self._hw:
                self._hw = depth
            st.on_deliver(self._cls, nbytes, depth)
            self._q.put((msg, time.monotonic()))
        else:
            self._q.put(msg)

    def unsubscribe(self):
        self._alive = False
        self.bus._remove(self)
        self._q.put(_CLOSE)


class _OneShotInbox:
    """Thread-less subscription for request/reply inboxes: delivery
    goes straight into the waiter's queue on the PUBLISHER's thread —
    no dispatcher thread, no close sentinel. Safe because the waiter
    is already blocked on the queue and a reply handler's trace context
    travels inside the message envelope, not the delivery thread.
    Quacks like Subscription where the bus touches it (``.topic``,
    ``._deliver``, ``.unsubscribe``)."""

    __slots__ = ("bus", "topic", "_q", "_alive", "_cls")

    def __init__(self, bus: "MessageBus", topic: str, q: queue.Queue):
        self.bus = bus
        self.topic = topic
        self._q = q
        self._alive = True
        self._cls = topic_class(topic)

    def _deliver(self, msg, nbytes: int = 0):
        if not self._alive:
            return
        st = self.bus.stats
        if st is not None:
            st.on_deliver(self._cls, nbytes, self._q.qsize() + 1)
        self._q.put(msg)

    def unsubscribe(self):
        self._alive = False
        self.bus._remove(self)


_CLOSE = object()


class BusTimeout(TimeoutError):
    """Uniform request/reply timeout across bus transports.

    Both ``MessageBus.request`` and ``netbus.RemoteBus.request`` raise
    THIS (never a bare ``TimeoutError``) so broker/agent retry logic can
    catch one exception type regardless of transport."""


class MessageBus:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, list] = {}
        # Bounded ring of the last HANDLER_ERROR_RING failures (topic,
        # exception, unix_ns) — a long-lived bus under sustained handler
        # failure must not leak; the true cumulative count lives in
        # _handler_errors_total / pixie_bus_handler_errors_total.
        self.handler_errors: deque = deque(maxlen=HANDLER_ERROR_RING)
        self._handler_errors_total = 0
        # Optional faults.FaultInjector consulted on every publish
        # (drop/delay/duplicate + trigger hooks); None = no faults.
        self.fault_injector = None
        self.stats: BusStats | None = (
            BusStats() if get_flag("bus_telemetry") else None
        )

    def subscribe(self, topic: str, fn: Callable) -> Subscription:
        sub = Subscription(self, topic, fn)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
        return sub

    def publish(self, topic: str, msg: dict) -> int:
        """Fan out to all subscribers; returns the number delivered to.

        With a fault injector attached, the injector decides the
        delivery plan (drop/delay/duplicate); the returned count is the
        SUBSCRIBER count regardless — a NATS publisher can't observe
        in-flight loss either.

        Trace-context envelope: a publish from inside a traced scope
        (an explicit ``tracectx.bound`` or a handler delivering a
        context-stamped message) stamps the ambient context onto the
        message — on a COPY, so retried publishes of a shared dict and
        the caller's object are never mutated."""
        st = self.stats
        nbytes = st.on_publish(topic, msg)[1] if st is not None else 0
        msg = tracectx.attach(msg)
        inj = self.fault_injector
        if inj is not None:
            for delay_s in inj.intercept(topic, msg):
                if delay_s <= 0:
                    self._fanout(topic, msg, nbytes)
                else:
                    t = threading.Timer(
                        delay_s, self._fanout, (topic, msg, nbytes)
                    )
                    t.daemon = True
                    t.start()
            with self._lock:
                return len(self._subs.get(topic, []))
        return self._fanout(topic, msg, nbytes)

    def _fanout(self, topic: str, msg: dict, nbytes: int = 0) -> int:
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for s in subs:
            s._deliver(msg, nbytes)
        return len(subs)

    def request(self, topic: str, msg: dict, timeout_s: float = 5.0) -> dict:
        """NATS request/reply: publish with a one-shot ``_reply_to`` inbox
        and block for the response (the UDTF -> MDS stub call pattern).

        The inbox is a thread-less ``_OneShotInbox`` — the reply lands
        directly in this waiter's queue instead of spinning up (and
        tearing down) a dispatcher thread per call."""
        import uuid as _uuid

        st = self.stats
        inbox = f"_inbox.{_uuid.uuid4().hex}"
        q: queue.Queue = queue.Queue()
        sub = _OneShotInbox(self, inbox, q)
        with self._lock:
            self._subs.setdefault(inbox, []).append(sub)
        t0 = time.monotonic()
        try:
            n = self.publish(topic, {**msg, "_reply_to": inbox})
            if n == 0:
                if st is not None:
                    st.on_request("local", time.monotonic() - t0,
                                  error=True)
                raise BusTimeout(f"no responder on {topic!r}")
            reply = q.get(timeout=timeout_s)
            if st is not None:
                st.on_request("local", time.monotonic() - t0)
            return reply
        except queue.Empty:
            if st is not None:
                st.on_request("local", time.monotonic() - t0, error=True)
            raise BusTimeout(
                f"no reply from {topic!r} in {timeout_s}s"
            ) from None
        finally:
            sub.unsubscribe()

    def _remove(self, sub):
        with self._lock:
            lst = self._subs.get(sub.topic, [])
            if sub in lst:
                lst.remove(sub)

    def _on_handler_error(self, topic: str, e: Exception):
        with self._lock:
            self.handler_errors.append((topic, e, time.time_ns()))
            self._handler_errors_total += 1

    def busz(self) -> dict:
        """The ``/debug/busz`` surface for this bus: cumulative stat
        rows, live per-topic-class queue state, and the recent
        handler-error ring."""
        st = self.stats
        with self._lock:
            subs = [(t, list(lst)) for t, lst in self._subs.items()]
            recent = [
                {"topic": t, "error": repr(e), "unix_ns": ns}
                for t, e, ns in self.handler_errors
            ]
            errors_total = self._handler_errors_total
        queues: dict[str, dict] = {}
        for topic, lst in subs:
            cls = topic_class(topic)
            ent = queues.setdefault(
                cls, {"subscriptions": 0, "depth": 0, "high_water": 0}
            )
            for s in lst:
                ent["subscriptions"] += 1
                ent["depth"] = max(ent["depth"], s._q.qsize())
                ent["high_water"] = max(
                    ent["high_water"], getattr(s, "_hw", 0)
                )
        if st is not None:
            for cls, hw in st.queue_high_water().items():
                ent = queues.setdefault(
                    cls, {"subscriptions": 0, "depth": 0, "high_water": 0}
                )
                ent["high_water"] = max(ent["high_water"], hw)
        return {
            "rows": st.snapshot() if st is not None else [],
            "queues": queues,
            "handler_errors_total": errors_total,
            "recent_errors": recent,
        }

    def close(self):
        with self._lock:
            subs = [s for lst in self._subs.values() for s in lst]
        for s in subs:
            s.unsubscribe()
