"""In-process message bus with NATS pub/sub semantics.

Reference parity: ``src/common/event/nats.h:36-60`` (C++ NATS connector)
and the Go ``msgbus`` wrapper (``src/shared/services/msgbus``) — topics,
fan-out to every subscriber, asynchronous delivery. Each subscription
owns a queue + dispatcher thread so a slow handler never blocks
publishers or sibling subscribers (NATS's per-subscription pending
buffer). Swapping in a real NATS/gRPC transport means reimplementing
this one class against sockets; everything above it is transport-blind.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

from ..exec import tracectx


class Subscription:
    def __init__(self, bus: "MessageBus", topic: str, fn: Callable):
        self.bus = bus
        self.topic = topic
        self.fn = fn
        self._q: queue.Queue = queue.Queue()
        self._alive = True
        # Named for observability (and the ack-thread regression test):
        # one dispatcher thread per subscription, identifiable by topic.
        self._thread = threading.Thread(
            target=self._run, name=f"bus-sub-{topic}", daemon=True
        )
        self._thread.start()

    def _run(self):
        while True:
            msg = self._q.get()
            if msg is _CLOSE:
                return
            try:
                # Distributed-trace propagation: bind the message's
                # context envelope (if any) around the handler so work
                # it triggers — including Engine query traces — parents
                # under the publisher's span (tracectx.py).
                with tracectx.bound(tracectx.extract(msg)):
                    self.fn(msg)
            except Exception as e:  # handler errors must not kill delivery
                self.bus._on_handler_error(self.topic, e)

    def _deliver(self, msg):
        if self._alive:
            self._q.put(msg)

    def unsubscribe(self):
        self._alive = False
        self.bus._remove(self)
        self._q.put(_CLOSE)


_CLOSE = object()


class BusTimeout(TimeoutError):
    """Uniform request/reply timeout across bus transports.

    Both ``MessageBus.request`` and ``netbus.RemoteBus.request`` raise
    THIS (never a bare ``TimeoutError``) so broker/agent retry logic can
    catch one exception type regardless of transport."""


class MessageBus:
    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, list[Subscription]] = {}
        self.handler_errors: list[tuple[str, Exception]] = []
        # Optional faults.FaultInjector consulted on every publish
        # (drop/delay/duplicate + trigger hooks); None = no faults.
        self.fault_injector = None

    def subscribe(self, topic: str, fn: Callable) -> Subscription:
        sub = Subscription(self, topic, fn)
        with self._lock:
            self._subs.setdefault(topic, []).append(sub)
        return sub

    def publish(self, topic: str, msg: dict) -> int:
        """Fan out to all subscribers; returns the number delivered to.

        With a fault injector attached, the injector decides the
        delivery plan (drop/delay/duplicate); the returned count is the
        SUBSCRIBER count regardless — a NATS publisher can't observe
        in-flight loss either.

        Trace-context envelope: a publish from inside a traced scope
        (an explicit ``tracectx.bound`` or a handler delivering a
        context-stamped message) stamps the ambient context onto the
        message — on a COPY, so retried publishes of a shared dict and
        the caller's object are never mutated."""
        msg = tracectx.attach(msg)
        inj = self.fault_injector
        if inj is not None:
            for delay_s in inj.intercept(topic, msg):
                if delay_s <= 0:
                    self._fanout(topic, msg)
                else:
                    t = threading.Timer(delay_s, self._fanout, (topic, msg))
                    t.daemon = True
                    t.start()
            with self._lock:
                return len(self._subs.get(topic, []))
        return self._fanout(topic, msg)

    def _fanout(self, topic: str, msg: dict) -> int:
        with self._lock:
            subs = list(self._subs.get(topic, []))
        for s in subs:
            s._deliver(msg)
        return len(subs)

    def request(self, topic: str, msg: dict, timeout_s: float = 5.0) -> dict:
        """NATS request/reply: publish with a one-shot ``_reply_to`` inbox
        and block for the response (the UDTF -> MDS stub call pattern)."""
        import queue as _queue
        import uuid as _uuid

        inbox = f"_inbox.{_uuid.uuid4().hex}"
        q: _queue.Queue = _queue.Queue()
        sub = self.subscribe(inbox, q.put)
        try:
            n = self.publish(topic, {**msg, "_reply_to": inbox})
            if n == 0:
                raise BusTimeout(f"no responder on {topic!r}")
            return q.get(timeout=timeout_s)
        except _queue.Empty:
            raise BusTimeout(
                f"no reply from {topic!r} in {timeout_s}s"
            ) from None
        finally:
            sub.unsubscribe()

    def _remove(self, sub: Subscription):
        with self._lock:
            lst = self._subs.get(sub.topic, [])
            if sub in lst:
                lst.remove(sub)

    def _on_handler_error(self, topic: str, e: Exception):
        with self._lock:
            self.handler_errors.append((topic, e))

    def close(self):
        with self._lock:
            subs = [s for lst in self._subs.values() for s in lst]
        for s in subs:
            s.unsubscribe()
