"""Telemetry as tables: the engine's own history, queryable with PxL.

The platform that observes your cluster is observable the same way: a
``TelemetryCollector`` registers as a finished-trace listener on an
engine's ``Tracer`` (``exec/trace.py``) and folds every trace + its
``QueryResourceUsage`` into real ``table_store`` tables —

- ``__queries__``  one row per finished query/fragment/merge trace
- ``__spans__``    one row per span (bounded per trace)
- ``__agents__``   the folding agent's running totals per finished trace

— with bounded retention (each table's byte-budget ring expires its own
oldest rows, the same mechanism that bounds ingest tables). Bundled PxL
scripts (``px/slow_queries``, ``px/query_cost``, ``px/agent_health``)
run over these through the NORMAL engine path: on a cluster the
distributed planner fans the scan across every agent's local telemetry,
so per-agent attribution falls out of the ``agent_id`` column.

The collector also closes the planner's feedback loop (PAPERS.md
"Online Sketch-based Query Optimization", arXiv:2102.02440): observed
aggregate output cardinalities per script hash are retained and exposed
through ``Engine._compile_table_stats`` under ``__observed__``, where
``push_agg_through_join`` floors its partial-agg capacity at reality.

``ClusterTraceView`` is the stitching half (PAPERS.md "Near Data
Processing in Taurus", 2506.20010 — ship span summaries, not rows):
agents publish the spans of traces that carry a distributed parent
context on ``telemetry.spans``, the broker's view groups them with its
own dispatch spans by trace id, and ``/debug/tracez`` renders one
coherent waterfall per distributed query.

Both classes run OFF the engine's hot path: folding happens in
``Tracer.end_query`` after the exec guard is released, uses host lists
only (no device work, no syncs — registered in ``PXLINT_HOT_REGIONS``),
and all shared state is lock-guarded (bus dispatcher threads).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from ..config import get_flag
from ..ingest.schemas import TELEMETRY_SCHEMAS

#: Bus topic distributed-trace span summaries ride on (agent -> broker).
TOPIC_SPANS = "telemetry.spans"

#: Span rows folded/published per trace (the trace itself caps spans at
#: 512; telemetry keeps the head — root/compile/fragments come first).
MAX_SPAN_ROWS = 128

#: Observed-cardinality entries retained (per script hash; LRU-evicted).
MAX_OBSERVED = 256


def _span_rows(trace, agent_id: str, end_ns: int) -> dict:
    spans = trace.spans[:MAX_SPAN_ROWS]
    return {
        "time_": [s.start_unix_nano or end_ns for s in spans],
        "trace_id": [trace.trace_id] * len(spans),
        "span_id": [s.span_id for s in spans],
        "parent_id": [s.parent_id for s in spans],
        "name": [s.name for s in spans],
        "agent_id": [agent_id] * len(spans),
        "duration_ms": [
            ((s.end_unix_nano - s.start_unix_nano) / 1e6
             if s.end_unix_nano and s.start_unix_nano else 0.0)
            for s in spans
        ],
    }


def _span_summaries(trace) -> list:
    """Compact wire form of a trace's spans (ClusterTraceView rows)."""
    out = []
    for s in trace.spans[:MAX_SPAN_ROWS]:
        d = {
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "name": s.name,
            "start_unix_nano": int(s.start_unix_nano),
            "end_unix_nano": int(s.end_unix_nano),
        }
        status = s.attributes.get("status")
        if status:
            d["status"] = str(status)
        out.append(d)
    return out


class TableStatsCollector:
    """Folds table-store freshness snapshots into ``__tables__``.

    One row per (agent, table) whose stats CHANGED since this
    collector's previous fold (a change cursor, like the ``__programs__``
    drain: an idle table contributes zero rows however often the fold
    runs). Fired from two cadences — every finished trace (so a query
    immediately sees current storage state in its own history) and the
    agent heartbeat loop (so a query-less ingesting agent still records
    its watermark advance). ``__tables__`` itself is excluded: folding
    it would make every fold a change (each fold appends to it), one
    self-perpetuating row per fold forever on an idle system.

    Host-only arithmetic over already-maintained counters (registered
    in ``PXLINT_HOT_REGIONS`` alongside the trace fold); the lock
    serializes the cursor against concurrent trace listeners +
    heartbeat threads.
    """

    def __init__(self, engine, agent_id: str = "engine"):
        self.engine = engine
        self.agent_id = agent_id
        self._lock = threading.Lock()
        self._last: dict = {}  # table -> change signature tuple

    @staticmethod
    def _signature(f: dict) -> tuple:
        """What 'changed' means: any counter/watermark/size movement.
        ``last_append``/EWMA excluded on purpose — they only move when a
        counter does, and including wall-clock would defeat the cursor."""
        return (
            f["rows_total"], f["expired_rows_total"], f["bytes_total"],
            f["expired_bytes_total"], f["watermark"], f["device_bytes"],
            f["hot_bytes"],
        )

    def fold(self, end_ns: int | None = None, force: bool = False,
             snapshot: dict | None = None) -> int:
        """Append a ``__tables__`` row per changed table (every table
        when ``force`` — the heartbeat cadence, matching the reference's
        stats-on-every-heartbeat: an idle table's row still advances
        ``time_`` past its frozen watermark, which is exactly how
        px/ingest_lag sees a STOPPED ingest as growing lag). The
        change-cursored (per-trace) form covers USER tables only: the
        fold pass itself just appended to ``__queries__``/``__spans__``,
        so dunder tables are "changed" on every finished trace — rows
        for them at query rate would let self-telemetry snapshots evict
        the user-table history out of the ring; they fold at the
        bounded heartbeat cadence instead. ``snapshot`` lets the
        heartbeat reuse one ``TableStore.freshness()`` sweep for both
        the fold and the envelope. Returns the row count."""
        end_ns = end_ns or time.time_ns()
        snap = dict(
            snapshot if snapshot is not None
            else self.engine.table_store.freshness()
        )
        snap.pop("__tables__", None)
        with self._lock:
            changed = {
                name: f for name, f in snap.items()
                if (force or not name.startswith("__"))
                and (force or self._last.get(name) != self._signature(f))
            }
            if not changed:
                return 0
            names = sorted(changed)
            rows = [changed[n] for n in names]
            n = len(names)
            self.engine.append_data("__tables__", {
                "time_": [end_ns] * n,
                "agent_id": [self.agent_id] * n,
                "table": names,
                "rows": [f["rows"] for f in rows],
                "bytes": [f["bytes"] for f in rows],
                "hot_bytes": [f["hot_bytes"] for f in rows],
                "cold_bytes": [f["cold_bytes"] for f in rows],
                "hot_rows": [f["hot_rows"] for f in rows],
                "cold_rows": [f["cold_rows"] for f in rows],
                "cold_raw_bytes": [f["cold_raw_bytes"] for f in rows],
                "cold_demotions_total": [
                    f["cold_demotions_total"] for f in rows
                ],
                "cold_evictions_total": [
                    f["cold_evictions_total"] for f in rows
                ],
                "device_bytes": [f["device_bytes"] for f in rows],
                "rows_total": [f["rows_total"] for f in rows],
                "bytes_total": [f["bytes_total"] for f in rows],
                "expired_rows_total": [
                    f["expired_rows_total"] for f in rows
                ],
                "expired_bytes_total": [
                    f["expired_bytes_total"] for f in rows
                ],
                "watermark": [f["watermark"] for f in rows],
                "min_time": [f["min_time"] for f in rows],
                "last_append": [f["last_append"] for f in rows],
                "ingest_rows_per_s": [
                    float(f["ingest_rows_per_s"]) for f in rows
                ],
            })
            # Commit the cursor only after a successful append (the
            # __programs__ contract: a raising ring must not eat rows).
            for name, f in changed.items():
                self._last[name] = self._signature(f)
            return n


class BusStatsCollector:
    """Folds bus transport snapshots into ``__bus__``.

    One row per (kind, topic_class/peer, direction) key whose counters
    CHANGED since this collector's previous fold (the ``__tables__``
    change-cursor shape). Fired from the heartbeat cadence ONLY, never
    per trace: every distributed trace moves its own ack/dispatch
    counters, so a per-trace fold would be a self-perpetuating row per
    query — the same reasoning that keeps dunder tables out of the
    per-trace ``__tables__`` fold. Reads whatever ``bus.stats`` the
    agent's transport carries (``MessageBus`` or ``RemoteBus``); a
    stats-less bus (``bus_telemetry`` off, or no bus at all) folds
    nothing.
    """

    def __init__(self, engine, agent_id: str = "engine", bus=None):
        self.engine = engine
        self.agent_id = agent_id
        self.bus = bus
        self._lock = threading.Lock()
        self._last: dict = {}  # (kind, key, direction) -> signature

    @staticmethod
    def _signature(r: dict) -> tuple:
        """Any counter movement is a change; the histogram quantiles
        only move when a counter does."""
        return (r["msgs"], r["bytes"], r["errors"], r["queue_high_water"])

    def fold(self, end_ns: int | None = None, force: bool = False) -> int:
        """Append a ``__bus__`` row per changed key (every key when
        ``force`` — the heartbeat cadence). Returns the row count."""
        stats = getattr(self.bus, "stats", None)
        if stats is None:
            return 0
        end_ns = end_ns or time.time_ns()
        snap = stats.snapshot()
        with self._lock:
            changed = [
                r for r in snap
                if force or self._last.get(
                    (r["kind"], r["topic_class"], r["direction"])
                ) != self._signature(r)
            ]
            if not changed:
                return 0
            n = len(changed)
            self.engine.append_data("__bus__", {
                "time_": [end_ns] * n,
                "agent_id": [self.agent_id] * n,
                "kind": [r["kind"] for r in changed],
                "topic_class": [r["topic_class"] for r in changed],
                "direction": [r["direction"] for r in changed],
                "msgs": [int(r["msgs"]) for r in changed],
                "bytes": [int(r["bytes"]) for r in changed],
                "errors": [int(r["errors"]) for r in changed],
                "lag_p50_ms": [float(r["lag_p50_ms"]) for r in changed],
                "lag_p99_ms": [float(r["lag_p99_ms"]) for r in changed],
                "service_p50_ms": [
                    float(r["service_p50_ms"]) for r in changed
                ],
                "service_p99_ms": [
                    float(r["service_p99_ms"]) for r in changed
                ],
                "queue_high_water": [
                    int(r["queue_high_water"]) for r in changed
                ],
            })
            # Commit the cursor only after a successful append (the
            # __programs__ contract: a raising ring must not eat rows).
            for r in changed:
                self._last[
                    (r["kind"], r["topic_class"], r["direction"])
                ] = self._signature(r)
            return n


class TelemetryCollector:
    """Folds one engine's finished traces into its own table store."""

    def __init__(self, engine, agent_id: str = "engine",
                 kind: str = "engine", bus=None):
        self.engine = engine
        self.agent_id = agent_id
        self.kind = kind
        self.bus = bus
        # Storage-tier fold (``__tables__``): shared with the agent
        # heartbeat loop, which calls table_stats.fold() on its cadence.
        self.table_stats = TableStatsCollector(engine, agent_id)
        # Transport-tier fold (``__bus__``): heartbeat cadence only —
        # see BusStatsCollector on why never per trace.
        self.bus_stats = BusStatsCollector(engine, agent_id, bus=bus)
        self._lock = threading.Lock()
        self._totals = {
            "queries": 0, "errors": 0, "bytes_staged": 0,
            "device_ms": 0.0, "wire_bytes": 0,
        }
        self._observed: "OrderedDict[str, dict]" = OrderedDict()
        self._installed = False
        self.fold_errors = 0  # visible health of the fold path itself
        # __programs__ drain cursor into the process program registry
        # (exec/programs.py): each collector folds the rows that changed
        # since ITS last fold, so co-resident agents each get the full
        # program history in their own table.
        self._programs_seq = 0

    # -- lifecycle -----------------------------------------------------------
    def install(self) -> "TelemetryCollector":
        """Create the telemetry tables (bounded rings) and start folding.
        Idempotent; returns self."""
        if self._installed:
            return self
        budget = max(int(get_flag("telemetry_table_mb")), 1) << 20
        for name, rel in TELEMETRY_SCHEMAS.items():
            if self.engine.table_store.relation(name) is None:
                self.engine.create_table(name, rel, max_bytes=budget)
        self.engine.tracer.add_listener(self.on_trace)
        self.engine.telemetry = self
        self._installed = True
        return self

    # -- the fold (Tracer listener) ------------------------------------------
    def on_trace(self, trace) -> None:
        # Tracer._notify already contains exceptions, but count them
        # here too so a schema drift is visible, not silent.
        try:
            self._fold(trace)
        except Exception:
            with self._lock:
                self.fold_errors += 1
            raise

    def _fold(self, trace) -> None:
        end_ns = trace.end_unix_nano or time.time_ns()
        u = trace.usage
        agent = trace.agent_id or self.agent_id
        pred = trace.predicted or {}
        pred_bytes = pred.get("bytes_staged_hi")
        pred_rows = pred.get("rows_in_hi")
        self.engine.append_data("__queries__", {
            "time_": [end_ns],
            "trace_id": [trace.trace_id],
            "qid": [trace.qid or ""],
            "tenant": [getattr(trace, "tenant", "") or ""],
            "agent_id": [agent],
            "kind": [trace.kind],
            "script_hash": [trace.script_hash],
            "script": [trace.script[:200]],
            "status": [trace.status],
            "duration_ms": [trace.duration_s * 1e3],
            "rows_in": [int(u.rows_in)],
            "rows_out": [int(u.rows_out)],
            "windows": [int(u.windows)],
            "bytes_staged": [int(u.bytes_staged)],
            "device_ms": [float(u.device_ms)],
            "compile_ms": [float(u.compile_ms)],
            "stall_ms": [float(u.stall_ms)],
            "wire_bytes": [int(u.wire_bytes)],
            "retries": [int(u.retries)],
            "skipped_windows": [int(u.skipped_windows)],
            "device_peak_bytes": [int(u.device_peak_bytes)],
            # 0 = unknown (sketch-less plan / no bounds pass) — the
            # calibration scripts filter on > 0.
            "predicted_bytes": [int(pred_bytes or 0)],
            "predicted_rows": [int(pred_rows or 0)],
            "freshness_lag_ms": [float(u.freshness_lag_ms)],
            "cache": [getattr(trace, "cache", "")],
        })
        self.engine.append_data("__spans__", _span_rows(trace, agent, end_ns))
        self._fold_programs(end_ns)
        self.table_stats.fold(end_ns)
        with self._lock:
            t = self._totals
            t["queries"] += 1
            if trace.status == "error":
                t["errors"] += 1
            t["bytes_staged"] += int(u.bytes_staged)
            t["device_ms"] += float(u.device_ms)
            t["wire_bytes"] += int(u.wire_bytes)
            snapshot = dict(t)
            self._record_observed(trace)
        self.engine.append_data("__agents__", {
            "time_": [end_ns],
            "agent_id": [self.agent_id],
            "kind": [self.kind],
            "queries_total": [snapshot["queries"]],
            "errors_total": [snapshot["errors"]],
            "bytes_staged_total": [snapshot["bytes_staged"]],
            "device_ms_total": [snapshot["device_ms"]],
            "wire_bytes_total": [snapshot["wire_bytes"]],
        })
        # Distributed participants ship their span summary to the
        # broker's ClusterTraceView (sketch-sized telemetry, not rows).
        if self.bus is not None and trace.parent_ctx:
            self.bus.publish(TOPIC_SPANS, {
                "trace_id": trace.trace_id,
                "agent": agent,
                "spans": _span_summaries(trace),
            })

    def _fold_programs(self, end_ns: int) -> None:
        """Drain program-registry updates into ``__programs__`` (one
        cumulative-counter row per changed program; host-list arithmetic
        only — same no-sync contract as the trace fold)."""
        from ..exec.programs import default_program_registry

        # The whole fetch-append-commit runs under the collector lock:
        # listeners fire on whichever thread finished the trace (stream
        # cursor threads overlap query threads), and the cursor must
        # advance exactly once per successfully-appended row set — an
        # early commit would permanently drop rows when append_data
        # raises (ring budget/schema drift), an unlocked one could
        # double-fold or regress. Row volume is bounded by the registry
        # size, so the held append is small host-list work.
        with self._lock:
            cursor, rows = default_program_registry().rows(
                self._programs_seq
            )
            if rows:
                self._append_program_rows(end_ns, rows)
            self._programs_seq = max(self._programs_seq, cursor)

    def _append_program_rows(self, end_ns: int, rows: list) -> None:
        n = len(rows)
        self.engine.append_data("__programs__", {
            "time_": [end_ns] * n,
            "agent_id": [self.agent_id] * n,
            "program_id": [r["program_id"] for r in rows],
            "kind": [r["kind"] for r in rows],
            "label": [r["label"] for r in rows],
            "compiles": [int(r["compiles"]) for r in rows],
            "hits": [int(r["hits"]) for r in rows],
            "compile_ms": [float(r["compile_ms"]) for r in rows],
            "flops": [float(r["flops"]) for r in rows],
            "bytes_accessed": [float(r["bytes_accessed"]) for r in rows],
            "argument_bytes": [int(r["argument_bytes"]) for r in rows],
            "temp_bytes": [int(r["temp_bytes"]) for r in rows],
            "peak_bytes": [int(r["peak_bytes"]) for r in rows],
        })

    # -- planner feedback ----------------------------------------------------
    def _record_observed(self, trace) -> None:
        """Caller holds self._lock. Retain observed output cardinalities
        per script hash: the max aggregate-fragment rows_out is the true
        group count the sketch-driven sizing only estimated."""
        if trace.status != "ok":
            return
        agg_groups = 0
        for f in trace.stats.fragments:
            if any(op in ("AggOp", "rebucket") for op in f.ops):
                agg_groups = max(agg_groups, int(f.rows_out))
        ent = self._observed.pop(trace.script_hash, None) or {
            "agg_groups": 0, "rows_out": 0, "runs": 0,
        }
        ent["agg_groups"] = max(ent["agg_groups"], agg_groups)
        ent["rows_out"] = max(ent["rows_out"], int(trace.rows_out))
        ent["runs"] += 1
        self._observed[trace.script_hash] = ent  # re-insert = most recent
        while len(self._observed) > MAX_OBSERVED:
            self._observed.popitem(last=False)

    def observed(self) -> dict:
        """{script_hash: {agg_groups, rows_out, runs}} snapshot — what
        ``Engine._compile_table_stats`` exposes under ``__observed__``."""
        with self._lock:
            return {h: dict(e) for h, e in self._observed.items()}

    def totals(self) -> dict:
        with self._lock:
            return dict(self._totals)


class ObservedCostIndex:
    """Observed per-script-hash resource history → admission floor.

    The observed half of the arXiv:2102.02440 feedback loop at the
    BROKER: a tracer listener retains, per script hash, the maximum
    observed ``bytes_staged``/``rows_in`` of finished queries (the same
    numbers the agents' collectors fold into ``__queries__`` — the
    broker has no table store, so it indexes its own traces, whose
    usage is the merged per-agent record). ``floor_predicted`` then
    calibrates a pxbound prediction against that history the way
    ``push_agg_through_join`` floors its capacity at observed
    cardinality: an UNKNOWN (sketch-less) prediction with history
    becomes the observed bytes instead of zero, and a known prediction
    below observed reality is raised to it — so admission control
    (`_Admission`) schedules on calibrated rather than worst-case (or
    no) bounds. Bounded LRU; lock-guarded (tracer listeners run on
    whatever thread finished the query).
    """

    def __init__(self, tracer=None, max_entries: int = MAX_OBSERVED):
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        if tracer is not None:
            tracer.add_listener(self.on_trace)

    def on_trace(self, trace) -> None:
        if trace.status not in ("ok", "partial"):
            return
        u = trace.usage
        with self._lock:
            ent = self._entries.pop(trace.script_hash, None) or {
                "bytes_staged": 0, "rows_in": 0, "runs": 0,
            }
            ent["bytes_staged"] = max(
                ent["bytes_staged"], int(u.bytes_staged)
            )
            ent["rows_in"] = max(ent["rows_in"], int(u.rows_in))
            ent["runs"] += 1
            self._entries[trace.script_hash] = ent  # re-insert = recent
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def observed(self, script_hash: str) -> dict | None:
        with self._lock:
            ent = self._entries.get(script_hash)
            return dict(ent) if ent is not None else None

    def seed(self, entries: dict | None) -> None:
        """Fold a mirrored cost history into this index (broker-HA
        takeover: the standby replayed the leader's ``broker.state``
        cost events and the new leader starts calibrated instead of
        re-learning admission floors from zero). Max-merge per script
        hash — seeding can only raise an entry, mirroring
        :meth:`on_trace`; same LRU bound."""
        with self._lock:
            for h, e in (entries or {}).items():
                ent = self._entries.pop(h, None) or {
                    "bytes_staged": 0, "rows_in": 0, "runs": 0,
                }
                ent["bytes_staged"] = max(
                    ent["bytes_staged"], int(e.get("bytes_staged", 0))
                )
                ent["rows_in"] = max(ent["rows_in"], int(e.get("rows_in", 0)))
                ent["runs"] = max(ent["runs"], int(e.get("runs", 0)))
                self._entries[h] = ent
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)

    def floor_predicted(self, predicted: dict | None,
                        script_hash: str) -> dict | None:
        """Calibrated prediction: ``predicted`` floored at the observed
        history for ``script_hash`` (returns a NEW dict when flooring
        applied; the input is never mutated — it may already be stamped
        on a trace). No history, or history of zero staged bytes
        (fully device-resident runs), leaves the prediction unchanged —
        the floor can only ever RAISE the admission account."""
        ent = self.observed(script_hash)
        obs = int(ent["bytes_staged"]) if ent else 0
        if obs <= 0:
            return predicted
        pred_bytes = (predicted or {}).get("bytes_staged_hi")
        if pred_bytes is not None and int(pred_bytes) >= obs:
            return predicted
        out = dict(predicted or {})
        out["bytes_staged_hi"] = obs
        out["observed_floor"] = obs
        out["origin"] = (
            "observed" if pred_bytes is None
            else f"{out.get('origin', 'sketch')}+observed"
        )
        # Observed history carries no safety multiplier; keep the key
        # present so admission-reject diagnostics render "x1 safety"
        # instead of "xNone" when the floor built the dict from scratch.
        out.setdefault("safety", 1.0)
        return out


class ClusterTraceView:
    """Cluster-wide stitched traces for ``/debug/tracez`` (broker role).

    Collects span summaries from two feeds — the local tracer's finished
    traces (the broker's compile/dispatch/failover spans) and agents'
    ``telemetry.spans`` publications — grouped by trace id in a bounded
    LRU. A distributed query therefore renders as ONE trace: the
    broker's dispatch span parenting every agent's fragment spans.
    """

    def __init__(self, bus=None, tracer=None, max_traces: int = 64,
                 max_spans: int = 1024):
        self.max_traces = max_traces
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._sub = (
            bus.subscribe(TOPIC_SPANS, self._on_spans)
            if bus is not None else None
        )
        if tracer is not None:
            tracer.add_listener(self.add_trace)

    def close(self) -> None:
        if self._sub is not None:
            self._sub.unsubscribe()
            self._sub = None

    # -- feeds ---------------------------------------------------------------
    def add_trace(self, trace) -> None:
        """Local-tracer listener (broker's own traces)."""
        self._ingest(
            trace.trace_id, trace.agent_id or "broker",
            _span_summaries(trace),
        )

    def _on_spans(self, msg) -> None:
        tid, spans = msg.get("trace_id"), msg.get("spans")
        if isinstance(tid, str) and isinstance(spans, list):
            self._ingest(tid, str(msg.get("agent", "?")), spans)

    def _ingest(self, trace_id: str, agent: str, spans: list) -> None:
        with self._lock:
            ent = self._traces.pop(trace_id, None) or {
                "spans": [], "agents": set(), "updated_unix_nano": 0,
            }
            room = self.max_spans - len(ent["spans"])
            if room > 0:
                ent["spans"].extend(spans[:room])
            ent["agents"].add(agent)
            ent["updated_unix_nano"] = time.time_ns()
            self._traces[trace_id] = ent  # re-insert = most recent
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    # -- the /debug/tracez surface -------------------------------------------
    def tracez(self) -> dict:
        with self._lock:
            rows = [
                {
                    "trace_id": tid,
                    "agents": sorted(ent["agents"]),
                    "spans": len(ent["spans"]),
                    "root": next(
                        (s for s in ent["spans"] if not s["parent_id"]),
                        None,
                    ),
                    "updated_unix_nano": ent["updated_unix_nano"],
                }
                for tid, ent in reversed(self._traces.items())
            ]
        return {"traces": rows}

    def get(self, trace_id: str) -> dict | None:
        """Full stitched span list for one trace (newest-first feed
        order preserved per participant)."""
        with self._lock:
            ent = self._traces.get(trace_id)
            if ent is None:
                return None
            return {
                "trace_id": trace_id,
                "agents": sorted(ent["agents"]),
                "spans": [dict(s) for s in ent["spans"]],
            }


def enable_self_telemetry(engine, agent_id: str = "engine",
                          kind: str = "engine",
                          bus=None) -> TelemetryCollector:
    """Wire a TelemetryCollector onto an engine (idempotent: an engine
    that already has one keeps it)."""
    if getattr(engine, "telemetry", None) is not None:
        return engine.telemetry
    return TelemetryCollector(engine, agent_id, kind, bus=bus).install()


# -- profiling tier: folded-stack math + export formats ----------------------
#
# Pure host arithmetic over {folded_stack: count} maps and the
# profile-summary row shape agents ship in heartbeats
# ({stack, count, qid, script_hash, tenant, phase} — see
# ingest/profiler.py profile_summary). The broker's /debug/pprof,
# /debug/flamez and `px profile --diff` are thin wrappers over these.

def profile_counts(
    rows,
    tenant: str | None = None,
    script_hash: str | None = None,
    phase: str | None = None,
) -> dict[str, int]:
    """Collapse profile-summary rows to ``{folded_stack: count}``,
    optionally filtered by attribution."""
    out: dict[str, int] = {}
    for r in rows or ():
        if tenant is not None and r.get("tenant", "") != tenant:
            continue
        if script_hash is not None and r.get("script_hash", "") != script_hash:
            continue
        if phase is not None and r.get("phase", "") != phase:
            continue
        stack = r.get("stack", "")
        if not stack:
            continue
        out[stack] = out.get(stack, 0) + int(r.get("count", 0))
    return out


def counts_delta(before: dict, after: dict) -> dict[str, int]:
    """Per-stack growth between two cumulative snapshots (the
    ``/debug/pprof?seconds=N`` windowing primitive). Counts are
    monotonic per surviving stack; stacks evicted from a bounded
    summary between snapshots clamp to 0 rather than going negative."""
    return {
        s: n - before.get(s, 0)
        for s, n in after.items()
        if n - before.get(s, 0) > 0
    }


def collapsed_text(counts: dict[str, int]) -> str:
    """Flamegraph collapsed format: one ``stack count`` line per folded
    stack, hottest first — feedable to flamegraph.pl / speedscope / any
    pprof-collapsed importer."""
    lines = [
        f"{stack} {n}"
        for stack, n in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def profile_diff(base: dict, cmp: dict) -> list[dict]:
    """Differential profile between two ``{folded_stack: count}`` maps
    (two time windows, two script hashes, before/after a change...).

    Per-frame rows — ``frame`` is one ``file:func`` element — with
    **self** counts (samples where the frame is the leaf) and **total**
    counts (samples where it appears anywhere on the stack, counted
    once per stack), sorted by largest absolute self delta. This is the
    regression-hunting primitive: a frame whose self_delta jumped owns
    the new CPU; one whose total_delta jumped but self_delta did not is
    just calling someone who does."""
    def per_frame(counts: dict) -> tuple[dict, dict]:
        self_c: dict[str, int] = {}
        total_c: dict[str, int] = {}
        for stack, n in counts.items():
            frames = stack.split(";")
            leaf = frames[-1]
            self_c[leaf] = self_c.get(leaf, 0) + n
            for f in set(frames):
                total_c[f] = total_c.get(f, 0) + n
        return self_c, total_c

    self_b, total_b = per_frame(base)
    self_c, total_c = per_frame(cmp)
    rows = []
    for frame in set(total_b) | set(total_c):
        sb, sc = self_b.get(frame, 0), self_c.get(frame, 0)
        tb, tc = total_b.get(frame, 0), total_c.get(frame, 0)
        rows.append({
            "frame": frame,
            "self_base": sb, "self_cmp": sc, "self_delta": sc - sb,
            "total_base": tb, "total_cmp": tc, "total_delta": tc - tb,
        })
    rows.sort(
        key=lambda r: (
            -abs(r["self_delta"]), -abs(r["total_delta"]), r["frame"]
        )
    )
    return rows


def _flame_tree(counts: dict[str, int]) -> dict:
    """Folded stacks -> nested {name, value, children: [...]} tree."""
    root: dict = {"name": "all", "value": 0, "children": {}}
    for stack, n in counts.items():
        root["value"] += n
        node = root
        for frame in stack.split(";"):
            child = node["children"].setdefault(
                frame, {"name": frame, "value": 0, "children": {}}
            )
            child["value"] += n
            node = child

    def finish(node: dict) -> dict:
        kids = sorted(
            (finish(c) for c in node["children"].values()),
            key=lambda c: -c["value"],
        )
        return {"name": node["name"], "value": node["value"], "children": kids}

    return finish(root)


def flame_html(counts: dict[str, int], title: str = "pixie flame") -> str:
    """Self-contained static HTML flamegraph (no external assets): the
    folded-stack tree is embedded as JSON and rendered by ~30 lines of
    vanilla JS as nested width-proportional boxes with hover detail and
    click-to-zoom."""
    import html as _html
    import json as _json

    tree = _flame_tree(counts)
    return f"""<!doctype html>
<html><head><meta charset="utf-8"><title>{_html.escape(title)}</title>
<style>
body {{ font: 12px monospace; margin: 8px; background: #fff; }}
#flame {{ position: relative; }}
#flame div.f {{ position: absolute; box-sizing: border-box;
  overflow: hidden; white-space: nowrap; height: 17px;
  border: 1px solid #fff; cursor: pointer; }}
#meta {{ margin-bottom: 8px; color: #444; }}
</style></head><body>
<div id="meta">{_html.escape(title)} — total samples: {tree["value"]}
 (click a frame to zoom; click the root frame to reset)</div>
<div id="flame"></div>
<script>
const TREE = {_json.dumps(tree)};
const el = document.getElementById('flame');
function render(root) {{
  el.innerHTML = '';
  let maxDepth = 0;
  function place(node, x, frac, depth) {{
    maxDepth = Math.max(maxDepth, depth);
    const d = document.createElement('div'); d.className = 'f';
    d.style.left = (x * 100).toFixed(4) + '%';
    d.style.width = (frac * 100).toFixed(4) + '%';
    d.style.top = (depth * 18) + 'px';
    const pct = root.value ? (100 * node.value / root.value) : 0;
    d.textContent = node.name;
    d.title = node.name + ' — ' + node.value + ' samples (' +
      pct.toFixed(2) + '%)';
    d.style.background = depth === 0 ? '#d9d9d9' :
      'hsl(' + (38 - 18 * Math.min(pct, 100) / 100) + ',90%,' +
      (62 + (node.name.length % 5) * 2) + '%)';
    d.onclick = () => render(depth === 0 ? TREE : node);
    el.appendChild(d);
    let cx = x;
    for (const c of node.children) {{
      const cf = node.value ? frac * c.value / node.value : 0;
      if (root.value && c.value / root.value > 0.0005)
        place(c, cx, cf, depth + 1);
      cx += cf;
    }}
  }}
  place(root, 0, 1.0, 0);
  el.style.height = ((maxDepth + 1) * 18 + 4) + 'px';
}}
render(TREE);
</script></body></html>
"""
