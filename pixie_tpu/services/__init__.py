"""Service shell: agents, control plane, query brokering.

Reference parity: ``src/vizier/services`` — the agent runtime (PEM/Kelvin
managers over NATS, ``agent/manager/manager.h:102``), the metadata
service's agent tracker (``controllers/agent/agent.go``), and the query
broker (``query_broker/controllers/server.go``). The control plane here
is an in-process message bus with NATS semantics (topics, fan-out,
queued async delivery); the data plane passes payload objects in-process
where the reference streams protobuf over gRPC.
"""

from .agent import Agent, KelvinAgent, PEMAgent
from .broker_ha import BrokerReplica
from .faults import FaultInjector
from .msgbus import BusTimeout, MessageBus
from .query_broker import (
    AgentLost,
    QueryAbandoned,
    QueryBroker,
    QueryResultForwarder,
    QueryTimeout,
)
from .telemetry import (
    ClusterTraceView,
    TelemetryCollector,
    enable_self_telemetry,
)
from .tracker import AgentTracker

__all__ = [
    "Agent",
    "AgentLost",
    "AgentTracker",
    "BrokerReplica",
    "BusTimeout",
    "ClusterTraceView",
    "FaultInjector",
    "KelvinAgent",
    "MessageBus",
    "PEMAgent",
    "QueryAbandoned",
    "QueryBroker",
    "QueryResultForwarder",
    "QueryTimeout",
    "TelemetryCollector",
    "enable_self_telemetry",
]
