"""Tracepoint registry: the MDS deploy state machine.

Reference parity: ``src/vizier/services/metadata/controllers/tracepoint/
tracepoint.go`` — tracepoints register with a TTL, deploy to PEMs over
the message bus, aggregate per-agent states into PENDING / RUNNING / FAILED
/ TERMINATED, and expire (terminate + undeploy) when their TTL lapses.
The query broker's mutation executor (``mutation_executor.go:84``) drives
``apply`` + ``wait_ready`` before running the query phase.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..trace.spec import TracepointDelete, TracepointDeployment
from .msgbus import MessageBus

TOPIC_STATUS = "tracepoint.status"

PENDING = "PENDING"
RUNNING = "RUNNING"
FAILED = "FAILED"
TERMINATED = "TERMINATED"


@dataclass
class _TracepointRecord:
    deployment: TracepointDeployment
    state: str = PENDING
    agents: dict = field(default_factory=dict)  # agent_id -> state
    error: str = ""
    expires_at: float = 0.0


class TracepointRegistry:
    def __init__(self, bus: MessageBus, tracker,
                 ttl_check_interval_s: float = 5.0):
        self.bus = bus
        self.tracker = tracker
        self._lock = threading.Lock()
        self._records: dict[str, _TracepointRecord] = {}
        self._changed = threading.Condition(self._lock)
        self._sub = bus.subscribe(TOPIC_STATUS, self._on_status)
        # TTL watcher (tracepoint.go's expiry loop): tick() stays public
        # so tests drive expiry with explicit clocks.
        self._stop = threading.Event()
        self._ttl_thread = threading.Thread(
            target=self._ttl_loop, args=(ttl_check_interval_s,),
            name="tracepoint-ttl", daemon=True,
        )
        self._ttl_thread.start()

    def _ttl_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            self.tick()

    # -- mutation application ----------------------------------------------
    def apply(self, mutations, now: float | None = None) -> dict:
        """Upsert/delete a batch; returns {name: state}."""
        out = {}
        for m in mutations:
            if isinstance(m, TracepointDeployment):
                out[m.name] = self.upsert(m, now=now)
            elif isinstance(m, TracepointDelete):
                self.delete(m.name)
                out[m.name] = TERMINATED
        return out

    def upsert(self, dep: TracepointDeployment, now: float | None = None) -> str:
        now = time.monotonic() if now is None else now
        data_agents = [
            a.agent_id
            for a in self.tracker.distributed_state().agents
            if a.processes_data
        ]
        with self._lock:
            rec = self._records.get(dep.name)
            if rec is not None and rec.deployment == dep and rec.state in (
                PENDING, RUNNING
            ):
                rec.expires_at = now + dep.ttl_s  # TTL refresh only
                return rec.state
            rec = _TracepointRecord(
                deployment=dep, expires_at=now + dep.ttl_s
            )
            rec.agents = {aid: PENDING for aid in data_agents}
            self._records[dep.name] = rec
        for aid in data_agents:
            self.bus.publish(
                f"agent.{aid}.tracepoint", {"op": "deploy", "deployment": dep}
            )
        return PENDING

    def delete(self, name: str) -> None:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                return
            rec.state = TERMINATED
            agents = list(rec.agents)
        for aid in agents:
            self.bus.publish(
                f"agent.{aid}.tracepoint", {"op": "remove", "name": name}
            )

    # -- status aggregation --------------------------------------------------
    def _on_status(self, msg: dict) -> None:
        name, agent, state = msg["name"], msg["agent"], msg["state"]
        with self._lock:
            rec = self._records.get(name)
            if rec is None or rec.state == TERMINATED:
                return
            rec.agents[agent] = state
            if msg.get("error"):
                rec.error = msg["error"]
            states = set(rec.agents.values())
            if RUNNING in states:
                rec.state = RUNNING  # any running PEM serves the table
            elif states and states <= {FAILED}:
                rec.state = FAILED
            self._changed.notify_all()

    def state(self, name: str) -> str | None:
        with self._lock:
            rec = self._records.get(name)
            return rec.state if rec is not None else None

    def info(self, name: str) -> dict | None:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                return None
            return {
                "state": rec.state,
                "agents": dict(rec.agents),
                "error": rec.error,
                "table_name": rec.deployment.table_name,
            }

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._records)

    def wait_ready(self, names, timeout_s: float = 10.0) -> dict:
        """Block until every named tracepoint is RUNNING (and its table
        schema is visible to the planner) or FAILED; returns states."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while True:
                states = {
                    n: (self._records[n].state if n in self._records else None)
                    for n in names
                }
                settled = all(s in (RUNNING, FAILED, TERMINATED) for s in states.values())
                if settled:
                    tables = [
                        self._records[n].deployment.table_name
                        for n in names
                        if n in self._records
                        and self._records[n].state == RUNNING
                    ]
                    known = self.tracker.schemas()
                    if all(t in known for t in tables):
                        return states
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return states
                self._changed.wait(timeout=min(remaining, 0.25))

    # -- TTL expiry ----------------------------------------------------------
    def tick(self, now: float | None = None) -> list[str]:
        """Expire TTL-lapsed tracepoints (tracepoint.go TTL watcher)."""
        now = time.monotonic() if now is None else now
        expired = []
        with self._lock:
            for name, rec in self._records.items():
                if rec.state != TERMINATED and now >= rec.expires_at:
                    expired.append(name)
        for name in expired:
            self.delete(name)
        return expired

    def close(self) -> None:
        self._stop.set()
        self._sub.unsubscribe()
