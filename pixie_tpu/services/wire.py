"""Wire format: versioned self-describing binary encoding for the
control and data planes.

Reference parity: Carnot ships RowBatches and exec errors as protobuf
over gRPC (``src/carnot/carnotpb/carnot.proto:96-99``
``TransferResultChunkRequest``) and control messages as protobuf NATS
envelopes (``src/vizier/messages/messagespb``). This codec plays both
roles for this runtime: every message the in-process bus carries — plan
dispatch, bridge payloads (partial-agg state pytrees, row batches),
results, tracepoint deployments — round-trips through ``encode`` /
``decode`` so agents can live in separate processes (see ``netbus.py``).

Design: tag-prefixed recursive encoding over an explicit TYPE TABLE —
no pickle, no arbitrary code execution on decode; unknown tags/types are
hard errors. The first byte is the format version.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

WIRE_VERSION = 4  # v4: AggStatePayload.dense_strides (v3: .dense_offsets)

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U16 = struct.Struct("<H")


class WireError(Exception):
    pass


def _registered_types():
    """The closed set of structured types allowed on the wire."""
    from ..exec import otel as _otel
    from ..exec import plan as _plan
    from ..exec.engine import AggStatePayload, RowsPayload
    from ..trace import spec as _trace
    from ..types.batch import HostBatch
    from ..types.relation import Relation
    from ..types.strings import StringDictionary

    types = [
        Relation,
        StringDictionary,
        HostBatch,
        AggStatePayload,
        RowsPayload,
        _plan.Plan,
        _plan.PlanNode,
        _plan.MemorySourceOp,
        _plan.MapOp,
        _plan.FilterOp,
        _plan.AggOp,
        _plan.JoinOp,
        _plan.LimitOp,
        _plan.UnionOp,
        _plan.UDTFSourceOp,
        _plan.EmptySourceOp,
        _plan.BridgeSinkOp,
        _plan.BridgeSourceOp,
        _plan.OTelExportSinkOp,
        _plan.ResultSinkOp,
        _plan.TableSinkOp,
        _plan.ColumnRef,
        _plan.Literal,
        _plan.FuncCall,
        _plan.AggExpr,
        _otel.OTelEndpointConfig,
        _otel.OTelMetricGauge,
        _otel.OTelMetricSummary,
        _otel.OTelSpan,
        _otel.OTelDataSpec,
        _trace.TraceExpr,
        _trace.ProbeDef,
        _trace.TracepointDeployment,
        _trace.TracepointDelete,
    ]
    return types


_TYPES: list | None = None
_TYPE_IDS: dict | None = None


def _tables():
    global _TYPES, _TYPE_IDS
    if _TYPES is None:
        _TYPES = _registered_types()
        _TYPE_IDS = {t: i for i, t in enumerate(_TYPES)}
    return _TYPES, _TYPE_IDS


def _obj_fields(obj) -> dict:
    """Structured object -> plain field dict (encoder side)."""
    from ..exec.plan import Plan
    from ..types.batch import HostBatch
    from ..types.relation import Relation
    from ..types.strings import StringDictionary

    if isinstance(obj, Relation):
        return {"items": [(n, t.value) for n, t in obj.items()]}
    if isinstance(obj, StringDictionary):
        return {"strings": list(obj.strings)}
    if isinstance(obj, HostBatch):
        return {
            "relation": obj.relation,
            "cols": {n: tuple(np.asarray(p) for p in ps)
                     for n, ps in obj.cols.items()},
            "length": obj.length,
            "dicts": dict(obj.dicts),
            "eow": obj.eow,
            "eos": obj.eos,
        }
    if isinstance(obj, Plan):
        return {"nodes": dict(obj.nodes)}
    if dataclasses.is_dataclass(obj):
        return {f.name: getattr(obj, f.name) for f in dataclasses.fields(obj)}
    raise WireError(f"cannot encode fields of {type(obj).__name__}")


def _obj_build(cls, fields: dict):
    """Field dict -> object (decoder side)."""
    import itertools

    from ..exec.plan import Plan
    from ..types.batch import HostBatch
    from ..types.dtypes import DataType
    from ..types.relation import Relation
    from ..types.strings import StringDictionary

    if cls is Relation:
        return Relation([(n, DataType(v)) for n, v in fields["items"]])
    if cls is StringDictionary:
        return StringDictionary(fields["strings"])
    if cls is HostBatch:
        return HostBatch(
            relation=fields["relation"],
            cols={n: tuple(ps) for n, ps in fields["cols"].items()},
            length=fields["length"],
            dicts=fields["dicts"],
            eow=fields["eow"],
            eos=fields["eos"],
        )
    if cls is Plan:
        nodes = fields["nodes"]
        start = (max(nodes) + 1) if nodes else 0
        return Plan(nodes=nodes, _counter=itertools.count(start))
    return cls(**fields)


# -- encoder -----------------------------------------------------------------


def _enc(obj, out: list) -> None:
    from ..types.dtypes import DataType

    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, int) and not isinstance(obj, bool):
        if -(2**63) <= obj < 2**63:
            out.append(b"I")
            out.append(_I64.pack(obj))
        else:  # u128 values etc.
            s = str(obj).encode()
            out.append(b"J")
            out.append(_U32.pack(len(s)))
            out.append(s)
    elif isinstance(obj, float):
        out.append(b"D")
        out.append(_F64.pack(obj))
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(b"S")
        out.append(_U32.pack(len(b)))
        out.append(b)
    elif isinstance(obj, bytes):
        out.append(b"B")
        out.append(_U32.pack(len(obj)))
        out.append(obj)
    elif isinstance(obj, (np.ndarray, np.generic)):
        # np.ascontiguousarray promotes 0-d to 1-d — preserve 0-d shapes.
        arr = np.asarray(obj)
        if not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        if arr.dtype == object:  # decoded string columns etc.
            out.append(b"G")
            out.append(_U16.pack(arr.ndim))
            for d in arr.shape:
                out.append(_U32.pack(d))
            for v in arr.reshape(-1).tolist():
                _enc(v, out)
            return
        dt = arr.dtype.str.encode()
        out.append(b"A")
        out.append(_U16.pack(len(dt)))
        out.append(dt)
        out.append(b"\x01" if isinstance(obj, np.generic) else b"\x00")
        out.append(_U16.pack(arr.ndim))
        for d in arr.shape:
            out.append(_U32.pack(d))
        out.append(arr.tobytes())
    elif isinstance(obj, tuple):
        out.append(b"U")
        out.append(_U32.pack(len(obj)))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, (set, frozenset)):
        # Sets would decode as lists — a silent type change the in-process
        # bus never makes; reject at the publisher instead.
        raise WireError(
            "sets are not wire-encodable; send a sorted list/tuple"
        )
    elif isinstance(obj, list):
        out.append(b"L")
        out.append(_U32.pack(len(obj)))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, dict):
        out.append(b"M")
        out.append(_U32.pack(len(obj)))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    elif isinstance(obj, DataType):
        b = obj.value.encode()
        out.append(b"E")
        out.append(_U16.pack(len(b)))
        out.append(b)
    else:
        _, ids = _tables()
        tid = ids.get(type(obj))
        if tid is None:
            raise WireError(
                f"type {type(obj).__name__} is not wire-registered"
            )
        out.append(b"O")
        out.append(_U16.pack(tid))
        _enc(_obj_fields(obj), out)


def encode(obj) -> bytes:
    out: list = [bytes([WIRE_VERSION])]
    _enc(obj, out)
    return b"".join(out)


# -- decoder -----------------------------------------------------------------


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise WireError("truncated message")
        self.pos += n
        return b


def _dec(r: _Reader):
    from ..types.dtypes import DataType

    tag = r.take(1)
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"I":
        return _I64.unpack(r.take(8))[0]
    if tag == b"J":
        (n,) = _U32.unpack(r.take(4))
        return int(r.take(n).decode())
    if tag == b"D":
        return _F64.unpack(r.take(8))[0]
    if tag == b"S":
        (n,) = _U32.unpack(r.take(4))
        return r.take(n).decode()
    if tag == b"B":
        (n,) = _U32.unpack(r.take(4))
        return r.take(n)
    if tag == b"A":
        (dl,) = _U16.unpack(r.take(2))
        dt = np.dtype(r.take(dl).decode())
        scalar = r.take(1) == b"\x01"
        (ndim,) = _U16.unpack(r.take(2))
        shape = tuple(_U32.unpack(r.take(4))[0] for _ in range(ndim))
        count = 1
        for d in shape:
            count *= d
        arr = np.frombuffer(
            r.take(count * dt.itemsize), dtype=dt
        ).reshape(shape).copy()
        return arr[()] if scalar and ndim == 0 else arr
    if tag == b"G":
        (ndim,) = _U16.unpack(r.take(2))
        shape = tuple(_U32.unpack(r.take(4))[0] for _ in range(ndim))
        count = 1
        for d in shape:
            count *= d
        # Every element takes >= 1 byte on the wire: a corrupted shape
        # must fail as truncation, not as a giant up-front allocation.
        if count > len(r.buf) - r.pos:
            raise WireError("object-array count exceeds buffer")
        # Decode into a list FIRST: allocation stays proportional to
        # elements actually present (truncation fails fast) instead of
        # an up-front count-sized pointer array from a hostile shape.
        vals = [_dec(r) for _ in range(count)]
        arr = np.empty(count, dtype=object)
        for i, v in enumerate(vals):
            arr[i] = v  # per-element: sequences must not broadcast
        return arr.reshape(shape)
    if tag == b"U":
        (n,) = _U32.unpack(r.take(4))
        return tuple(_dec(r) for _ in range(n))
    if tag == b"L":
        (n,) = _U32.unpack(r.take(4))
        return [_dec(r) for _ in range(n)]
    if tag == b"M":
        (n,) = _U32.unpack(r.take(4))
        return {_dec(r): _dec(r) for _ in range(n)}
    if tag == b"E":
        (n,) = _U16.unpack(r.take(2))
        return DataType(r.take(n).decode())
    if tag == b"O":
        (tid,) = _U16.unpack(r.take(2))
        types, _ = _tables()
        if tid >= len(types):
            raise WireError(f"unknown wire type id {tid}")
        fields = _dec(r)
        return _obj_build(types[tid], fields)
    raise WireError(f"unknown wire tag {tag!r}")


def _dec_guarded(r: _Reader):
    """_dec with the failure surface promised to transports: ANY
    malformed input raises WireError. Corruption otherwise leaks
    ValueError/UnicodeDecodeError/KeyError/TypeError/struct.error out
    of the tag handlers and object constructors (fuzz-verified), and
    transport read loops only treat WireError/ConnectionError as
    "drop this peer"."""
    try:
        return _dec(r)
    except WireError:
        raise
    except (ValueError, KeyError, TypeError, AttributeError, IndexError,
            OverflowError, UnicodeDecodeError, struct.error,
            RecursionError, MemoryError) as e:
        raise WireError(
            f"malformed message: {type(e).__name__}: {e}"
        ) from None


def decode(buf: bytes):
    if not buf:
        raise WireError("empty message")
    if buf[0] != WIRE_VERSION:
        raise WireError(f"wire version {buf[0]} != {WIRE_VERSION}")
    r = _Reader(buf)
    r.pos = 1
    obj = _dec_guarded(r)
    if r.pos != len(buf):
        raise WireError(f"{len(buf) - r.pos} trailing bytes")
    return obj
