"""Process entrypoints for deployed roles (the agent/service mains).

Reference parity: the per-process mains — PEM (``src/vizier/services/
agent/pem/pem_main.cc``), Kelvin (``kelvin/kelvin_main.go``), and the
query-broker service (``src/vizier/services/query_broker``). One image,
one module, three roles:

  python -m pixie_tpu.deploy broker   # tracker + broker + netbus + obs
  python -m pixie_tpu.deploy pem      # data agent + source collectors
  python -m pixie_tpu.deploy kelvin   # merge agent

PEM/Kelvin dial the broker's netbus (PIXIE_TPU_BROKER host:port); the
broker serves the bus (NATS analog), the script APIs, and healthz/
statusz/metrics.
"""

from __future__ import annotations

import os
import signal
import sys
import threading


def _agent_id(default: str) -> str:
    return os.environ.get("PIXIE_TPU_AGENT_ID", default)


def _broker_addr() -> tuple[str, int]:
    addr = os.environ.get("PIXIE_TPU_BROKER", "127.0.0.1:6100")
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def run_broker() -> int:
    from .services.msgbus import MessageBus
    from .services.netbus import BusServer
    from .services.observability import ObservabilityServer
    from .services.query_broker import QueryBroker
    from .services.script_runner import ScriptRunner
    from .services.tracker import AgentTracker
    from .services.tracepoints import TracepointRegistry

    bus = MessageBus()
    # Broker HA (PIXIE_TPU_BROKER_HA=1): this process is one replica of
    # an N-broker control plane — PIXIE_TPU_BROKER_ID names it,
    # PIXIE_TPU_BROKER_ROLE=standby boots it as a lease-watching mirror
    # (default: leader). Standbys fold the leader's broker.state log
    # and take over in-flight queries when the lease lapses
    # (docs/RESILIENCE.md "Broker HA").
    replica = None
    if os.environ.get("PIXIE_TPU_BROKER_HA"):
        from .services.broker_ha import BrokerReplica

        replica = BrokerReplica(
            bus,
            os.environ.get("PIXIE_TPU_BROKER_ID", "broker-0"),
            leader=os.environ.get(
                "PIXIE_TPU_BROKER_ROLE", "leader"
            ) != "standby",
        )
        tracker, broker = replica.tracker, replica.broker
        broker.tracepoints = TracepointRegistry(bus, tracker)
    else:
        tracker = AgentTracker(bus)
        broker = QueryBroker(bus, tracker)
        broker.tracepoints = TracepointRegistry(bus, tracker)
        broker.serve()
    runner = ScriptRunner(broker)
    if replica is not None:
        # Cron scripts run on the LEADER only — a standby executing the
        # same schedule would double-run every script cluster-wide. The
        # gate follows failover: a promoted standby starts ticking.
        _tick = runner.tick
        runner.tick = (
            lambda: _tick() if replica.role == "leader" else None
        )
    runner.run_forever()
    netbus_port = int(os.environ.get("PIXIE_TPU_NETBUS_PORT", "6100"))
    server = BusServer(bus, host="0.0.0.0", port=netbus_port)
    # Broker self-profiling (self_profiling flag): the broker has no
    # agent engine, so its attributed stacks land in a process-local
    # TableStore (__stacks__ + stack_traces.beta) — but they DO merge
    # into /debug/pprof and /debug/flamez below: broker.profile_rows
    # folds the broker profiler's cumulative summary (agent_id
    # "broker") into the tracker's cluster merge.
    prof_store, prof_coll = _self_profiler("broker")
    statusz_extra = (
        (lambda: {"profiler": {
            "stacks": prof_store.get_table("stack_traces.beta").num_rows
            if prof_store.get_table("stack_traces.beta") else 0,
            "collector": dict(prof_coll.stats),
        }})
        if prof_coll is not None else (lambda: {})
    )
    obs = ObservabilityServer(
        statusz_fn=lambda: {
            "agents": tracker.agents_info(),
            "tables": sorted(tracker.schemas()),
            "quarantined": tracker.quarantined(),
            # HA role/epoch/lease-age/replay-lag on every replica's
            # /debug/statusz (leader AND standbys serve obs).
            **({"ha": replica.statusz()} if replica is not None else {}),
            **statusz_extra(),
        },
        # Broker-side distributed-query traces (dispatch/retry/failover
        # spans) back /debug/queryz on this role; the cluster-stitched
        # view (broker + agent spans per trace id) backs /debug/tracez.
        tracer=broker.tracer,
        trace_view=broker.trace_view,
        programs=_program_registry(),
        # Cluster-merged storage-tier snapshot: watermark = max across
        # agents, counters summed, per-agent lag spread.
        tablez_fn=lambda: {
            "scope": "cluster",
            "tables": tracker.table_freshness(),
        },
        # Result cache: merged distributed results keyed by script +
        # cluster watermarks (exec/result_cache.py).
        cachez_fn=broker.result_cache.cachez,
        # Profiling tier: cluster-merged CPU flames (agents' heartbeat
        # summaries + the broker's own sampler) back /debug/pprof and
        # /debug/flamez.
        profilez_fn=broker.profile_rows,
        # Transport tier: cluster-merged agent bus summaries + the
        # broker's local bus + the BusServer's per-connection wire
        # accounting.
        busz_fn=lambda: {
            **broker.busz(),
            "connections": server.busz(),
        },
    )
    obs_port = obs.start(int(os.environ.get("PIXIE_TPU_OBS_PORT", "6101")))
    print(
        f"[broker] netbus :{server.port} obs :{obs_port}", flush=True
    )
    _wait_forever()
    return 0


def _dial_broker(host: str, port: int):
    """RemoteBus with startup retry: deploy roles come up in any order
    (k8s gives no sequencing), so a PEM that boots before the broker's
    netbus listens must keep dialing, not crash."""
    import time as _time

    from .services.netbus import RemoteBus
    from .services.observability import default_counter

    deadline = _time.monotonic() + float(
        os.environ.get("PIXIE_TPU_DIAL_TIMEOUT_S", "60")
    )
    retries = default_counter(
        "pixie_net_dial_retries_total",
        "Failed broker-netbus dial attempts during role startup "
        "(roles come up in any order; each retry counts here).",
    )
    while True:
        try:
            return RemoteBus(host, port)
        except (ConnectionError, OSError):
            retries.inc()
            if _time.monotonic() >= deadline:
                raise
            _time.sleep(0.5)


def run_pem() -> int:
    from .ingest.collector import Collector
    from .ingest.connectors import (
        NetworkStatsConnector,
        PIDRuntimeConnector,
        ProcExitConnector,
        ProcStatConnector,
        ProcessStatsConnector,
        SeqGenConnector,
        StirlingErrorConnector,
    )
    from .ingest.profiler import PerfProfilerConnector
    from .services.agent import PEMAgent

    from .config import get_flag

    host, port = _broker_addr()
    bus = _dial_broker(host, port)
    agent = PEMAgent(bus, _agent_id("pem")).start()
    coll = Collector()
    coll.wire_to(agent)
    coll.register_source(ProcessStatsConnector())
    if get_flag("self_profiling"):
        coll.register_source(PerfProfilerConnector(pod=_agent_id("pem")))
    coll.register_source(ProcStatConnector())
    coll.register_source(PIDRuntimeConnector())
    coll.register_source(ProcExitConnector())
    coll.register_source(NetworkStatsConnector(pod=_agent_id("pem")))
    coll.register_source(StirlingErrorConnector())
    if os.environ.get("PIXIE_TPU_SEQGEN"):
        coll.register_source(SeqGenConnector())
    coll.run_as_thread()
    obs = _agent_obs(agent, extra=lambda: {"collector": dict(coll.stats)})
    print(
        f"[pem] {agent.agent_id} -> {host}:{port} obs :{obs}", flush=True
    )
    _wait_forever()
    return 0


def run_kelvin() -> int:
    from .config import get_flag
    from .services.agent import KelvinAgent

    host, port = _broker_addr()
    bus = _dial_broker(host, port)
    agent = KelvinAgent(bus, _agent_id("kelvin")).start()
    if get_flag("self_profiling"):
        # The kelvin's own collector thread (Agent.start ran it) drains
        # the profiler into its local stack_traces.beta — merge-tier
        # stacks are queryable through the agent's own engine/queryz.
        from .ingest.profiler import PerfProfilerConnector

        agent.collector.register_source(
            PerfProfilerConnector(pod=_agent_id("kelvin"))
        )
    obs = _agent_obs(agent)
    print(
        f"[kelvin] {agent.agent_id} -> {host}:{port} obs :{obs}", flush=True
    )
    _wait_forever()
    return 0


def _agent_obs(agent, extra=None) -> int:
    """healthz/statusz/metrics/queryz for an agent process; returns the
    port. The engine's tracer backs /debug/queryz and the query-latency
    histograms; the engine collector refreshes table/cache/pipeline
    gauges at each scrape (docs/OBSERVABILITY.md)."""
    from .services.observability import (
        ObservabilityServer,
        default_registry,
        engine_collector,
    )

    def statusz():
        out = {
            "agent_id": agent.agent_id,
            "tables": sorted(agent.engine.table_store.table_names()),
        }
        if extra is not None:
            out.update(extra())
        return out

    default_registry.register_collector(engine_collector(agent.engine))
    obs = ObservabilityServer(
        statusz_fn=statusz, tracer=agent.engine.tracer,
        # Device-tier surfaces: the process program registry backs
        # /debug/programz; pixie_device_memory_bytes gauges refresh at
        # scrape through the default monitor's collector (installed by
        # the engine).
        programs=_program_registry(),
        # Storage tier: this agent's local freshness snapshot (the
        # broker's /debug/tablez serves the cluster merge).
        tablez_fn=lambda: {
            "scope": "agent",
            "agent_id": agent.agent_id,
            "tables": agent.engine.table_store.freshness(),
        },
        # Local-engine result cache + registered materialized views.
        cachez_fn=lambda: {
            **agent.engine.result_cache.cachez(),
            "views": agent.engine.views.viewz(),
        },
        # Local profiler summary (this agent only): the broker serves
        # the cluster merge; an agent's /debug/pprof is its own flames.
        profilez_fn=_local_profilez(agent.agent_id),
        # Transport tier: this agent's bus (a RemoteBus in deploy, the
        # shared MessageBus in-process) — frames/RTT to the broker plus
        # its subscription queue state.
        busz_fn=lambda: {
            "scope": "agent",
            "agent_id": agent.agent_id,
            **agent.bus.busz(),
        },
    )
    return obs.start(int(os.environ.get("PIXIE_TPU_OBS_PORT", "0")))


def _local_profilez(own_agent_id: str):
    """profilez_fn over this process's profiler roster, filtered to one
    agent's samples (plus the handler's tenant/script filters)."""
    def fn(agent_id=None, tenant=None, script_hash=None):
        from .ingest.profiler import profile_summary

        rows = profile_summary(agent_id=agent_id or own_agent_id, top=0)
        return [
            r for r in rows
            if (tenant is None or r.get("tenant", "") == tenant)
            and (script_hash is None
                 or r.get("script_hash", "") == script_hash)
        ]
    return fn


def _self_profiler(role: str):
    """Broker-role self-profiling (``self_profiling`` flag): a
    Collector + PerfProfilerConnector sampling this process into a
    local TableStore (attributed ``__stacks__`` rows + the anonymous
    ``stack_traces.beta`` aggregate). Returns (store, collector) or
    (None, None) when the flag is off. The connector registers itself
    in the profiler's active roster, so its cumulative summary merges
    into /debug/pprof via broker.profile_rows. Agent roles don't use
    this — their profiler rides the agent's own collector into the
    queryable table store."""
    from .config import get_flag

    if not get_flag("self_profiling"):
        return None, None
    from .ingest.collector import Collector
    from .ingest.profiler import PerfProfilerConnector
    from .table_store import TableStore

    store = TableStore()
    coll = Collector()
    coll.wire_to(store)
    coll.register_source(PerfProfilerConnector(pod=role))
    coll.run_as_thread()
    return store, coll


def _program_registry():
    from .exec.programs import default_program_registry

    return default_program_registry()


def _wait_forever() -> None:
    stop = threading.Event()

    def on_stop(*_):
        # Last-gasp flushes before the role's own teardown runs
        # (crash.register_fatal_handler's SIGTERM contract — the crash
        # module's own SIGTERM handler is disabled for deploy roles).
        from .services.crash import run_fatal_handlers

        run_fatal_handlers()
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, on_stop)
    stop.wait()


def run_operator() -> int:
    """Operator role: reconcile the deployment's roles as processes
    (``src/operator/controllers`` analog — see services/operator.py).
    Spec comes from PIXIE_TPU_OPERATOR_SPEC (a YAML file of
    {role: replicas | {replicas, env}}); default is one of each role."""
    from .services.operator import Reconciler, specs_from_config

    spec_path = os.environ.get("PIXIE_TPU_OPERATOR_SPEC", "")
    cfg = {"broker": 1, "pem": 1, "kelvin": 1}
    if spec_path:
        import yaml

        try:
            with open(spec_path) as f:
                loaded = yaml.safe_load(f)
        except (OSError, yaml.YAMLError) as e:
            print(f"[operator] cannot read spec {spec_path}: {e}",
                  file=sys.stderr)
            return 2
        if loaded is not None:
            if not isinstance(loaded, dict):
                print("[operator] spec must be a mapping of "
                      "{role: replicas|{...}}", file=sys.stderr)
                return 2
            cfg = loaded
    # Self-reference would recurse (children also strip the spec env).
    cfg.pop("operator", None)
    try:
        specs = specs_from_config(cfg)
    except ValueError as e:
        print(f"[operator] {e}", file=sys.stderr)
        return 2
    rec = Reconciler(specs)
    rec.run_as_thread()
    print(f"[operator] reconciling roles: "
          f"{ {r: s.replicas for r, s in rec.specs.items()} }", flush=True)
    _wait_forever()
    rec.stop()
    return 0


def main(argv=None) -> int:
    roles = {"broker": run_broker, "pem": run_pem, "kelvin": run_kelvin,
             "operator": run_operator}
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 1 or args[0] not in roles:
        print(f"usage: python -m pixie_tpu.deploy {{{'|'.join(roles)}}}",
              file=sys.stderr)
        return 2
    # Crash machinery before anything else (signal_action.h parity):
    # hard faults dump stacks to the crash log, uncaught exceptions run
    # registered last-gasp handlers. SIGTERM stays with _wait_forever's
    # graceful teardown.
    from .services.crash import install as install_crash

    install_crash(role=args[0], sigterm_exits=False)
    # Runtime lock-order validation (pxlock's dynamic half): with the
    # lockdep flag set, enable BEFORE the role constructs any engine/
    # broker/agent — only locks created after enable() are tracked.
    from .config import get_flag

    if get_flag("lockdep"):
        from .analysis import lockdep

        lockdep.enable()
    return roles[args[0]]()


if __name__ == "__main__":
    sys.exit(main())
