"""Dictionary encoding for STRING columns.

TPU-first design: strings never reach the device. At staging time each
string column is encoded into int32 dictionary ids; all device-side ops
(equality filters, group-by keys, join keys) are id ops. Host-side UDFs
(regex, json, normalization) transform the *dictionary*, not the rows —
a dictionary with K distinct values is transformed in O(K) instead of
O(rows).

Reference contrast: Carnot ships raw strings through Arrow StringArrays
and hashes them per-row in agg/join maps (``src/carnot/exec/row_tuple.h``).
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

NULL_ID = -1


class StringDictionary:
    """Append-only string <-> int32 id mapping."""

    __slots__ = ("_str_to_id", "_strings")

    def __init__(self, strings: Iterable[str] = ()):
        self._strings: list[str] = []
        self._str_to_id: dict[str, int] = {}
        for s in strings:
            self.get_or_add(s)

    def __len__(self) -> int:
        return len(self._strings)

    def get_or_add(self, s: str) -> int:
        sid = self._str_to_id.get(s)
        if sid is None:
            sid = len(self._strings)
            self._str_to_id[s] = sid
            self._strings.append(s)
        return sid

    def lookup(self, s: str) -> int:
        """Id for ``s`` or NULL_ID if unseen (for filter literals)."""
        return self._str_to_id.get(s, NULL_ID)

    def encode(self, values: Iterable[str]) -> np.ndarray:
        vals = list(values)
        return np.fromiter((self.get_or_add(v) for v in vals), dtype=np.int32, count=len(vals))

    def decode(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids)
        table = np.empty(len(self._strings) + 1, dtype=object)
        table[:-1] = self._strings
        table[-1] = None  # slot for out-of-range / NULL_ID
        safe = np.where((ids >= 0) & (ids < len(self._strings)), ids, len(self._strings))
        return table[safe]

    def decode_one(self, sid: int) -> str | None:
        return self._strings[sid] if 0 <= sid < len(self._strings) else None

    @property
    def strings(self) -> list[str]:
        return self._strings

    def transform(self, fn) -> tuple["StringDictionary", np.ndarray]:
        """Host UDF escape hatch: apply ``fn`` to every distinct string.

        Returns (new_dict, remap) where ``remap[old_id] -> new_id``; device
        side applies the remap as a gather. O(K distinct), not O(rows).
        """
        new = StringDictionary()
        remap = np.empty(len(self._strings), dtype=np.int32)
        for i, s in enumerate(self._strings):
            remap[i] = new.get_or_add(fn(s))
        return new, remap

    def union(self, other: "StringDictionary") -> tuple["StringDictionary", np.ndarray, np.ndarray]:
        """Merged dict + id remaps for self and other (join/union alignment)."""
        merged = StringDictionary(self._strings)
        remap_self = np.arange(len(self._strings), dtype=np.int32)
        remap_other = np.fromiter(
            (merged.get_or_add(s) for s in other._strings),
            dtype=np.int32,
            count=len(other._strings),
        )
        return merged, remap_self, remap_other
