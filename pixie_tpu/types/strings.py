"""Dictionary encoding for STRING columns.

TPU-first design: strings never reach the device. At staging time each
string column is encoded into int32 dictionary ids; all device-side ops
(equality filters, group-by keys, join keys) are id ops. Host-side UDFs
(regex, json, normalization) transform the *dictionary*, not the rows —
a dictionary with K distinct values is transformed in O(K) instead of
O(rows).

Reference contrast: Carnot ships raw strings through Arrow StringArrays
and hashes them per-row in agg/join maps (``src/carnot/exec/row_tuple.h``).
"""

from __future__ import annotations

import hashlib
import struct
import threading
from typing import Iterable

import numpy as np

NULL_ID = -1


class StringDictionary:
    """Append-only string <-> int32 id mapping."""

    __slots__ = ("_str_to_id", "_strings", "_fp", "_fp_len", "_fp_digest",
                 "_fp_lock")

    def __init__(self, strings: Iterable[str] = ()):
        self._strings: list[str] = []
        self._str_to_id: dict[str, int] = {}
        # Incremental content fingerprint (content_key): hasher state,
        # how many strings it has absorbed, and the digest at that
        # length. Lazy — dictionaries that never cross a cache key pay
        # nothing. Per-dictionary lock: a first-call fingerprint of a
        # LARGE ingest dictionary hashes its whole string table, and a
        # process-wide lock would stall every other thread's compile
        # fast path behind that one dictionary.
        self._fp = None
        self._fp_len = 0
        self._fp_digest = b""
        self._fp_lock = threading.Lock()
        for s in strings:
            self.get_or_add(s)

    def content_key(self) -> tuple:
        """Content-addressed identity: ``(len, digest)`` over the
        ordered string table.

        The fragment cache (``exec/fragment.compile_fragment_cached``)
        keys dictionaries by THIS instead of ``id()``: bridge payloads
        that cross the wire decode into fresh ``StringDictionary``
        objects every query, so identity-keyed caching recompiled the
        merge tier's XLA programs on every distributed query — equal
        content must hit. Sound because the dictionary is append-only:
        two dictionaries with equal (ordered) content resolve every id
        and every compile-time ``lookup`` identically, and a dictionary
        that later GROWS simply produces a new key (its first
        ``len`` entries — all any cached fragment resolved against —
        are immutable). Amortized O(new strings): the hash state
        extends incrementally under the dictionary's own lock (a query
        thread can fingerprint while ingest appends on another).
        """
        with self._fp_lock:
            n = len(self._strings)
            if self._fp is None:
                self._fp = hashlib.blake2b(digest_size=16)
            if n > self._fp_len:
                h = self._fp
                for s in self._strings[self._fp_len:n]:
                    b = s.encode("utf-8", "surrogatepass")
                    # Length-prefixed: ("ab","c") never collides with
                    # ("a","bc").
                    h.update(struct.pack("<I", len(b)))
                    h.update(b)
                self._fp_len = n
                self._fp_digest = h.digest()
            elif not self._fp_digest and n == 0:
                self._fp_digest = self._fp.digest()
            return (n, self._fp_digest)

    def __len__(self) -> int:
        return len(self._strings)

    def get_or_add(self, s: str) -> int:
        sid = self._str_to_id.get(s)
        if sid is None:
            sid = len(self._strings)
            self._str_to_id[s] = sid
            self._strings.append(s)
        return sid

    def lookup(self, s: str) -> int:
        """Id for ``s`` or NULL_ID if unseen (for filter literals)."""
        return self._str_to_id.get(s, NULL_ID)

    def encode(self, values: Iterable[str]) -> np.ndarray:
        vals = list(values)
        return np.fromiter((self.get_or_add(v) for v in vals), dtype=np.int32, count=len(vals))

    def decode(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids)
        table = np.empty(len(self._strings) + 1, dtype=object)
        table[:-1] = self._strings
        table[-1] = None  # slot for out-of-range / NULL_ID
        safe = np.where((ids >= 0) & (ids < len(self._strings)), ids, len(self._strings))
        return table[safe]

    def decode_one(self, sid: int) -> str | None:
        return self._strings[sid] if 0 <= sid < len(self._strings) else None

    @property
    def strings(self) -> list[str]:
        return self._strings

    def transform(self, fn) -> tuple["StringDictionary", np.ndarray]:
        """Host UDF escape hatch: apply ``fn`` to every distinct string.

        Returns (new_dict, remap) where ``remap[old_id] -> new_id``; device
        side applies the remap as a gather. O(K distinct), not O(rows).
        """
        new = StringDictionary()
        remap = np.empty(len(self._strings), dtype=np.int32)
        for i, s in enumerate(self._strings):
            remap[i] = new.get_or_add(fn(s))
        return new, remap

    def union(self, other: "StringDictionary") -> tuple["StringDictionary", np.ndarray, np.ndarray]:
        """Merged dict + id remaps for self and other (join/union alignment)."""
        merged = StringDictionary(self._strings)
        remap_self = np.arange(len(self._strings), dtype=np.int32)
        remap_other = np.fromiter(
            (merged.get_or_add(s) for s in other._strings),
            dtype=np.int32,
            count=len(other._strings),
        )
        return merged, remap_self, remap_other
