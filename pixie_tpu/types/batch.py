"""Columnar batches: host-side staging form and device-resident form.

Reference parity: ``src/table_store/schema/row_batch.h:40`` (RowBatch =
vector of Arrow arrays + eow/eos markers). TPU-first redesign:

- A ``DeviceBatch`` is a *fixed-capacity* set of column planes plus a
  validity mask. Filters flip mask bits instead of producing
  data-dependent shapes (XLA needs static shapes); compaction happens
  only at shard/window boundaries.
- Capacities are bucketed to powers of two (min 1024 = 8 sublanes x 128
  lanes) so streaming windows reuse compiled programs instead of
  recompiling per batch size.
- A logical column is 1-2 physical planes (UINT128 -> hi/lo uint64).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .dtypes import DataType, device_dtypes, from_numpy_dtype, host_dtypes, pad_values
from .relation import Relation
from .strings import StringDictionary

# 8 float32 sublanes x 128 lanes — the minimum TPU tile.
MIN_CAPACITY = 1024


def bucket_capacity(n: int) -> int:
    """Round up to a power of two, at least MIN_CAPACITY."""
    cap = MIN_CAPACITY
    while cap < n:
        cap *= 2
    return cap


Planes = tuple  # tuple of np.ndarray | jnp.ndarray, one per physical plane


@dataclass
class HostBatch:
    """Host-side columnar batch (numpy planes; strings already dict-encoded)."""

    relation: Relation
    cols: dict[str, Planes]
    length: int
    dicts: dict[str, StringDictionary] = field(default_factory=dict)
    # Stream markers (reference: eow/eos on RowBatch).
    eow: bool = False
    eos: bool = False

    @classmethod
    def from_pydict(
        cls,
        data: Mapping[str, Sequence],
        relation: Relation | None = None,
        time_cols: Sequence[str] = ("time_",),
        dicts: Mapping[str, StringDictionary] | None = None,
    ) -> "HostBatch":
        """Build from {col: values}; infers the relation when not given."""
        cols: dict[str, Planes] = {}
        out_dicts: dict[str, StringDictionary] = {}
        rel_items: list[tuple[str, DataType]] = []
        length = None
        for name, values in data.items():
            arr = np.asarray(values)
            if length is None:
                length = len(arr)
            elif len(arr) != length:
                raise ValueError(f"column {name!r} length {len(arr)} != {length}")
            if relation is not None:
                dt = relation.col_type(name)
            else:
                if arr.ndim == 2 and arr.shape[1] == 2 and arr.dtype == np.uint64:
                    dt = DataType.UINT128  # (n, 2) [hi, lo] UPID layout
                else:
                    dt = from_numpy_dtype(arr.dtype, is_time=name in time_cols)
                rel_items.append((name, dt))
            if dt == DataType.STRING:
                if dicts is not None and name in dicts:
                    d = dicts[name]
                else:
                    d = StringDictionary()
                if np.issubdtype(arr.dtype, np.integer):
                    ids = arr.astype(np.int32)  # already dict-encoded
                else:
                    ids = d.encode([str(v) for v in arr])
                out_dicts[name] = d
                cols[name] = (ids,)
            elif dt == DataType.UINT128:
                if arr.ndim == 2 and arr.shape[1] == 2:  # (n, 2) [hi, lo]
                    cols[name] = (
                        arr[:, 0].astype(np.uint64),
                        arr[:, 1].astype(np.uint64),
                    )
                else:  # python ints
                    hi = np.fromiter(((int(v) >> 64) & (2**64 - 1) for v in values), np.uint64, length)
                    lo = np.fromiter((int(v) & (2**64 - 1) for v in values), np.uint64, length)
                    cols[name] = (hi, lo)
            else:
                (hdt,) = host_dtypes(dt)
                cols[name] = (arr.astype(hdt),)
        rel = relation if relation is not None else Relation(rel_items)
        return cls(relation=rel, cols=cols, length=length or 0, dicts=out_dicts)

    @property
    def nbytes(self) -> int:
        """Total plane bytes (the resource-accounting unit for staging
        and bridge-wire costs; dictionary strings not included)."""
        return int(sum(
            p.nbytes for planes in self.cols.values() for p in planes
        ))

    def to_pydict(self, decode_strings: bool = True) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {}
        for name, dt in self.relation.items():
            planes = self.cols[name]
            if dt == DataType.STRING and decode_strings and name in self.dicts:
                out[name] = self.dicts[name].decode(planes[0])
            elif dt == DataType.UINT128:
                out[name] = np.stack(planes, axis=1)
            else:
                out[name] = planes[0]
        return out

    def to_device(self, capacity: int | None = None, sharding=None) -> "DeviceBatch":
        """Pad to a fixed capacity and place on device.

        ``sharding`` (a jax.sharding.Sharding) places planes row-sharded
        over a mesh — the distributed staging path; None keeps the default
        single-device placement.
        """
        cap = capacity if capacity is not None else bucket_capacity(self.length)
        if cap < self.length:
            raise ValueError(f"capacity {cap} < batch length {self.length}")
        put = (lambda a: jax.device_put(a, sharding)) if sharding is not None else jnp.asarray
        cols: dict[str, Planes] = {}
        for name, dt in self.relation.items():
            pads = pad_values(dt)
            ddts = device_dtypes(dt)
            planes = []
            for plane, pad, ddt in zip(self.cols[name], pads, ddts):
                padded = np.full(cap, pad, dtype=np.dtype(ddt))
                padded[: self.length] = plane
                planes.append(put(padded))
            cols[name] = tuple(planes)
        valid = np.zeros(cap, dtype=np.bool_)
        valid[: self.length] = True
        return DeviceBatch(relation=self.relation, cols=cols, valid=put(valid))


@jax.tree_util.register_pytree_node_class
class DeviceBatch:
    """Fixed-capacity device-resident columnar batch with validity mask.

    Pytree: children = (cols, valid); aux = relation. Safe to pass through
    jit/shard_map; the relation is static metadata.
    """

    __slots__ = ("relation", "cols", "valid")

    def __init__(self, relation: Relation, cols: dict[str, Planes], valid):
        self.relation = relation
        self.cols = cols
        self.valid = valid

    @property
    def capacity(self) -> int:
        return self.valid.shape[-1]

    def n_valid(self):
        return jnp.sum(self.valid.astype(jnp.int32), axis=-1)

    def plane(self, name: str, i: int = 0):
        return self.cols[name][i]

    def with_cols(self, new_cols: Mapping[str, Planes], relation: Relation) -> "DeviceBatch":
        return DeviceBatch(relation=relation, cols=dict(new_cols), valid=self.valid)

    def with_valid(self, valid) -> "DeviceBatch":
        return DeviceBatch(relation=self.relation, cols=self.cols, valid=valid)

    def select(self, names: Sequence[str]) -> "DeviceBatch":
        return DeviceBatch(
            relation=self.relation.select(names),
            cols={n: self.cols[n] for n in names},
            valid=self.valid,
        )

    def to_host(
        self,
        dicts: Mapping[str, StringDictionary] | None = None,
        eow: bool = False,
        eos: bool = False,
    ) -> HostBatch:
        """Copy back to host, compacting to valid rows.

        eow/eos are host-plane stream markers (they never ride the device
        pytree — that would fork compiled programs per marker combination);
        the streaming layer threads them around the device hop.
        """
        valid = np.asarray(self.valid)
        idx = np.nonzero(valid)[0]
        cols: dict[str, Planes] = {}
        for name, dt in self.relation.items():
            hdts = host_dtypes(dt)
            cols[name] = tuple(
                np.asarray(p)[idx].astype(hdt) for p, hdt in zip(self.cols[name], hdts)
            )
        return HostBatch(
            relation=self.relation,
            cols=cols,
            length=len(idx),
            dicts=dict(dicts) if dicts else {},
            eow=eow,
            eos=eos,
        )

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        names = self.relation.column_names
        children = (tuple(self.cols[n] for n in names), self.valid)
        return children, self.relation

    @classmethod
    def tree_unflatten(cls, relation: Relation, children):
        col_planes, valid = children
        cols = {n: p for n, p in zip(relation.column_names, col_planes)}
        return cls(relation=relation, cols=cols, valid=valid)

    def __repr__(self) -> str:
        return f"DeviceBatch(capacity={self.capacity}, relation={self.relation})"
