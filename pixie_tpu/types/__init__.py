from .dtypes import DataType, device_dtypes, host_dtypes, is_numeric, n_planes, pad_values
from .relation import Relation
from .strings import NULL_ID, StringDictionary
from .batch import MIN_CAPACITY, DeviceBatch, HostBatch, bucket_capacity

__all__ = [
    "DataType",
    "Relation",
    "StringDictionary",
    "NULL_ID",
    "DeviceBatch",
    "HostBatch",
    "MIN_CAPACITY",
    "bucket_capacity",
    "device_dtypes",
    "host_dtypes",
    "is_numeric",
    "n_planes",
    "pad_values",
]
