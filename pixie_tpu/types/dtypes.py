"""Logical data types and their device representations.

Reference parity: the six Carnot data types
(``src/shared/types/typespb/types.proto:28-33``): BOOLEAN, INT64, UINT128,
FLOAT64, STRING, TIME64NS.

TPU-first mapping:

- BOOLEAN   -> bool_
- INT64     -> int64 (XLA emulates i64 on TPU; fine for adds/compares)
- UINT128   -> two uint64 planes (hi, lo) — no native u128 in XLA. UPIDs
  (``src/shared/upid``) are the main user; hash/compare are defined on the
  pair.
- FLOAT64   -> logically f64; **physically float32 on device**. Two reasons:
  (a) TPU emulates f64 in software — f32 keeps the VPU/MXU fast paths;
  (b) XLA:CPU exhibits a ~100x compile-time blowup fusing f64 multi-operand
  sorts with downstream arithmetic (measured 107s vs 0.66s for the t-digest
  compress kernel), so f64 never enters sorted/fused device code. Exact
  accumulation still happens: UDA carries (sum/mean) are f64 — they are
  [num_groups]-sized, sort-free, and finalize returns them to the host at
  full precision.
- STRING    -> int32 dictionary ids. Encoding happens host-side at staging
  time (see pixie_tpu.types.strings). Equality/group-by/join on strings are
  id ops inside XLA; regex & friends run host-side on the dictionary.
- TIME64NS  -> int64 nanoseconds since epoch.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    BOOLEAN = "boolean"
    INT64 = "int64"
    UINT128 = "uint128"
    FLOAT64 = "float64"
    STRING = "string"
    TIME64NS = "time64ns"

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"DataType.{self.name}"


# Number of physical device planes a logical column occupies.
_N_PLANES = {
    DataType.BOOLEAN: 1,
    DataType.INT64: 1,
    DataType.UINT128: 2,
    DataType.FLOAT64: 1,
    DataType.STRING: 1,
    DataType.TIME64NS: 1,
}

# Device dtypes per plane.
_DEVICE_DTYPES = {
    DataType.BOOLEAN: (jnp.bool_,),
    DataType.INT64: (jnp.int64,),
    DataType.UINT128: (jnp.uint64, jnp.uint64),
    DataType.FLOAT64: (jnp.float32,),
    DataType.STRING: (jnp.int32,),
    DataType.TIME64NS: (jnp.int64,),
}

# Host (numpy) dtypes per plane, used by the staging path and the hot store.
_HOST_DTYPES = {
    DataType.BOOLEAN: (np.bool_,),
    DataType.INT64: (np.int64,),
    DataType.UINT128: (np.uint64, np.uint64),
    DataType.FLOAT64: (np.float64,),
    DataType.STRING: (np.int32,),
    DataType.TIME64NS: (np.int64,),
}

# Neutral pad value per plane for invalid (masked) rows.
_PAD_VALUES = {
    DataType.BOOLEAN: (False,),
    DataType.INT64: (0,),
    DataType.UINT128: (0, 0),
    DataType.FLOAT64: (0.0,),
    DataType.STRING: (-1,),
    DataType.TIME64NS: (0,),
}

_NUMERIC = frozenset({DataType.INT64, DataType.FLOAT64, DataType.TIME64NS})


def n_planes(dt: DataType) -> int:
    return _N_PLANES[dt]


def device_dtypes(dt: DataType) -> tuple:
    return _DEVICE_DTYPES[dt]


def host_dtypes(dt: DataType) -> tuple:
    return _HOST_DTYPES[dt]


def pad_values(dt: DataType) -> tuple:
    return _PAD_VALUES[dt]


def is_numeric(dt: DataType) -> bool:
    return dt in _NUMERIC


def from_numpy_dtype(np_dtype, *, is_time: bool = False) -> DataType:
    """Infer a logical DataType from a numpy dtype (strings -> STRING)."""
    np_dtype = np.dtype(np_dtype) if not np.issubdtype(type(np_dtype), np.generic) else np_dtype
    if np_dtype == np.bool_:
        return DataType.BOOLEAN
    if np.issubdtype(np_dtype, np.integer):
        return DataType.TIME64NS if is_time else DataType.INT64
    if np.issubdtype(np_dtype, np.floating):
        return DataType.FLOAT64
    if np_dtype.kind in ("U", "S", "O"):
        return DataType.STRING
    if np_dtype.kind == "M":  # datetime64
        return DataType.TIME64NS
    raise TypeError(f"no DataType mapping for numpy dtype {np_dtype}")
