"""Semantic column types: what a value MEANS beyond its storage type.

Reference parity: the SemanticType enum
(``/root/reference/src/shared/types/typespb/types.proto:63-92``) and the
UDF semantic-inference machinery (``src/carnot/udf/type_inference.h``)
that threads e.g. ST_SERVICE_NAME through plans so metadata resolution
and UI formatting know a STRING column holds service names.

Here semantic types annotate UDF/UDA definitions directly (see
``udf.ScalarUDFDef.semantic_type``); the metadata resolver derives its
ctx-property mapping from them and docgen publishes them.
"""

from __future__ import annotations

import enum


class SemanticType(enum.IntEnum):
    """Mirrors the reference enum's names/values (types.proto:63)."""

    ST_UNSPECIFIED = 0
    ST_NONE = 1
    ST_TIME_NS = 2
    ST_AGENT_UID = 100
    ST_ASID = 101
    ST_UPID = 200
    ST_SERVICE_NAME = 300
    ST_POD_NAME = 400
    ST_POD_PHASE = 401
    ST_POD_STATUS = 402
    ST_NODE_NAME = 500
    ST_CONTAINER_NAME = 600
    ST_CONTAINER_STATE = 601
    ST_CONTAINER_STATUS = 602
    ST_NAMESPACE_NAME = 700
    ST_BYTES = 800
    ST_PERCENT = 900
    ST_DURATION_NS = 901
    ST_THROUGHPUT_PER_NS = 902
    ST_THROUGHPUT_BYTES_PER_NS = 903
    ST_QUANTILES = 1000
    ST_DURATION_NS_QUANTILES = 1001
    ST_IP_ADDRESS = 1100
    ST_PORT = 1200
    ST_HTTP_REQ_METHOD = 1300
    ST_HTTP_RESP_STATUS = 1400
    ST_HTTP_RESP_MESSAGE = 1500
    ST_SCRIPT_REFERENCE = 3000


#: Semantic type -> df.ctx[...] property keys it answers (the
#: convert_metadata_rule mapping, driven by annotations instead of a
#: hardcoded handler list).
CTX_KEYS: dict[SemanticType, tuple[str, ...]] = {
    SemanticType.ST_POD_NAME: ("pod", "pod_name"),
    SemanticType.ST_SERVICE_NAME: ("service", "service_name"),
    SemanticType.ST_NODE_NAME: ("node", "node_name"),
    SemanticType.ST_NAMESPACE_NAME: ("namespace",),
    SemanticType.ST_CONTAINER_NAME: ("container", "container_name"),
}
