"""Relation: an ordered (column name -> DataType) schema.

Reference parity: ``src/table_store/schema/relation.h:41`` — column
names + types, with semantic-type annotations deferred to the planner.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Mapping

from .dtypes import DataType


class Relation:
    """Immutable ordered schema."""

    __slots__ = ("_names", "_types", "_items")

    def __init__(self, columns: Mapping[str, DataType] | Iterable[tuple[str, DataType]] = ()):
        if isinstance(columns, Mapping):
            items = list(columns.items())
        else:
            items = list(columns)
        names = [n for n, _ in items]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in relation: {names}")
        self._names: tuple[str, ...] = tuple(names)
        self._types: dict[str, DataType] = {n: t for n, t in items}
        self._items: tuple | None = None  # items_tuple cache

    @property
    def column_names(self) -> tuple[str, ...]:
        return self._names

    def col_type(self, name: str) -> DataType:
        try:
            return self._types[name]
        except KeyError:
            raise KeyError(f"column {name!r} not in relation {self._names}") from None

    def has_column(self, name: str) -> bool:
        return name in self._types

    def col_index(self, name: str) -> int:
        return self._names.index(name)

    def items(self) -> Iterator[tuple[str, DataType]]:
        return iter((n, self._types[n]) for n in self._names)

    def items_tuple(self) -> tuple:
        """``tuple(self.items())``, computed once (the relation is
        immutable). Memo keys build one of these per table per compile
        (verify/bounds caches, fragment cache) — at ~20 canonical
        tables the rebuild was the dominant cost of a memo HIT."""
        if self._items is None:
            self._items = tuple((n, self._types[n]) for n in self._names)
        return self._items

    def select(self, names: Iterable[str]) -> "Relation":
        return Relation([(n, self.col_type(n)) for n in names])

    def add(self, name: str, dt: DataType) -> "Relation":
        if name in self._types:
            raise ValueError(f"column {name!r} already in relation")
        return Relation(list(self.items()) + [(name, dt)])

    def merge(self, other: "Relation", suffix: str = "_y") -> "Relation":
        """Concatenate schemas, suffixing collisions (join output naming)."""
        out = list(self.items())
        taken = set(self._names)
        for n, t in other.items():
            new_n = n
            while new_n in taken:
                new_n += suffix
            taken.add(new_n)
            out.append((new_n, t))
        return Relation(out)

    def __len__(self) -> int:
        return len(self._names)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Relation)
            and self._names == other._names
            and self._types == other._types
        )

    def __hash__(self) -> int:
        return hash(self.items_tuple())

    def __repr__(self) -> str:
        inner = ", ".join(f"{n}:{t.name}" for n, t in self.items())
        return f"Relation[{inner}]"
