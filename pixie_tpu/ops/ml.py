"""ML exec primitives: kmeans, mergeable uniform samples (coresets).

Reference parity: ``src/carnot/exec/ml/`` — Eigen kmeans (``kmeans.h:32``)
with kmeans++ init, streaming coresets (``coreset.h``), sampling
(``sampling.h``), consumed by ``funcs/builtins/ml_ops.h`` (KMeansUDA
:88, ReservoirSampleUDA :145).

TPU-first redesign: the reference's coreset tree is a pointer-chasing
stream structure; here the mergeable uniform sample is a **bottom-k
priority sketch** — every row draws a deterministic pseudo-random
priority (a hash of its value bits and window position) and each group
keeps the k lowest-priority rows. Bottom-k unions are associative, so
the same sketch serves window folds, cross-device ``psum``-style merges,
and agent-mode bridge payloads. K-means then runs on the per-group
sample entirely on device (vectorized Lloyd over [G, C] samples).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .scan import blocked_cummax

# numpy, not jnp: an eagerly-created jax scalar captured as a jit
# constant permanently poisons axon-tunnel dispatch.
_EMPTY = np.float32(np.inf)  # priority of an empty reservoir slot


def row_priorities(values, salt: int = 0x9E3779B9):
    """Deterministic pseudo-random priority per row in [0, 1).

    splitmix-style integer hash of the value bits xor'd with the row's
    window position. Rows at the same position with the same value in
    different windows collide; for sampling telemetry streams the bias
    is negligible (documented, matches the determinism constraint of
    compiled code — no RNG state threading).
    """
    values = jnp.asarray(values)
    if jnp.issubdtype(values.dtype, jnp.integer):
        u = values.astype(jnp.uint64)
        bits = (u ^ (u >> 32)).astype(jnp.uint32)
    else:
        bits = jax.lax.bitcast_convert_type(
            values.astype(jnp.float32), jnp.uint32
        )
    idx = jnp.arange(bits.shape[-1], dtype=jnp.uint32)
    x = bits ^ (idx * jnp.uint32(0x85EBCA6B)) ^ jnp.uint32(salt)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x.astype(jnp.float32) / jnp.float32(2**32)


# -- bottom-k reservoir (per-group, mergeable) -------------------------------
def reservoir_init(num_groups: int, capacity: int, dtype=jnp.float32):
    """``dtype`` is the sampled values' dtype — int64 samples stay int64
    (a sample must be an element of the data, bit-exactly)."""
    return (
        jnp.zeros((num_groups, capacity), dtype=dtype),  # values
        jnp.full((num_groups, capacity), _EMPTY),  # priorities
        jnp.zeros((num_groups,), dtype=jnp.float32),  # row counts
    )


def _batch_to_reservoir(values, prio, group_ids, mask, num_groups, capacity, dtype):
    """Scatter a window's rows into a fresh [G, C] bottom-k reservoir."""
    n = values.shape[-1]
    g, c = num_groups, capacity
    gid = jnp.where(mask, group_ids, g)
    # Lexsort (gid, prio): stable argsort of gid after argsort of prio.
    order1 = jnp.argsort(jnp.where(mask, prio, _EMPTY), stable=True)
    order2 = jnp.argsort(gid[order1], stable=True)
    order = order1[order2]
    gs = gid[order]
    vs = jnp.asarray(values, dtype)[order]
    ps = jnp.where(mask, prio, _EMPTY)[order]
    pos = jnp.arange(n)
    is_first = jnp.concatenate([jnp.ones(1, bool), gs[1:] != gs[:-1]])
    seg_start = blocked_cummax(jnp.where(is_first, pos, 0))
    rank = pos - seg_start
    slot = jnp.where((gs < g) & (rank < c), gs * c + rank, g * c)
    out_v = jnp.zeros(g * c + 1, dtype).at[slot].set(vs, mode="drop")
    out_p = jnp.full(g * c + 1, _EMPTY).at[slot].set(ps, mode="drop")
    counts = jax.ops.segment_sum(
        jnp.where(mask, 1.0, 0.0), gid, num_segments=g + 1
    )[:-1]
    return (
        out_v[:-1].reshape(g, c),
        out_p[:-1].reshape(g, c),
        counts.astype(jnp.float32),
    )


def reservoir_merge(a, b):
    """Associative bottom-k union of two reservoirs."""
    va, pa, ca = a
    vb, pb, cb = b
    v = jnp.concatenate([va, vb], axis=-1)
    p = jnp.concatenate([pa, pb], axis=-1)
    c = va.shape[-1]
    neg_top, idx = jax.lax.top_k(-p, c)  # lowest priorities win
    return (
        jnp.take_along_axis(v, idx, axis=-1),
        -neg_top,
        ca + cb,
    )


def reservoir_update(carry, group_ids, mask, values):
    g, c = carry[0].shape
    fresh = _batch_to_reservoir(
        values, row_priorities(values), group_ids, mask, g, c, carry[0].dtype
    )
    return reservoir_merge(carry, fresh)


# -- 1-D weighted k-means over per-group samples -----------------------------
def kmeans_groups(samples, sample_mask, k_max: int, k, iters: int = 16):
    """Lloyd iterations per group on [G, C] sample values.

    ``k`` is a [G] (or scalar) runtime cluster count <= k_max; centroids
    beyond k come back NaN. Init = evenly-spaced sample quantiles (the
    1-D stand-in for kmeans++: spread over the value range).
    """
    g, c = samples.shape
    k_arr = jnp.broadcast_to(jnp.asarray(k, jnp.int32), (g,))
    big = jnp.float32(3.4e38)
    s_sorted = jnp.sort(jnp.where(sample_mask, samples, big), axis=-1)
    n_valid = jnp.sum(sample_mask, axis=-1)
    # Initial centroids: quantile positions j/(k) over the valid prefix.
    j = jnp.arange(k_max, dtype=jnp.float32)
    pos = jnp.clip(
        ((j[None, :] + 0.5) / jnp.maximum(k_arr[:, None], 1))
        * jnp.maximum(n_valid[:, None] - 1, 0),
        0,
        c - 1,
    ).astype(jnp.int32)
    cent = jnp.take_along_axis(s_sorted, pos, axis=-1)  # [G, k_max]
    kmask = j[None, :] < k_arr[:, None]

    def body(_, cent):
        d = jnp.abs(samples[:, :, None] - cent[:, None, :])  # [G, C, K]
        d = jnp.where(kmask[:, None, :], d, big)
        assign = jnp.argmin(d, axis=-1)  # [G, C]
        onehot = (
            jax.nn.one_hot(assign, k_max, dtype=jnp.float32)
            * sample_mask[:, :, None]
        )
        wsum = jnp.sum(onehot, axis=1)  # [G, K]
        vsum = jnp.sum(onehot * samples[:, :, None], axis=1)
        return jnp.where(wsum > 0, vsum / jnp.maximum(wsum, 1e-30), cent)

    cent = jax.lax.fori_loop(0, iters, body, cent)
    cent = jnp.sort(jnp.where(kmask, cent, jnp.nan), axis=-1)
    return jnp.where(kmask & (n_valid[:, None] > 0), cent, jnp.nan)


# -- standalone multi-dim kmeans (library API, kmeans.h parity) --------------
@partial(jax.jit, static_argnames=("k", "iters"))
def kmeans_fit(points, k: int, iters: int = 32, weights=None):
    """Weighted Lloyd k-means on [N, D] points; returns [k, D] centroids.

    kmeans++-style init: greedy farthest-point seeding from the weighted
    mean (deterministic — compiled code can't thread RNG state).
    """
    n, d = points.shape
    w = jnp.ones(n) if weights is None else jnp.asarray(weights, jnp.float32)

    def seed_body(i, cent):
        d2 = jnp.min(
            jnp.sum((points[:, None, :] - cent[None, :, :]) ** 2, axis=-1)
            + jnp.where(jnp.arange(k)[None, :] < i, 0.0, 3.4e38),
            axis=-1,
        )
        nxt = points[jnp.argmax(d2 * w)]
        return cent.at[i].set(nxt)

    mean0 = jnp.sum(points * w[:, None], axis=0) / jnp.sum(w)
    cent = jnp.zeros((k, d)).at[0].set(mean0)
    cent = jax.lax.fori_loop(1, k, seed_body, cent)

    def lloyd(_, cent):
        d2 = jnp.sum((points[:, None, :] - cent[None, :, :]) ** 2, axis=-1)
        assign = jnp.argmin(d2, axis=-1)
        onehot = jax.nn.one_hot(assign, k, dtype=jnp.float32) * w[:, None]
        wsum = jnp.sum(onehot, axis=0)
        vsum = onehot.T @ points
        return jnp.where(wsum[:, None] > 0, vsum / jnp.maximum(wsum[:, None], 1e-30), cent)

    return jax.lax.fori_loop(0, iters, lloyd, cent)
