"""Batched HyperLogLog count-distinct over [num_groups, M] register arrays.

A mergeable sketch in the same shape as the t-digest: per-group int32
registers, segment-max updates, elementwise-max merge (associative — the
cross-device finalize is one all-reduce-max). 64-bit splitmix hashing is
done in uint64 lanes; the leading-zero count uses exact shift-based
highest-bit search (no float log2 — off-by-one at powers of two would bias
the estimator).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DEFAULT_P = 10  # 2^10 = 1024 registers, ~3.25% relative error


def _splitmix64(x):
    x = x.astype(jnp.uint64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15)) & jnp.uint64(0xFFFFFFFFFFFFFFFF)
    z = x
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def _hibit(w):
    """floor(log2(w)) for w > 0, exact, via 6 shift steps."""
    r = jnp.zeros(w.shape, dtype=jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        m = w >> jnp.uint64(s)
        take = m > 0
        r = r + take.astype(jnp.int32) * s
        w = jnp.where(take, m, w)
    return r


def hll_init(num_groups: int, p: int = DEFAULT_P):
    return jnp.zeros((num_groups, 1 << p), dtype=jnp.int32)


def hll_update(registers, group_ids, mask, values, p: int = DEFAULT_P):
    g, m = registers.shape
    h = _splitmix64(values.astype(jnp.int64))
    idx = (h & jnp.uint64(m - 1)).astype(jnp.int32)
    w = h >> jnp.uint64(p)
    rho = jnp.where(w > 0, 64 - p - _hibit(w), 64 - p + 1).astype(jnp.int32)

    flat = jnp.where(mask, group_ids.astype(jnp.int32) * m + idx, g * m)
    upd = jax.ops.segment_max(
        jnp.where(mask, rho, 0), flat, num_segments=g * m + 1
    )[:-1].reshape(g, m)
    return jnp.maximum(registers, upd)


def hll_estimate(registers):
    """Per-group cardinality estimate [G] (int64), with small-range correction."""
    g, m = registers.shape
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv_sum = jnp.sum(jnp.exp2(-registers.astype(jnp.float32)), axis=-1)
    raw = alpha * m * m / inv_sum
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    return jnp.round(est).astype(jnp.int64)
