"""Batched HyperLogLog count-distinct over [num_groups, M] register arrays.

A mergeable sketch in the same shape as the t-digest: per-group int32
registers, segment-max updates, elementwise-max merge (associative — the
cross-device finalize is one all-reduce-max). 64-bit splitmix hashing is
done in uint64 lanes; the leading-zero count uses exact shift-based
highest-bit search (no float log2 — off-by-one at powers of two would bias
the estimator).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_P = 10  # 2^10 = 1024 registers, ~3.25% relative error


def _splitmix64(x):
    x = x.astype(jnp.uint64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15)) & jnp.uint64(0xFFFFFFFFFFFFFFFF)
    z = x
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return z ^ (z >> jnp.uint64(31))


def _hibit(w):
    """floor(log2(w)) for w > 0, exact, via 6 shift steps."""
    r = jnp.zeros(w.shape, dtype=jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        m = w >> jnp.uint64(s)
        take = m > 0
        r = r + take.astype(jnp.int32) * s
        w = jnp.where(take, m, w)
    return r


def hll_init(num_groups: int, p: int = DEFAULT_P):
    return jnp.zeros((num_groups, 1 << p), dtype=jnp.int32)


def hll_update(registers, group_ids, mask, values, p: int = DEFAULT_P):
    g, m = registers.shape
    h = _splitmix64(values.astype(jnp.int64))
    idx = (h & jnp.uint64(m - 1)).astype(jnp.int32)
    w = h >> jnp.uint64(p)
    rho = jnp.where(w > 0, 64 - p - _hibit(w), 64 - p + 1).astype(jnp.int32)

    flat = jnp.where(mask, group_ids.astype(jnp.int32) * m + idx, g * m)
    upd = jax.ops.segment_max(
        jnp.where(mask, rho, 0), flat, num_segments=g * m + 1
    )[:-1].reshape(g, m)
    return jnp.maximum(registers, upd)


def hll_estimate(registers):
    """Per-group cardinality estimate [G] (int64), with small-range correction."""
    g, m = registers.shape
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv_sum = jnp.sum(jnp.exp2(-registers.astype(jnp.float32)), axis=-1)
    raw = alpha * m * m / inv_sum
    zeros = jnp.sum((registers == 0).astype(jnp.float32), axis=-1)
    linear = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    est = jnp.where((raw <= 2.5 * m) & (zeros > 0), linear, raw)
    return jnp.round(est).astype(jnp.int64)


# -- host (numpy) mirror -----------------------------------------------------
# The table-store ingest sketches (``table_store/sketches.py``) maintain
# ONE register row per key column on the append path, where a jax
# dispatch per pushed batch would dominate the sketch's cost. These
# mirrors compute bit-identical registers/estimates to the device
# kernels above (same splitmix64, same rho, same estimator constants),
# so a host-maintained sketch can be merged with (or checked against)
# device-produced registers freely.


def splitmix64_np(x: np.ndarray) -> np.ndarray:
    """Numpy splitmix64 — bit-identical to ``_splitmix64`` above."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64, copy=False) + np.uint64(0x9E3779B97F4A7C15)
        z = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def hll_init_np(p: int = DEFAULT_P) -> np.ndarray:
    return np.zeros(1 << p, dtype=np.int32)


def hll_update_np(registers: np.ndarray, values: np.ndarray,
                  p: int = DEFAULT_P) -> np.ndarray:
    """Fold ``values`` (any integer dtype) into one register row in place."""
    m = len(registers)
    h = splitmix64_np(values.astype(np.int64, copy=False).view(np.uint64))
    idx = (h & np.uint64(m - 1)).astype(np.int64)
    w = h >> np.uint64(p)
    # rho = leading-zero rank of the remaining 64-p bits, exact (no
    # float log2 — see module docstring).
    nz = w > 0
    hibit = np.zeros(len(w), dtype=np.int32)
    ww = w.copy()
    for s in (32, 16, 8, 4, 2, 1):
        m2 = ww >> np.uint64(s)
        take = m2 > 0
        hibit += take.astype(np.int32) * s
        ww = np.where(take, m2, ww)
    rho = np.where(nz, 64 - p - hibit, 64 - p + 1).astype(np.int32)
    np.maximum.at(registers, idx, rho)
    return registers


def hll_estimate_np(registers: np.ndarray) -> int:
    """Scalar estimate from one register row — same math as
    ``hll_estimate`` (alpha, small-range linear counting)."""
    m = len(registers)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv_sum = float(np.sum(np.exp2(-registers.astype(np.float64))))
    raw = alpha * m * m / inv_sum
    zeros = float(np.sum(registers == 0))
    if raw <= 2.5 * m and zeros > 0:
        return int(round(m * np.log(m / max(zeros, 1.0))))
    return int(round(raw))
