"""Pallas TPU kernel: t-digest histogram binning (dual scatter-add).

The sketch pipeline's hot loop (``ops/tdigest.py`` batch_to_digest,
reference ``src/carnot/funcs/builtins/math_sketches.h:34`` QuantilesUDA)
is two segment-sums over the same flat bin ids: per-bin weight and
weighted-value totals across ``G * B`` slots. XLA lowers those to two
HBM scatter passes; this kernel computes BOTH in one sweep of the rows
with the accumulators VMEM-resident, tiling the slot axis and using the
same one-hot MXU contraction trick as ``pallas_groupby`` — a [C, T]
one-hot against the row chunk yields the weight row-sum and the
weighted-mean contraction per tile.

FLOP note: the dense sweep costs n * S MACs (S = G*B slots). It wins
when S is small enough for the MXU to beat two scatter passes —
the caller gates on ``S <= 1 << 15`` (~2 GFLOP per 2M-row window, sub-ms
on the MXU) and falls back to the XLA scatters beyond that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: Slot-axis tile width (lanes).
_TILE = 2048


def _hist_kernel(bin_ref, val_ref, w_ref, mw_ref, *, tile: int):
    """Grid (slot_tiles, row_chunks): fold one row chunk into one tile."""
    t = pl.program_id(0)
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        w_ref[:] = jnp.zeros_like(w_ref)
        mw_ref[:] = jnp.zeros_like(mw_ref)

    bins = bin_ref[:]  # [C] i32 flat slot ids (trash >= n_slots_pad)
    vals = val_ref[:]  # [C] f32
    base = t * tile
    onehot = (
        (bins[:, None] - base)
        == jax.lax.broadcasted_iota(jnp.int32, (bins.shape[0], tile), 1)
    ).astype(jnp.float32)
    w_ref[:] += jnp.sum(onehot, axis=0)
    mw_ref[:] += vals @ onehot


@functools.partial(jax.jit, static_argnames=("n_slots", "chunk", "interpret"))
def hist_fold(bins, values, n_slots: int, chunk: int = 2048,
              interpret: bool = False):
    """(weights, weighted_sums) f32[n_slots] over flat bin ids.

    ``bins`` i32[n] in [0, n_slots) for live rows, >= padded slot count
    for masked rows; ``values`` f32[n]. n must be a chunk multiple;
    n_slots pads internally to the tile width.
    """
    n = bins.shape[0]
    pad = -(-n_slots // _TILE) * _TILE
    grid = (pad // _TILE, n // chunk)
    w, mw = pl.pallas_call(
        functools.partial(_hist_kernel, tile=_TILE),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk,), lambda t, i: (i,)),
            pl.BlockSpec((chunk,), lambda t, i: (i,)),
        ],
        out_specs=[
            pl.BlockSpec((_TILE,), lambda t, i: (t,)),
            pl.BlockSpec((_TILE,), lambda t, i: (t,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((pad,), jnp.float32),
            jax.ShapeDtypeStruct((pad,), jnp.float32),
        ],
        interpret=interpret,
    )(bins.astype(jnp.int32), values.astype(jnp.float32))
    return w[:n_slots], mw[:n_slots]
