"""Group-by machinery: exact dense group ids with static shapes.

Reference parity: Carnot's BlockingAggNode builds an absl flat_hash_map
keyed by RowTuple (``src/carnot/exec/agg_node.h:66``,
``src/carnot/exec/row_tuple.h``). Two exact device strategies:

- ``dense_group_ids`` — **multi-key lexicographic sort + first-occurrence
  cumsum**: no hashing at all; used for small inputs (regrouping two [G]
  states) where sort cost is negligible.
- ``dense_group_ids_hash`` — **bounded-probe open-addressing insert on
  device**: rows claim slots in a 2G-slot table via scatter-min rounds,
  then slot ranks give dense ids. Exact (full keys are compared, the hash
  only picks probe order); O(rounds * n) elementwise work instead of
  O(key_planes) full-window stable sorts — the per-window fast path.
  Probe exhaustion reports overflow, which the engine's rebucketing
  doubles away (Carnot's growing hash map, ``agg_node.cc``).

Plus the regroup layer: align two group states (different slot orders,
e.g. accumulated-state x new-window, or per-device partials) onto a shared
dense id space so UDA carries can be merged slot-wise. This is the TPU
replacement for Carnot's partial-agg-serialize -> GRPC -> finalize-agg
pipeline (``planner/distributed/splitter/partial_op_mgr``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .scan import blocked_cumsum


def _sortable(plane):
    """Map a key plane to its sortable bit view.

    Sorting/grouping happens on bit patterns (``_to_bits``), not values,
    so float keys group exactly by payload — bit-identical NaNs form ONE
    group — matching the hash path. (Value order for negative floats
    differs from numeric order; group *membership* is unaffected and
    callers never rely on group emission order.)
    """
    if plane.dtype == jnp.bool_:
        return plane.astype(jnp.int8)
    if jnp.issubdtype(plane.dtype, jnp.floating):
        return _to_bits(plane)
    return plane


def dense_group_ids(key_planes, mask, max_groups: int):
    """Assign dense group ids by multi-key sort.

    Args:
      key_planes: list of [n] arrays (a UINT128 key contributes two).
      mask: [n] bool; masked rows get id ``max_groups`` (trash slot).
      max_groups: static group capacity G.

    Returns:
      gids: int32[n] in [0, G) for valid rows, G for invalid.
      group_keys: list of [G] arrays — key values per dense id.
      group_valid: bool[G] — slots actually occupied.
      n_groups: int32 scalar — true distinct count (may exceed G; caller
        checks ``n_groups > max_groups`` to detect overflow).
    """
    n = mask.shape[0]
    planes = [_sortable(p) for p in key_planes]

    # Lexicographic stable sort: secondary keys first, primary last, with
    # invalid rows forced to the end via a final sort on ~mask.
    order = jnp.arange(n, dtype=jnp.int32)
    for p in reversed(planes):
        order = order[jnp.argsort(p[order], stable=True)]
    order = order[jnp.argsort(~mask[order], stable=True)]

    sorted_mask = mask[order]
    is_new = jnp.zeros(n, dtype=jnp.bool_)
    for p in planes:
        sp = p[order]
        diff = jnp.concatenate([jnp.ones(1, jnp.bool_), sp[1:] != sp[:-1]])
        is_new = is_new | diff
    is_new = is_new & sorted_mask

    # blocked: a flat window-length i32 cumsum overflows TPU scoped vmem
    # at multi-million-row windows (see ops/scan.py).
    sorted_gid = blocked_cumsum(is_new.astype(jnp.int32)) - 1
    n_groups = jnp.sum(is_new.astype(jnp.int32))
    # Clamp overflowing groups into the last slot; invalid rows -> G.
    sorted_gid_c = jnp.where(
        sorted_mask, jnp.clip(sorted_gid, 0, max_groups - 1), max_groups
    )
    gids = jnp.zeros(n, dtype=jnp.int32).at[order].set(sorted_gid_c)

    # First occurrence (in original row order) of each group -> key values.
    first_idx = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32), gids, num_segments=max_groups + 1
    )[:-1]
    group_valid = first_idx < n
    safe_idx = jnp.where(group_valid, first_idx, 0)
    group_keys = [p[safe_idx] for p in key_planes]
    return gids, group_keys, group_valid, n_groups


def _to_bits(p):
    """Bit-exact unsigned view of a key plane (u32 or u64).

    Comparing bit patterns (not values) makes float keys well-defined for
    NaNs (bit-identical NaNs group together) and costs nothing for ints;
    -0.0 canonicalizes to +0.0 first so both zeros stay one group
    (value-equality semantics, Carnot's RowTuple ==).
    """
    if p.dtype == jnp.bool_:
        return p.astype(jnp.uint32)
    if jnp.issubdtype(p.dtype, jnp.floating):
        p = jnp.where(p == 0, jnp.zeros_like(p), p)
    nbits = p.dtype.itemsize * 8
    if nbits < 32:
        return jax.lax.bitcast_convert_type(
            p.astype(jnp.int32), jnp.uint32
        )
    target = jnp.uint32 if nbits == 32 else jnp.uint64
    return jax.lax.bitcast_convert_type(p, target)


def _from_bits(bits, dtype):
    if dtype == jnp.bool_:
        return bits != 0
    nbits = jnp.dtype(dtype).itemsize * 8
    if nbits < 32:
        return jax.lax.bitcast_convert_type(bits, jnp.int32).astype(dtype)
    return jax.lax.bitcast_convert_type(bits, dtype)


def _mix32(x):
    """32-bit finalizer (lowbias32); wrapping uint32 arithmetic."""
    x ^= x >> jnp.uint32(16)
    x = x * jnp.uint32(0x7FEB352D)
    x ^= x >> jnp.uint32(15)
    x = x * jnp.uint32(0x846CA68B)
    x ^= x >> jnp.uint32(16)
    return x


def _hash_bits(bit_planes):
    h = jnp.full(bit_planes[0].shape, jnp.uint32(0x9E3779B9))
    for b in bit_planes:
        if b.dtype == jnp.uint64:
            h = _mix32(h ^ (b & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32))
            h = _mix32(h ^ (b >> jnp.uint64(32)).astype(jnp.uint32))
        else:
            h = _mix32(h ^ b)
    return h


def _table_size(max_groups: int) -> int:
    size = 16
    while size < 2 * max_groups:
        size *= 2
    return size


def dense_group_ids_hash(key_planes, mask, max_groups: int,
                         max_rounds: int = 32):
    """``dense_group_ids`` via a device-built open-addressing table.

    Same contract as ``dense_group_ids`` except group ids are in hash
    (arbitrary) order rather than key-sorted order. Rows linear-probe a
    2G-slot table: each round, rows whose candidate slot is free race to
    claim it (scatter-min on row index), the winner publishes its key,
    and every row whose candidate slot now holds its exact key resolves.
    Unresolved rows after ``max_rounds`` report overflow (n_groups >
    max_groups) so the caller rebuckets larger.
    """
    n = mask.shape[0]
    if not key_planes:
        # No-group aggregation: every valid row lands in slot 0 (matches
        # the sort path's degenerate behavior).
        gids = jnp.where(mask, 0, max_groups).astype(jnp.int32)
        group_valid = (
            jnp.zeros(max_groups, dtype=jnp.bool_).at[0].set(jnp.any(mask))
        )
        return gids, [], group_valid, jnp.int32(0)
    size = _table_size(max_groups)
    bit_planes = [_to_bits(p) for p in key_planes]
    base = (_hash_bits(bit_planes) & jnp.uint32(size - 1)).astype(jnp.int32)
    iota = jnp.arange(n, dtype=jnp.int32)

    slot_bits0 = tuple(
        jnp.zeros(size + 1, dtype=b.dtype) for b in bit_planes
    )
    occupied0 = jnp.zeros(size + 1, dtype=jnp.bool_)

    def round_body(carry):
        r, active, row_slot, occupied, slot_bits = carry
        cand = (base + r) & jnp.int32(size - 1)
        free = ~occupied[cand]
        contender = active & free
        claim_idx = jnp.where(contender, cand, size)
        claims = (
            jnp.full(size + 1, n, dtype=jnp.int32).at[claim_idx].min(iota)
        )
        winner = contender & (claims[cand] == iota)
        win_idx = jnp.where(winner, cand, size)
        occupied = occupied.at[win_idx].set(True)
        occupied = occupied.at[size].set(False)
        slot_bits = tuple(
            sb.at[win_idx].set(b) for sb, b in zip(slot_bits, bit_planes)
        )
        # Resolve rows whose candidate slot now holds their exact key.
        match = active & occupied[cand]
        for sb, b in zip(slot_bits, bit_planes):
            match = match & (sb[cand] == b)
        row_slot = jnp.where(match, cand, row_slot)
        active = active & ~match
        return r + 1, active, row_slot, occupied, slot_bits

    def round_cond(carry):
        r, active, *_ = carry
        return (r < max_rounds) & jnp.any(active)

    init = (
        jnp.int32(0),
        mask,
        jnp.full(n, -1, dtype=jnp.int32),
        occupied0,
        slot_bits0,
    )
    _, active, row_slot, occupied, slot_bits = jax.lax.while_loop(
        round_cond, round_body, init
    )
    probe_failed = jnp.any(active)

    occ = occupied[:size]
    rank = blocked_cumsum(occ.astype(jnp.int32)) - 1  # [size]
    n_occupied = jnp.sum(occ.astype(jnp.int32))
    n_groups = jnp.where(
        probe_failed, jnp.int32(max_groups + 1), n_occupied
    )
    # Row ids: rank of the row's slot, clamped into [0, G) for valid rows
    # (overflowing ranks land in the last slot, like the sort path);
    # invalid/unresolved rows get the trash slot G.
    resolved = mask & (row_slot >= 0)
    raw_gid = rank[jnp.clip(row_slot, 0, size - 1)]
    gids = jnp.where(
        resolved, jnp.clip(raw_gid, 0, max_groups - 1), max_groups
    ).astype(jnp.int32)

    # Dense [G] key values from occupied slots (rank < G).
    dense_idx = jnp.where(occ & (rank < max_groups), rank, max_groups)
    group_keys = []
    for sb, p in zip(slot_bits, key_planes):
        dense = (
            jnp.zeros(max_groups + 1, dtype=sb.dtype)
            .at[dense_idx]
            .set(sb[:size])[:max_groups]
        )
        group_keys.append(_from_bits(dense, p.dtype))
    group_valid = jnp.arange(max_groups, dtype=jnp.int32) < jnp.minimum(
        n_occupied, max_groups
    )
    return gids, group_keys, group_valid, n_groups


def scatter_rows(arr, ids, valid, capacity: int, fill):
    """Scatter [n]-leading arr rows to slots ``ids`` (unique among valid)."""
    pad_shape = (capacity + 1,) + arr.shape[1:]
    out = jnp.full(pad_shape, fill, dtype=arr.dtype)
    out = out.at[jnp.where(valid, ids, capacity)].set(arr)
    return out[:capacity]


def regroup_pair(keys_a, valid_a, keys_b, valid_b, max_groups: int):
    """Compute a shared dense-id space for two [G]-slot group states.

    Returns (ids_a, ids_b, merged_keys, merged_valid, n_groups): slot i of
    side A maps to merged slot ids_a[i], likewise for B; merged_keys/valid
    describe the union. Carries are then aligned with ``scatter_rows`` /
    ``scatter_carry`` and combined with the UDA's associative merge
    (merge(init, x) == x makes empty slots neutral).
    """
    cat_keys = [jnp.concatenate([a, b]) for a, b in zip(keys_a, keys_b)]
    cat_valid = jnp.concatenate([valid_a, valid_b])
    ids, merged_keys, merged_valid, n_groups = dense_group_ids(
        cat_keys, cat_valid, max_groups
    )
    g = valid_a.shape[0]
    return ids[:g], ids[g:], merged_keys, merged_valid, n_groups


def scatter_carry(carry, ids, valid, capacity: int, init_carry):
    """Align a [G]-leading carry pytree onto merged slots (empty = init)."""
    return jax.tree_util.tree_map(
        lambda arr, init: jnp.concatenate(
            [init, jnp.zeros((1,) + arr.shape[1:], arr.dtype)]
        )
        .at[jnp.where(valid, ids, capacity)]
        .set(arr)[:capacity],
        carry,
        init_carry,
    )
