"""Group-by machinery: exact dense group ids with static shapes.

Reference parity: Carnot's BlockingAggNode builds an absl flat_hash_map
keyed by RowTuple (``src/carnot/exec/agg_node.h:66``,
``src/carnot/exec/row_tuple.h``). Hash maps are hostile to XLA, so groups
are found by **multi-key lexicographic sort + first-occurrence cumsum**:
exact (no hash collisions), fully static shapes, and the sort is the same
machinery the t-digest uses.

Two layers:

- ``dense_group_ids``: rows -> dense ids in [0, max_groups), plus the
  per-group key values and an overflow indicator (distinct groups beyond
  the static capacity are clamped into the last slot and flagged).
- ``scatter_group_state`` / regroup: align two group states (different
  slot orders, e.g. accumulated-state x new-window, or per-device
  partials) onto a shared dense id space so UDA carries can be merged
  slot-wise. This is the TPU replacement for Carnot's
  partial-agg-serialize -> GRPC -> finalize-agg pipeline
  (``planner/distributed/splitter/partial_op_mgr``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _sortable(plane):
    """Map a key plane to a sortable array (bools -> int8)."""
    if plane.dtype == jnp.bool_:
        return plane.astype(jnp.int8)
    return plane


def dense_group_ids(key_planes, mask, max_groups: int):
    """Assign dense group ids by multi-key sort.

    Args:
      key_planes: list of [n] arrays (a UINT128 key contributes two).
      mask: [n] bool; masked rows get id ``max_groups`` (trash slot).
      max_groups: static group capacity G.

    Returns:
      gids: int32[n] in [0, G) for valid rows, G for invalid.
      group_keys: list of [G] arrays — key values per dense id.
      group_valid: bool[G] — slots actually occupied.
      n_groups: int32 scalar — true distinct count (may exceed G; caller
        checks ``n_groups > max_groups`` to detect overflow).
    """
    n = mask.shape[0]
    planes = [_sortable(p) for p in key_planes]

    # Lexicographic stable sort: secondary keys first, primary last, with
    # invalid rows forced to the end via a final sort on ~mask.
    order = jnp.arange(n, dtype=jnp.int32)
    for p in reversed(planes):
        order = order[jnp.argsort(p[order], stable=True)]
    order = order[jnp.argsort(~mask[order], stable=True)]

    sorted_mask = mask[order]
    is_new = jnp.zeros(n, dtype=jnp.bool_)
    for p in planes:
        sp = p[order]
        diff = jnp.concatenate([jnp.ones(1, jnp.bool_), sp[1:] != sp[:-1]])
        is_new = is_new | diff
    is_new = is_new & sorted_mask

    sorted_gid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    n_groups = jnp.sum(is_new.astype(jnp.int32))
    # Clamp overflowing groups into the last slot; invalid rows -> G.
    sorted_gid_c = jnp.where(
        sorted_mask, jnp.clip(sorted_gid, 0, max_groups - 1), max_groups
    )
    gids = jnp.zeros(n, dtype=jnp.int32).at[order].set(sorted_gid_c)

    # First occurrence (in original row order) of each group -> key values.
    first_idx = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32), gids, num_segments=max_groups + 1
    )[:-1]
    group_valid = first_idx < n
    safe_idx = jnp.where(group_valid, first_idx, 0)
    group_keys = [p[safe_idx] for p in key_planes]
    return gids, group_keys, group_valid, n_groups


def scatter_rows(arr, ids, valid, capacity: int, fill):
    """Scatter [n]-leading arr rows to slots ``ids`` (unique among valid)."""
    pad_shape = (capacity + 1,) + arr.shape[1:]
    out = jnp.full(pad_shape, fill, dtype=arr.dtype)
    out = out.at[jnp.where(valid, ids, capacity)].set(arr)
    return out[:capacity]


def regroup_pair(keys_a, valid_a, keys_b, valid_b, max_groups: int):
    """Compute a shared dense-id space for two [G]-slot group states.

    Returns (ids_a, ids_b, merged_keys, merged_valid, n_groups): slot i of
    side A maps to merged slot ids_a[i], likewise for B; merged_keys/valid
    describe the union. Carries are then aligned with ``scatter_rows`` /
    ``scatter_carry`` and combined with the UDA's associative merge
    (merge(init, x) == x makes empty slots neutral).
    """
    cat_keys = [jnp.concatenate([a, b]) for a, b in zip(keys_a, keys_b)]
    cat_valid = jnp.concatenate([valid_a, valid_b])
    ids, merged_keys, merged_valid, n_groups = dense_group_ids(
        cat_keys, cat_valid, max_groups
    )
    g = valid_a.shape[0]
    return ids[:g], ids[g:], merged_keys, merged_valid, n_groups


def scatter_carry(carry, ids, valid, capacity: int, init_carry):
    """Align a [G]-leading carry pytree onto merged slots (empty = init)."""
    return jax.tree_util.tree_map(
        lambda arr, init: jnp.concatenate(
            [init, jnp.zeros((1,) + arr.shape[1:], arr.dtype)]
        )
        .at[jnp.where(valid, ids, capacity)]
        .set(arr)[:capacity],
        carry,
        init_carry,
    )
