"""TPU-shaped prefix sums.

XLA:TPU lowers a 1-D 64-bit ``cumsum`` to a variadic reduce-window over
(hi, lo) u32 pairs and stages the ENTIRE operand in scoped vmem — at
multi-million-row windows that is a guaranteed compile failure
("Scoped allocation ... exceeded scoped vmem limit", seen at 64 MiB vs
the 16 MiB cap). The classic two-level blocked scan sidesteps it:
chunk-local cumsums tile over the major axis (each row is one vmem-
resident lane), and only the tiny chunk-totals vector takes the scalar
scan. Integer wraparound keeps every step exact, so the blocked form is
bit-identical to the flat one.

Reference parity: this replaces the per-group accumulation loops of
``src/carnot/exec/agg_node.cc`` (value-wise adds into hash-table slots)
for the sorted-segment reduction strategy documented in
``udf/builtins/math_ops.py``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

#: Chunk width: one (rows, _CHUNK) i64 row = 64 KiB, comfortably inside
#: a vmem tile; reduce-window then scans the minor axis per-row.
_CHUNK = 8192

#: Flat cumsum below this operand size compiles fine (the scoped-vmem
#: cap is 16 MiB; stay well under) and avoids the reshape/pad
#: round-trip. In elements: 1M for i64/f64, 2M for i32.
_FLAT_MAX_BYTES = 1 << 23
#: Back-compat alias used by tests: the i64 flat-path element bound.
_FLAT_MAX = _FLAT_MAX_BYTES // 8


def _needs_blocking(x, force: bool) -> bool:
    """Size-based, backend-independent: the blocked form is used above
    the threshold on EVERY backend (CPU pays only a cheap reshape, and
    lowering-target-vs-default-backend mismatches can't reintroduce the
    TPU compile failure). ``force=True`` picks the blocked path at any
    size — the tests' hook for exercising it on small inputs."""
    if force:
        return True
    (n,) = x.shape
    return n * np.dtype(x.dtype).itemsize > _FLAT_MAX_BYTES


def blocked_cumsum(x: jnp.ndarray, force: bool = False) -> jnp.ndarray:
    """Inclusive 1-D cumsum, exact for integers, safe to compile on TPU
    at any length. Equals ``jnp.cumsum(x)`` elementwise for integer
    dtypes (wraparound included) on every backend; float association
    order depends on which path the size threshold selects, so floats
    should not rely on bit-reproducibility across sizes."""
    (n,) = x.shape
    if not _needs_blocking(x, force):
        return jnp.cumsum(x)
    c = -(-n // _CHUNK)
    pad = c * _CHUNK - n
    x2 = jnp.pad(x, (0, pad)).reshape(c, _CHUNK)
    within = jnp.cumsum(x2, axis=1)
    # Exclusive prefix of the chunk totals: a length-c scan (c = n/8192),
    # small enough for the flat lowering.
    totals = within[:, -1]
    prefix = jnp.concatenate(
        [jnp.zeros(1, x.dtype), jnp.cumsum(totals)[:-1]]
    )
    return (within + prefix[:, None]).reshape(-1)[:n]


def blocked_cummax(x: jnp.ndarray, force: bool = False) -> jnp.ndarray:
    """Inclusive 1-D cumulative max with the same blocked structure as
    :func:`blocked_cumsum` (``lax.cummax`` has the identical scoped-vmem
    reduce-window lowering on TPU)."""
    import jax

    if not _needs_blocking(x, force):
        return jax.lax.cummax(x)
    (n,) = x.shape
    if x.dtype == jnp.bool_:
        lowest = False  # cumulative OR: False is the identity
    elif jnp.issubdtype(x.dtype, jnp.integer):
        lowest = np.iinfo(np.dtype(x.dtype)).min
    else:
        lowest = -jnp.inf
    c = -(-n // _CHUNK)
    pad = c * _CHUNK - n
    x2 = jnp.pad(x, (0, pad), constant_values=lowest).reshape(c, _CHUNK)
    within = jax.lax.cummax(x2, axis=1)
    totals = within[:, -1]
    prefix = jnp.concatenate(
        [jnp.full(1, lowest, x.dtype), jax.lax.cummax(totals)[:-1]]
    )
    return jnp.maximum(within, prefix[:, None]).reshape(-1)[:n]
