"""Device-side N:M equijoin with static shapes.

Reference parity: ``src/carnot/exec/equijoin_node.{h,cc}`` — build+probe
hash join supporting inner/left/right/outer with N:M fan-out and chunked
output. Hash maps are hostile to XLA, so the TPU design is sort-based,
reusing the group-by machinery (``pixie_tpu.ops.groupby``):

1. Both sides' key planes are mapped to one exact dense key-id space by
   ``dense_group_ids`` over the concatenated rows (multi-key sort — no
   hash collisions, static shapes).
2. The build side is sorted by key id; ``searchsorted`` gives each probe
   row its contiguous match range [lo, hi).
3. Match ranges expand into a fixed-capacity output via exclusive prefix
   sums + a scatter/cummax ownership scan; rows beyond ``capacity`` are
   dropped and flagged (``overflow=True``) so the caller can re-run with
   a doubled capacity — the static-shape analog of Carnot's growing
   output chunks.

The kernel returns gather indices + take-masks, not materialized columns:
(probe_idx, probe_take, build_idx, build_take, out_valid, overflow).
Unmatched sides emit take=False, which callers turn into nulls. Where a
take-mask is False the paired index is arbitrary but always in-bounds,
so unconditional gathers stay safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .groupby import dense_group_ids, dense_group_ids_hash
from .hashtable import _mix64, _mix64_j
from .scan import blocked_cumsum


def _exclusive_cumsum(x):
    """(exclusive cumsum, total) for an int32 vector. Blocked so probe-
    length scans compile on TPU (flat cumsum overflows scoped vmem at
    multi-million rows — see ops/scan.py)."""
    c = blocked_cumsum(x)
    return jnp.concatenate([jnp.zeros(1, x.dtype), c[:-1]]), c[-1]


def _cummax(x):
    """Inclusive cumulative max (associative scan -> O(log n) on device)."""
    return jax.lax.associative_scan(jnp.maximum, x)


def _owners(slot_of, emitting, count, capacity):
    """Per-output-slot owner row (1-based; 0 = no owner yet).

    Scatter (row+1) at each emitting row's start slot, then cummax: every
    slot inherits the nearest preceding start's row. Emitting rows have
    strictly increasing starts, so scatters never collide.
    """
    marker = (
        jnp.zeros(capacity + 1, dtype=jnp.int32)
        .at[slot_of]
        .max(jnp.arange(1, count + 1, dtype=jnp.int32) * emitting)[:capacity]
    )
    return _cummax(marker)


def probe_sorted_join(
    sorted_build_keys,
    n_build,
    probe_keys,
    probe_valid,
    capacity: int,
    how: str = "inner",
):
    """Probe one window against a PRE-SORTED device-resident build side.

    The multi-window join driver (``exec/joins.py``) packs both sides'
    keys into one comparable int64 id space on host, sorts the build ids
    ONCE, and stages them on device once per query; each probe window
    then runs only the searchsorted + expansion half of ``device_join``
    — no per-window dense-id pass, no per-window build sort, and no
    per-window build transfer.

    Args:
      sorted_build_keys: int64[B]; entries [0, n_build) ascending, the
        rest padded with int64 max (never matched — ranges clamp to
        ``n_build``).
      n_build: traced int32 count of real build rows.
      probe_keys / probe_valid: int64[N] ids + bool[N] mask for this
        probe window.
      capacity: static output row capacity C.
      how: 'inner' | 'left' (windowable joins only: each probe row's
        output is independent of every other window's; right/outer need
        global unmatched-build knowledge and stay single-shot).

    Returns the same (probe_idx, probe_take, build_idx, build_take,
    out_valid, overflow) contract as ``device_join``, with ``build_idx``
    indexing the SORTED build order (the driver maps back through its
    host-side sort permutation).
    """
    if how not in ("inner", "left"):
        raise ValueError(f"probe_sorted_join supports inner/left, not {how!r}")
    nb = jnp.asarray(n_build, dtype=jnp.int32)
    lo = jnp.minimum(
        jnp.searchsorted(sorted_build_keys, probe_keys, side="left"), nb
    ).astype(jnp.int32)
    hi = jnp.minimum(
        jnp.searchsorted(sorted_build_keys, probe_keys, side="right"), nb
    ).astype(jnp.int32)
    return _expand_ranges(
        lo, hi, probe_valid, capacity, how, sorted_build_keys.shape[0]
    )


def _expand_ranges(lo, hi, probe_valid, capacity: int, how: str, b: int):
    """Expand per-probe match ranges [lo, hi) into the fixed-capacity
    (probe_idx, probe_take, build_idx, build_take, out_valid, overflow)
    output — the shared back half of every probe-side kernel."""
    n = probe_valid.shape[0]
    c = capacity
    m = jnp.where(probe_valid, hi - lo, 0).astype(jnp.int32)

    e = jnp.maximum(m, 1) if how == "left" else m
    e = jnp.where(probe_valid, e, 0).astype(jnp.int32)
    start, _ = _exclusive_cumsum(e)
    # Overflow detection in 64-bit: a window with > 2^31 total pairs
    # wraps the int32 prefix sums, which would otherwise read as "fits"
    # and silently drop the window. The int32 slot math stays exact in
    # every non-overflow case (total <= capacity << 2^31); on overflow
    # the caller discards this output and retries doubled anyway.
    total_pairs = jnp.sum(e.astype(jnp.int64))

    slot_of = jnp.where((e > 0) & (start < c), start, c)
    owner1 = _owners(slot_of, (e > 0).astype(jnp.int32), n, c)
    probe_idx = jnp.maximum(owner1 - 1, 0)

    j = jnp.arange(c, dtype=jnp.int32)
    t = j - start[probe_idx]
    pair_valid = (j < total_pairs) & (owner1 > 0)
    is_match = t < m[probe_idx]
    build_idx = jnp.clip(
        lo[probe_idx] + jnp.minimum(t, m[probe_idx] - 1), 0, b - 1
    )
    return (
        probe_idx, pair_valid, build_idx, pair_valid & is_match,
        pair_valid, total_pairs > c,
    )


# -- radix-partitioned probe -------------------------------------------------
def radix_partition_build(keys: np.ndarray, radix_bits: int):
    """Host-side build partitioning for ``radix_probe_join``.

    Hashes the packed int64 build keys with the splitmix64 mixer
    (``ops/hashtable._mix64``) and sorts them by (top ``radix_bits`` of
    the hash, key). Within a partition keys are ascending, so a probe
    row binary-searches ONE partition instead of the whole build side —
    log2(B/P) memory touches per probe instead of log2(B), against a
    partition-sized working set.

    Returns (order, part_starts, search_steps):
      order        int64[B] — build-row permutation (sorted position ->
                   original row), the analog of the sorted driver's
                   ``np.argsort``.
      part_starts  int32[P+1] — partition offsets into the sorted keys
                   (real rows only; padding stays outside every range).
      search_steps static trip count for the kernel's bounded binary
                   search: enough for the LARGEST partition, bucketed up
                   so one compiled program serves similar builds.
    """
    p = 1 << radix_bits
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    h = _mix64(keys.view(np.uint64))
    part = (h >> np.uint64(64 - radix_bits)).astype(np.int64)
    order = np.lexsort((keys, part)).astype(np.int64)
    counts = np.bincount(part, minlength=p)
    part_starts = np.zeros(p + 1, dtype=np.int64)
    np.cumsum(counts, out=part_starts[1:])
    # +1 step of slack over ceil(log2(max+1)): the branchless search
    # no-ops once converged, so slack costs one gather, never wrongness.
    steps = max(4, int(np.ceil(np.log2(int(counts.max()) + 2))) + 1)
    return order, part_starts.astype(np.int32), steps


def _bounded_searchsorted(a, keys, lo0, hi0, steps: int, side: str):
    """Per-row binary search of ``keys`` into ``a`` restricted to
    [lo0, hi0), with a STATIC trip count (extra steps no-op once
    lo == hi — static shapes, no data-dependent control flow)."""
    lo, hi = lo0, hi0
    top = a.shape[0] - 1
    for _ in range(steps):
        mid = (lo + hi) >> 1
        v = a[jnp.clip(mid, 0, top)]
        go = (v < keys) if side == "left" else (v <= keys)
        upd = lo < hi
        lo = jnp.where(upd & go, mid + 1, lo)
        hi = jnp.where(upd & ~go, mid, hi)
    return lo


def radix_probe_join(
    sorted_build_keys,
    part_starts,
    probe_keys,
    probe_valid,
    capacity: int,
    how: str = "inner",
    radix_bits: int = 8,
    search_steps: int = 24,
):
    """Probe one window against a radix-partitioned device build side.

    The driver partitions the build side ONCE per query with
    ``radix_partition_build`` and stages ``sorted_build_keys`` (int64[B],
    padding = int64 max past the real rows) + ``part_starts`` (int32[P+1])
    on device; each probe window hashes its keys with the same splitmix64
    mixer, reads its partition's [start, end) range, and binary-searches
    only that partition. Same output contract and ``how`` support
    (inner/left) as ``probe_sorted_join``.
    """
    if how not in ("inner", "left"):
        raise ValueError(f"radix_probe_join supports inner/left, not {how!r}")
    h = _mix64_j(probe_keys.astype(jnp.uint64))
    p = (h >> jnp.uint64(64 - radix_bits)).astype(jnp.int32)
    lo0 = part_starts[p]
    hi0 = part_starts[p + 1]
    lo = _bounded_searchsorted(
        sorted_build_keys, probe_keys, lo0, hi0, search_steps, "left"
    )
    hi = _bounded_searchsorted(
        sorted_build_keys, probe_keys, lo0, hi0, search_steps, "right"
    )
    return _expand_ranges(
        lo, hi, probe_valid, capacity, how, sorted_build_keys.shape[0]
    )


def device_join(
    build_keys,
    build_valid,
    probe_keys,
    probe_valid,
    capacity: int,
    how: str = "inner",
):
    """Join probe (left) rows against build (right) rows on equal keys.

    Args:
      build_keys / probe_keys: lists of [B] / [N] key planes (same plane
        count and dtypes per position; a UINT128 key contributes two).
        Both sides must be non-empty arrays (mask rows invalid instead).
      build_valid / probe_valid: bool masks.
      capacity: static output row capacity C.
      how: 'inner' | 'left' | 'right' | 'outer'.

    Returns:
      probe_idx int32[C], probe_take bool[C]  — left-side gather/null
      build_idx int32[C], build_take bool[C]  — right-side gather/null
      out_valid bool[C], overflow bool[]      — occupancy + truncation
    """
    if how not in ("inner", "left", "right", "outer"):
        raise ValueError(f"unsupported join how={how!r}")
    b = build_valid.shape[0]
    n = probe_valid.shape[0]
    c = capacity
    if b == 0 or n == 0:
        raise ValueError("device_join sides must be non-empty (mask instead)")

    # 1. Shared exact key-id space. Invalid rows get id b+n from the
    # group machinery; split that trash id per side so invalid build and
    # invalid probe rows can never match each other. The bounded-probe
    # hash table is O(rounds * (b+n)) elementwise vs the multi-plane
    # stable sort's O((b+n) log(b+n)) — at 10M-row joins that sort was
    # the kernel's hot spot. Distinct keys <= b+n by construction, so a
    # reported overflow can only mean probe exhaustion (pathological
    # clustering); lax.cond falls back to the exact sort path then.
    cat_keys = [jnp.concatenate([bk, pk]) for bk, pk in zip(build_keys, probe_keys)]
    cat_valid = jnp.concatenate([build_valid, probe_valid])
    ids_h, _, _, ng_h = dense_group_ids_hash(cat_keys, cat_valid, b + n)
    ids = jax.lax.cond(
        ng_h > b + n,
        lambda: dense_group_ids(cat_keys, cat_valid, b + n)[0],
        lambda: ids_h,
    )
    kb = jnp.where(build_valid, ids[:b], b + n)
    kp = jnp.where(probe_valid, ids[b:], b + n + 1)

    # 2. Sort build by key id; per-probe match ranges.
    perm = jnp.argsort(kb, stable=True).astype(jnp.int32)  # invalid last
    skb = kb[perm]
    lo = jnp.searchsorted(skb, kp, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(skb, kp, side="right").astype(jnp.int32)
    m = hi - lo  # matches per probe row (0 for invalid probe rows)

    # 3. Expansion: emitted rows per probe row.
    pad_unmatched = how in ("left", "outer")
    e = jnp.maximum(m, 1) if pad_unmatched else m
    e = jnp.where(probe_valid, e, 0).astype(jnp.int32)
    start, total_pairs = _exclusive_cumsum(e)

    slot_of = jnp.where((e > 0) & (start < c), start, c)
    owner1 = _owners(slot_of, (e > 0).astype(jnp.int32), n, c)
    probe_idx = jnp.maximum(owner1 - 1, 0)

    j = jnp.arange(c, dtype=jnp.int32)
    t = j - start[probe_idx]
    pair_valid = (j < total_pairs) & (owner1 > 0)
    is_match = t < m[probe_idx]
    build_idx = perm[
        jnp.clip(lo[probe_idx] + jnp.minimum(t, m[probe_idx] - 1), 0, b - 1)
    ]

    probe_take = pair_valid
    build_take = pair_valid & is_match
    out_valid = pair_valid
    overflow = total_pairs > c

    if how in ("right", "outer"):
        # Build rows whose key matches no probe row emit once with a null
        # left side, appended after the pair region.
        skp = jnp.sort(kp)
        lo_b = jnp.searchsorted(skp, kb, side="left")
        hi_b = jnp.searchsorted(skp, kb, side="right")
        unmatched = build_valid & ((hi_b - lo_b) == 0)
        su, n_extra = _exclusive_cumsum(unmatched.astype(jnp.int32))
        extra_slot = jnp.where(
            unmatched & (total_pairs + su < c), total_pairs + su, c
        )
        extra_owner = _owners(extra_slot, unmatched.astype(jnp.int32), b, c)
        # The extras region starts at total_pairs; inside it the pair
        # machinery's owner is stale, so extras override.
        in_extras = (j >= total_pairs) & (extra_owner > 0)
        build_idx = jnp.where(in_extras, jnp.maximum(extra_owner - 1, 0), build_idx)
        build_take = jnp.where(in_extras, True, build_take)
        probe_take = probe_take & ~in_extras
        out_valid = out_valid | (in_extras & (j < total_pairs + n_extra))
        overflow = overflow | (total_pairs + n_extra > c)

    return probe_idx, probe_take, build_idx, build_take, out_valid, overflow
