"""Batched t-digest quantile sketch over [num_groups, K] centroid arrays.

Reference parity: ``src/carnot/funcs/builtins/math_sketches.h:34``
(QuantilesUDA wrapping the sequential-insertion tdigest library).

TPU-first redesign: sequential insertion is hostile to XLA, so digests are
built by **sorted quantile-binning** — a whole batch of values is sorted
within each group, each value's within-group quantile position is mapped
through the t-digest k1 scale function k(q) = asin(2q-1) to one of K bins,
and bins are reduced with segment sums. Merging two digests (the partial-agg
path across devices) concatenates centroid sets and re-compresses with the
same binning. Everything is static-shape: [G groups, K centroids].

The carry is (means f32[G,K], weights f32[G,K]) — a pytree, trivially
shippable through shard_map/psum-style collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DEFAULT_K = 128
_BIG = jnp.inf


def _knorm(q):
    """t-digest k1 scale normalized to [0, 1): concentrates bins at tails."""
    q = jnp.clip(q, 0.0, 1.0)
    return (jnp.arcsin(2.0 * q - 1.0) / jnp.pi) + 0.5


def digest_init(num_groups: int, k: int = DEFAULT_K):
    return (
        jnp.zeros((num_groups, k), dtype=jnp.float32),
        jnp.zeros((num_groups, k), dtype=jnp.float32),
    )


def _compress(means, weights, k: int):
    """Re-bin [G, M] centroids to [G, k] by cumulative-weight position."""
    g, m = means.shape
    # Sort centroids by mean within each group; empty slots (w==0) last.
    sort_key = jnp.where(weights > 0, means, _BIG)
    order = jnp.argsort(sort_key, axis=-1, stable=True)
    means_s = jnp.take_along_axis(means, order, axis=-1)
    weights_s = jnp.take_along_axis(weights, order, axis=-1)

    total = jnp.sum(weights_s, axis=-1, keepdims=True)
    cumw = jnp.cumsum(weights_s, axis=-1)
    qmid = jnp.where(total > 0, (cumw - weights_s * 0.5) / total, 0.0)
    bins = jnp.clip(jnp.floor(_knorm(qmid) * k).astype(jnp.int32), 0, k - 1)

    gid = jnp.broadcast_to(jnp.arange(g, dtype=jnp.int32)[:, None], (g, m))
    flat = jnp.where(weights_s > 0, gid * k + bins, g * k).reshape(-1)
    w_flat = weights_s.reshape(-1)
    mw_flat = (means_s * weights_s).reshape(-1)

    new_w = jax.ops.segment_sum(w_flat, flat, num_segments=g * k + 1)[:-1]
    new_mw = jax.ops.segment_sum(mw_flat, flat, num_segments=g * k + 1)[:-1]
    new_w = new_w.reshape(g, k)
    new_means = jnp.where(new_w > 0, new_mw.reshape(g, k) / jnp.maximum(new_w, 1e-30), 0.0)
    return new_means, new_w


def digest_merge(a, b):
    """Associative merge of two [G, K] digests (cross-device finalize path)."""
    means = jnp.concatenate([a[0], b[0]], axis=-1)
    weights = jnp.concatenate([a[1], b[1]], axis=-1)
    return _compress(means, weights, a[0].shape[-1])


def batch_to_digest(values, group_ids, mask, num_groups: int, k: int = DEFAULT_K):
    """Build a [G, K] digest from one batch of (value, group) rows."""
    n = values.shape[0]
    values = values.astype(jnp.float32)
    gids = jnp.where(mask, group_ids.astype(jnp.int32), num_groups)
    vals_m = jnp.where(mask, values, _BIG)

    # Rows sorted by (group, value) with ONE sort: pack gid and the
    # monotone bit-view of the f32 value into a u64 key (IEEE-754 floats
    # order by their bits after the standard sign-flip transform), so the
    # digest costs one argsort instead of two stable ones — sorts are the
    # dominant cost of the sketch on both backends.
    vb = jax.lax.bitcast_convert_type(vals_m, jnp.uint32)
    vb = jnp.where(
        vals_m < 0, ~vb, vb | jnp.uint32(0x80000000)
    )
    key = (gids.astype(jnp.uint64) << jnp.uint64(32)) | vb.astype(jnp.uint64)
    if jax.default_backend() == "cpu":
        # XLA's CPU sort is ~4x slower than numpy's radix-ish argsort;
        # a host callback is free on the CPU backend (same memory space).
        import numpy as _np

        order = jax.pure_callback(
            lambda k: _np.argsort(k, kind="stable").astype(_np.int32),
            jax.ShapeDtypeStruct(key.shape, jnp.int32),
            key,
            vmap_method="sequential",
        )
    else:
        order = jnp.argsort(key).astype(jnp.int32)
    s_gid = gids[order]
    s_val = values[order]
    s_mask = mask[order]

    ones = mask.astype(jnp.float32)
    counts = jax.ops.segment_sum(ones, gids, num_segments=num_groups + 1)
    starts = jnp.concatenate([jnp.zeros(1, counts.dtype), jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(n, dtype=jnp.float32) - starts[s_gid]
    group_n = jnp.maximum(counts[s_gid], 1.0)
    q = (rank + 0.5) / group_n
    bins = jnp.clip(jnp.floor(_knorm(q) * k).astype(jnp.int32), 0, k - 1)

    flat = jnp.where(s_mask & (s_gid < num_groups), s_gid * k + bins, num_groups * k)
    w_flat = s_mask.astype(jnp.float32)
    w = jax.ops.segment_sum(w_flat, flat, num_segments=num_groups * k + 1)[:-1]
    mw = jax.ops.segment_sum(
        jnp.where(s_mask, s_val, 0.0), flat, num_segments=num_groups * k + 1
    )[:-1]
    w = w.reshape(num_groups, k)
    means = jnp.where(w > 0, mw.reshape(num_groups, k) / jnp.maximum(w, 1e-30), 0.0)
    return means, w


def digest_update(carry, group_ids, mask, values, *, num_groups: int | None = None):
    """UDA update: fold a batch into the digest carry."""
    g, k = carry[0].shape
    fresh = batch_to_digest(values, group_ids, mask, g if num_groups is None else num_groups, k)
    return digest_merge(carry, fresh)


def digest_quantile(carry, qs):
    """Estimate quantiles per group: [G, len(qs)] (NaN for empty groups).

    Linear interpolation of centroid means over cumulative-weight midpoints
    (the standard t-digest estimator).
    """
    means, weights = carry
    qs_arr = jnp.asarray(qs, dtype=jnp.float32)

    sort_key = jnp.where(weights > 0, means, _BIG)
    order = jnp.argsort(sort_key, axis=-1, stable=True)
    means_s = jnp.take_along_axis(means, order, axis=-1)
    weights_s = jnp.take_along_axis(weights, order, axis=-1)

    total = jnp.sum(weights_s, axis=-1)
    cumw = jnp.cumsum(weights_s, axis=-1)
    cmid = cumw - weights_s * 0.5

    # Fill empty (w==0, sorted to the end) slots so interp clamps to the
    # last real centroid instead of walking into garbage.
    filled_mean = jax.lax.cummax(jnp.where(weights_s > 0, means_s, -_BIG), axis=1)
    filled_cmid = jnp.where(weights_s > 0, cmid, total[:, None])

    def one_group(m, c, t):
        return jnp.interp(qs_arr * t, c, m)

    out = jax.vmap(one_group)(filled_mean, filled_cmid, total)
    return jnp.where(total[:, None] > 0, out, jnp.nan)
