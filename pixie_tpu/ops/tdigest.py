"""Batched t-digest quantile sketch over [num_groups, K] centroid arrays.

Reference parity: ``src/carnot/funcs/builtins/math_sketches.h:34``
(QuantilesUDA wrapping the sequential-insertion tdigest library).

TPU-first redesign: sequential insertion is hostile to XLA, and even
whole-batch sorting is the wrong primitive on both XLA backends (TPU sort
programs compile slowly and run sort-bound; XLA CPU sort is ~90x slower
than its scatter). Each batch is instead **histogram-binned by value**:
the f32 value's IEEE-754 bit pattern is made order-monotone (standard
sign-flip transform) and its top bits index one of B log-spaced bins per
group — a pure scatter-add, no sort, no data-dependent control flow. Bin
(weight, weighted-mean) pairs are already value-ordered, so re-binning the
histogram through the t-digest k1 scale function k(q) = asin(2q-1) down to
K centroids is cumsum + segment-sum only. Merging two digests (the
partial-agg path across devices) concatenates centroid sets and
re-compresses with one tiny [G, 2K] sort. Everything is static-shape.

The carry is (means f32[G,K], weights f32[G,K]) — a pytree, trivially
shippable through shard_map/psum-style collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DEFAULT_K = 128
_BIG = jnp.inf


def _knorm(q):
    """t-digest k1 scale normalized to [0, 1): concentrates bins at tails."""
    q = jnp.clip(q, 0.0, 1.0)
    return (jnp.arcsin(2.0 * q - 1.0) / jnp.pi) + 0.5


def digest_init(num_groups: int, k: int = DEFAULT_K):
    return (
        jnp.zeros((num_groups, k), dtype=jnp.float32),
        jnp.zeros((num_groups, k), dtype=jnp.float32),
    )


def _compress(means, weights, k: int, ordered: bool = False):
    """Re-bin [G, M] centroids to [G, k] by cumulative-weight position.

    ``ordered=True`` asserts the centroids are already ascending by mean
    within each group (histogram bins are, by construction) and skips the
    sort — empty (w==0) slots may then be interleaved; they carry no
    weight, land in the trash segment, and don't perturb ``cumw``.
    """
    g, m = means.shape
    if ordered:
        means_s, weights_s = means, weights
    else:
        # Sort centroids by mean within each group; empty slots last.
        sort_key = jnp.where(weights > 0, means, _BIG)
        order = jnp.argsort(sort_key, axis=-1, stable=True)
        means_s = jnp.take_along_axis(means, order, axis=-1)
        weights_s = jnp.take_along_axis(weights, order, axis=-1)

    total = jnp.sum(weights_s, axis=-1, keepdims=True)
    cumw = jnp.cumsum(weights_s, axis=-1)
    qmid = jnp.where(total > 0, (cumw - weights_s * 0.5) / total, 0.0)
    bins = jnp.clip(jnp.floor(_knorm(qmid) * k).astype(jnp.int32), 0, k - 1)

    gid = jnp.broadcast_to(jnp.arange(g, dtype=jnp.int32)[:, None], (g, m))
    flat = jnp.where(weights_s > 0, gid * k + bins, g * k).reshape(-1)
    w_flat = weights_s.reshape(-1)
    mw_flat = (means_s * weights_s).reshape(-1)

    new_w = jax.ops.segment_sum(w_flat, flat, num_segments=g * k + 1)[:-1]
    new_mw = jax.ops.segment_sum(mw_flat, flat, num_segments=g * k + 1)[:-1]
    new_w = new_w.reshape(g, k)
    new_means = jnp.where(new_w > 0, new_mw.reshape(g, k) / jnp.maximum(new_w, 1e-30), 0.0)
    return new_means, new_w


def digest_merge(a, b):
    """Associative merge of two [G, K] digests (cross-device finalize path)."""
    means = jnp.concatenate([a[0], b[0]], axis=-1)
    weights = jnp.concatenate([a[1], b[1]], axis=-1)
    return _compress(means, weights, a[0].shape[-1])


def _hist_bins(num_groups: int) -> int:
    """Histogram width B: as fine as a [G, B] f32 scratch budget allows.

    B=8192 gives positive values 4 mantissa bits of resolution (bins are
    ~4.4% wide in value; the within-bin weighted mean recovers most of
    that). Large-G aggregates shrink B toward a floor of K=128 so G*B
    stays near 2^25 slots — past G=2^18 the scratch tracks the [G, K]
    digest carry's own footprint (2 arrays of the same shape), which is
    the dominant allocation at that scale with or without the histogram.
    """
    b = 8192
    while b > DEFAULT_K and num_groups * b > (1 << 25):
        b //= 2
    return b


def batch_to_digest(values, group_ids, mask, num_groups: int, k: int = DEFAULT_K):
    """Build a [G, K] digest from one batch of (value, group) rows.

    Sort-free: values land in B log-spaced histogram bins per group via
    their order-monotone f32 bit pattern (one scatter-add), and the
    value-ordered histogram is k1-rebinned to K centroids with
    cumsum + segment-sum (``_compress(ordered=True)``).
    """
    values = values.astype(jnp.float32)
    # The sketch is defined over FINITE values on both fold paths: a NaN
    # would poison the Pallas contraction across all bins, and ±inf has
    # no meaningful quantile position either way.
    mask = mask & jnp.isfinite(values)
    gids = jnp.where(mask, group_ids.astype(jnp.int32), num_groups)
    b = _hist_bins(num_groups)
    shift = jnp.uint32(32 - b.bit_length() + 1)  # top log2(B) bits

    vb = jax.lax.bitcast_convert_type(values, jnp.uint32)
    vb = jnp.where(values < 0, ~vb, vb | jnp.uint32(0x80000000))
    bins = (vb >> shift).astype(jnp.int32)

    from ..config import get_flag

    n_slots = num_groups * b
    mode = get_flag("pallas_tdigest")
    use_pallas = (
        mode in ("auto", "interpret")
        and (mode == "interpret" or jax.default_backend() == "tpu")
        and n_slots <= (1 << 15)  # MXU dense sweep beats scatters here
        and values.shape[0] >= 128
    )
    if use_pallas:
        # Pallas kernel: both histograms in one VMEM-resident sweep
        # (pallas_tdigest.py); trash rows get an id past the kernel's
        # padded slot range so they match no tile column.
        from .pallas_tdigest import hist_fold, _TILE

        n = values.shape[0]
        chunk = min(2048, n)
        while n % chunk:
            chunk //= 2
        pad = -(-n_slots // _TILE) * _TILE
        flat = jnp.where(mask & (gids < num_groups), gids * b + bins, pad)
        w_f, mw_f = hist_fold(
            flat, jnp.where(mask, values, 0.0), n_slots, chunk=chunk,
            interpret=(mode == "interpret"),
        )
        w = w_f.reshape(num_groups, b)
        mw = mw_f.reshape(num_groups, b)
    else:
        flat = jnp.where(
            mask & (gids < num_groups), gids * b + bins, n_slots
        )
        w = jax.ops.segment_sum(
            mask.astype(jnp.float32), flat, num_segments=n_slots + 1
        )[:-1].reshape(num_groups, b)
        mw = jax.ops.segment_sum(
            jnp.where(mask, values, 0.0), flat, num_segments=n_slots + 1
        )[:-1].reshape(num_groups, b)
    means = jnp.where(w > 0, mw / jnp.maximum(w, 1e-30), 0.0)
    return _compress(means, w, k, ordered=True)


def digest_update(carry, group_ids, mask, values, *, num_groups: int | None = None):
    """UDA update: fold a batch into the digest carry."""
    g, k = carry[0].shape
    fresh = batch_to_digest(values, group_ids, mask, g if num_groups is None else num_groups, k)
    return digest_merge(carry, fresh)


def digest_quantile(carry, qs):
    """Estimate quantiles per group: [G, len(qs)] (NaN for empty groups).

    Linear interpolation of centroid means over cumulative-weight midpoints
    (the standard t-digest estimator).
    """
    means, weights = carry
    qs_arr = jnp.asarray(qs, dtype=jnp.float32)

    sort_key = jnp.where(weights > 0, means, _BIG)
    order = jnp.argsort(sort_key, axis=-1, stable=True)
    means_s = jnp.take_along_axis(means, order, axis=-1)
    weights_s = jnp.take_along_axis(weights, order, axis=-1)

    total = jnp.sum(weights_s, axis=-1)
    cumw = jnp.cumsum(weights_s, axis=-1)
    cmid = cumw - weights_s * 0.5

    # Fill empty (w==0, sorted to the end) slots so interp clamps to the
    # last real centroid instead of walking into garbage.
    filled_mean = jax.lax.cummax(jnp.where(weights_s > 0, means_s, -_BIG), axis=1)
    filled_cmid = jnp.where(weights_s > 0, cmid, total[:, None])

    def one_group(m, c, t):
        return jnp.interp(qs_arr * t, c, m)

    out = jax.vmap(one_group)(filled_mean, filled_cmid, total)
    return jnp.where(total[:, None] > 0, out, jnp.nan)
