"""Static open-addressing hash table: host build, device lookup.

The TPU replacement for pointer-chasing hash maps (reference:
``src/carnot/exec/row_tuple.h`` AbslRowTupleHashMap): the host builds a
power-of-two table with a *bounded* probe length (rebuilding larger until
every key fits within ``max_probes`` slots), so the device lookup is a
fixed number of gathers + compares — fully static shapes, no loops.

Keys are tuples of uint64 planes (a UINT128 UPID is (hi, lo)); values are
int32 payload indices. Used for metadata UPID->entity resolution and
reusable as a hash-join build side.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (wrapping uint64 arithmetic)."""
    x = x.astype(np.uint64)
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def _hash_planes(planes) -> np.ndarray:
    h = np.zeros(len(planes[0]), dtype=np.uint64)
    for p in planes:
        h = _mix64(h ^ (p.astype(np.uint64) + _GOLDEN))
    return h


@dataclass
class HashTable:
    """Host-built table; ``key_planes``/``values``/``occupied`` are dense
    [size] arrays ready for device placement."""

    key_planes: tuple  # tuple[np.ndarray[uint64]], one per key plane
    values: np.ndarray  # int32[size]
    occupied: np.ndarray  # bool[size]
    max_probes: int

    @property
    def size(self) -> int:
        return len(self.values)


def build_table(key_planes, values, max_probes: int = 8) -> HashTable:
    """Insert (key -> value) pairs; grow until probe length <= max_probes.

    ``key_planes``: sequence of uint64 arrays (same length n).
    ``values``: int array [n]. Duplicate keys keep the LAST value
    (metadata updates overwrite earlier state).
    """
    planes = [np.asarray(p, dtype=np.uint64) for p in key_planes]
    values = np.asarray(values, dtype=np.int32)
    n = len(values)
    size = 16
    while size < 2 * max(n, 1):
        size *= 2

    while True:
        mask = np.uint64(size - 1)
        tbl_planes = [np.zeros(size, dtype=np.uint64) for _ in planes]
        tbl_vals = np.zeros(size, dtype=np.int32)
        occ = np.zeros(size, dtype=bool)
        h = (_hash_planes(planes) & mask).astype(np.int64) if n else np.zeros(0, np.int64)
        ok = True
        for i in range(n):
            slot = h[i]
            placed = False
            for _p in range(max_probes):
                s = (slot + _p) & (size - 1)
                if not occ[s]:
                    occ[s] = True
                    for tp, kp in zip(tbl_planes, planes):
                        tp[s] = kp[i]
                    tbl_vals[s] = values[i]
                    placed = True
                    break
                if all(tp[s] == kp[i] for tp, kp in zip(tbl_planes, planes)):
                    tbl_vals[s] = values[i]  # overwrite duplicate key
                    placed = True
                    break
            if not placed:
                ok = False
                break
        if ok:
            return HashTable(tuple(tbl_planes), tbl_vals, occ, max_probes)
        size *= 2


def _mix64_j(x):
    x = x.astype(jnp.uint64)
    x ^= x >> jnp.uint64(30)
    x *= jnp.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> jnp.uint64(27)
    x *= jnp.uint64(0x94D049BB133111EB)
    x ^= x >> jnp.uint64(31)
    return x


def device_lookup(table: HashTable, query_planes, device_arrays=None):
    """Vectorized exact lookup: [n] keys -> (values int32[n], found bool[n]).

    ``device_arrays`` optionally carries pre-placed jnp copies of the
    table arrays (so a closure can stage them once); defaults to placing
    ``table``'s numpy arrays inline.
    """
    if device_arrays is None:
        device_arrays = (
            tuple(jnp.asarray(p) for p in table.key_planes),
            jnp.asarray(table.values),
            jnp.asarray(table.occupied),
        )
    tbl_planes, tbl_vals, occ = device_arrays
    size = table.size
    mask = jnp.uint64(size - 1)

    h = jnp.zeros(query_planes[0].shape, dtype=jnp.uint64)
    for p in query_planes:
        h = _mix64_j(h ^ (p.astype(jnp.uint64) + jnp.uint64(0x9E3779B97F4A7C15)))
    base = (h & mask).astype(jnp.int32)

    # [n, P] candidate slots; bounded probes -> static shapes.
    probes = jnp.arange(table.max_probes, dtype=jnp.int32)
    slots = (base[:, None] + probes[None, :]) & jnp.int32(size - 1)
    match = occ[slots]
    for tp, qp in zip(tbl_planes, query_planes):
        match = match & (tp[slots] == qp.astype(jnp.uint64)[:, None])
    found = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1)
    vals = tbl_vals[jnp.take_along_axis(slots, first[:, None], axis=1)[:, 0]]
    return jnp.where(found, vals, -1), found
