"""Pallas TPU kernel: fused dense-domain group-by fold (count/sum/max).

The XLA path aggregates via scatters/sorts per UDA (``ops/groupby.py``,
``udf/builtins/math_ops.py``). This kernel is the hand-scheduled
alternative for the dense-domain case (slot ids already packed, G slots
known statically): a grid over row chunks keeps the [G] accumulators
resident in VMEM for the whole pass and turns the per-chunk reduction
into MXU work — a [C, G] one-hot contraction computes count and sum in
two matmuls, and a masked VPU reduce folds max — instead of HBM
scatter traffic per aggregate.

Reference contrast: Carnot's AggNode walks a hash map row-by-row
(``src/carnot/exec/agg_node.h:66``); there is no reference analog of a
fused systolic-array group-by — this is the TPU-first design the MXU
makes natural.

Numeric contract: f32 throughout (count is exact below 2^24 per group;
sums carry f32 rounding) — the engine's exact i64 paths stay on the XLA
pipeline; this kernel serves FLOAT64-typed aggregations whose planes
are f32 on device anyway (``types/dtypes.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _fold_kernel(slot_ref, val_ref, cnt_ref, sum_ref, max_ref, aux_ref,
                 *, g: int, want_min: bool):
    """One grid step: fold a [C]-row chunk into the [G] accumulators.

    ``aux_ref`` is the per-group MIN when ``want_min`` (full VPU masked
    reduce) and otherwise a per-group count of -inf values (one extra
    MXU contraction) — the cheap evidence the sum-restore logic needs,
    since zeroed non-finite rows must resurface in their own group.
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        max_ref[:] = jnp.full_like(max_ref, -jnp.inf)
        aux_ref[:] = jnp.full_like(aux_ref, jnp.inf if want_min else 0.0)

    slots = slot_ref[:]  # [C] i32; trash rows carry an id >= g
    vals = val_ref[:]  # [C] f32
    # [C, G] one-hot via broadcast compare: rows with slot >= g match no
    # column, so invalid rows vanish without a separate mask pass.
    onehot = (
        slots[:, None]
        == jax.lax.broadcasted_iota(jnp.int32, (slots.shape[0], g), 1)
    ).astype(jnp.float32)
    # MXU: [1, C] @ [C, G] contractions. NON-FINITE values must be zeroed
    # for the contraction (NaN * 0 = NaN and inf * 0 = NaN would poison
    # EVERY group's sum, not just the row's own group); the masked
    # max/min reductions below see the raw values, so a group containing
    # NaN/+inf/-inf surfaces there and the caller restores the correct
    # non-finite sum into that group alone. The masked fills are ±inf —
    # they feed only VPU reductions, never the matmul, so a group whose
    # values are all +inf (f32 overflow of a huge f64) still reports the
    # true extremum the XLA scatter path would.
    cnt_ref[:] += jnp.sum(onehot, axis=0)
    sum_ref[:] += jnp.where(jnp.isfinite(vals), vals, 0.0) @ onehot
    masked_hi = jnp.where(onehot > 0, vals[:, None], -jnp.inf)  # [C, G] VPU
    max_ref[:] = jnp.maximum(max_ref[:], jnp.max(masked_hi, axis=0))
    if want_min:
        masked_lo = jnp.where(onehot > 0, vals[:, None], jnp.inf)
        aux_ref[:] = jnp.minimum(aux_ref[:], jnp.min(masked_lo, axis=0))
    else:
        aux_ref[:] += (vals == -jnp.inf).astype(jnp.float32) @ onehot


@functools.partial(
    jax.jit, static_argnames=("g", "chunk", "interpret", "want_min")
)
def dense_group_fold(slots, values, g: int, chunk: int = 2048,
                     interpret: bool = False, want_min: bool = False):
    """(count, sum, max, min | None) f32[g] over packed slot ids.

    ``slots`` i32[n] in [0, g) for live rows, >= g for masked rows;
    ``values`` f32[n]. n must be a multiple of ``chunk`` (the engine's
    capacity bucketing guarantees powers of two); g should be a multiple
    of 128 for lane alignment (pad and slice at the caller).
    ``want_min=False`` skips the min reduce (the 4th return is None) —
    queries without a min aggregate don't pay its VPU pass.
    """
    n = slots.shape[0]
    grid = (n // chunk,)
    out = pl.pallas_call(
        functools.partial(_fold_kernel, g=g, want_min=want_min),
        grid=grid,
        in_specs=[
            pl.BlockSpec((chunk,), lambda i: (i,)),
            pl.BlockSpec((chunk,), lambda i: (i,)),
        ],
        # Accumulators: every grid step maps to the SAME [g] block, so
        # they live in VMEM across the whole pass (init at step 0).
        out_specs=[
            pl.BlockSpec((g,), lambda i: (0,)),
            pl.BlockSpec((g,), lambda i: (0,)),
            pl.BlockSpec((g,), lambda i: (0,)),
            pl.BlockSpec((g,), lambda i: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g,), jnp.float32),
            jax.ShapeDtypeStruct((g,), jnp.float32),
            jax.ShapeDtypeStruct((g,), jnp.float32),
            jax.ShapeDtypeStruct((g,), jnp.float32),
        ],
        interpret=interpret,
    )(slots.astype(jnp.int32), values.astype(jnp.float32))
    cnt, s, m, aux = out
    # Restore per-group non-finite sums from the max/aux evidence (the
    # contraction zeroed them so they could not leak across groups):
    # NaN anywhere -> NaN; +inf and -inf together -> NaN; else +/-inf.
    mn = aux if want_min else None
    has_nan = jnp.isnan(m) | (jnp.isnan(aux) if want_min else False)
    has_pos = m == jnp.inf
    has_neg = (aux == -jnp.inf) if want_min else (aux > 0)
    s = jnp.where(
        has_nan | (has_pos & has_neg), jnp.nan,
        jnp.where(has_pos, jnp.inf, jnp.where(has_neg, -jnp.inf, s)),
    )
    live = cnt > 0
    return (
        cnt,
        jnp.where(live, s, 0.0),
        jnp.where(live, m, jnp.nan),
        jnp.where(live, mn, jnp.nan) if want_min else None,
    )
