"""Device-side kernel library: sketches, segment ops, (later) Pallas kernels."""
