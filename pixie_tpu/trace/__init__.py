from .spec import (
    ProbeDef,
    TraceExpr,
    TracepointDelete,
    TracepointDeployment,
    parse_ttl,
)

__all__ = [
    "ProbeDef",
    "TraceExpr",
    "TracepointDelete",
    "TracepointDeployment",
    "parse_ttl",
]
