"""Tracepoint specifications: the pxtrace compile target.

Reference parity: the probe DSL compiles PxL probe definitions into
``TracepointDeployment`` protos (``src/carnot/planner/probes/probes.h``,
``tracepoint_generator.h``); those deploy through the MDS tracepoint
registry to PEMs, whose dynamic tracer compiles them into attached
programs (``src/stirling/source_connectors/dynamic_tracer/
dynamic_tracer.h:48``).

Divergence (documented): the reference resolves argument/return types
from DWARF at attach time; this runtime instruments in-process Python
callables, so ``ArgExpr``/``RetExpr`` carry a declared logical type
(default INT64) instead.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..types.dtypes import DataType
from ..types.relation import Relation


@dataclass(frozen=True)
class TraceExpr:
    """One captured value: a function argument, the return value, or the
    call latency (probes.h ProbeIR output expressions)."""

    kind: str  # 'arg' | 'ret' | 'latency'
    expr: str = ""  # 'arg0'..'argN' or a keyword argument name; '' for ret
    dtype: DataType = DataType.INT64

    def __post_init__(self):
        if self.kind not in ("arg", "ret", "latency"):
            raise ValueError(f"unknown trace expr kind {self.kind!r}")


@dataclass(frozen=True)
class ProbeDef:
    """A probe function's compiled body: target symbol + named outputs."""

    target: str  # symbol to instrument, e.g. 'app.handle_request'
    outputs: tuple = ()  # tuple[(column name, TraceExpr)]


@dataclass(frozen=True)
class TracepointDeployment:
    """One UpsertTracepoint request (TracepointDeployment proto analog)."""

    name: str
    table_name: str
    probe: ProbeDef
    ttl_s: float = 600.0

    def relation(self) -> Relation:
        items = [
            ("time_", DataType.TIME64NS),
            ("upid", DataType.UINT128),
        ]
        for col, te in self.probe.outputs:
            items.append((col, te.dtype))
        return Relation(items)


@dataclass(frozen=True)
class TracepointDelete:
    """A DeleteTracepoint request."""

    name: str


def parse_ttl(ttl) -> float:
    """'30s' / '10m' / '2h' / number-of-seconds -> seconds."""
    if isinstance(ttl, (int, float)):
        return float(ttl)
    s = str(ttl).strip()
    units = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    if s and s[-1] in units:
        return float(s[:-1]) * units[s[-1]]
    return float(s)
