"""Shipped PxL script library.

Reference parity: ``src/pxl_scripts/px/`` — 60 script directories, each a
``manifest.yaml`` + ``*.pxl`` (+ vis spec), compiled wholesale in CI by
``src/e2e_test/vizier/planner/all_scripts_test.go`` against dumped
cluster schemas. Here every script dir under ``px/`` holds
``manifest.yaml`` + ``<name>.pxl`` (+ optional ``vis.json``), compiles
against the canonical ingest schemas (``pixie_tpu.ingest.schemas``), and
``tests/test_scripts.py`` is the compile-all regression.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

_SCRIPT_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "px")


@dataclass
class ScriptDef:
    """One shipped script: PxL source + manifest metadata."""

    name: str  # e.g. "px/http_stats"
    path: str
    pxl: str
    manifest: dict = field(default_factory=dict)
    vis: str | None = None  # vis.json contents when present

    @property
    def tables(self) -> list[str]:
        return list(self.manifest.get("tables", []))


def list_scripts() -> list[str]:
    """Names of every shipped script (sorted)."""
    if not os.path.isdir(_SCRIPT_ROOT):
        return []
    return sorted(
        f"px/{d}"
        for d in os.listdir(_SCRIPT_ROOT)
        if os.path.isdir(os.path.join(_SCRIPT_ROOT, d))
        and any(
            f.endswith(".pxl")
            for f in os.listdir(os.path.join(_SCRIPT_ROOT, d))
        )
    )


def load_script(name: str) -> ScriptDef:
    """Load ``px/<short>`` (or bare ``<short>``) from the library."""
    import yaml

    short = name.split("/", 1)[1] if "/" in name else name
    d = os.path.join(_SCRIPT_ROOT, short)
    if not os.path.isdir(d):
        raise KeyError(f"no shipped script named {name!r}")
    pxl_files = [f for f in os.listdir(d) if f.endswith(".pxl")]
    if not pxl_files:
        raise KeyError(f"script dir {d} has no .pxl file")
    with open(os.path.join(d, sorted(pxl_files)[0])) as f:
        pxl = f.read()
    manifest = {}
    mpath = os.path.join(d, "manifest.yaml")
    if os.path.exists(mpath):
        with open(mpath) as f:
            manifest = yaml.safe_load(f) or {}
    vis = None
    vpath = os.path.join(d, "vis.json")
    if os.path.exists(vpath):
        with open(vpath) as f:
            vis = f.read()
    return ScriptDef(
        name=f"px/{short}", path=d, pxl=pxl, manifest=manifest, vis=vis
    )


def load_all() -> list[ScriptDef]:
    return [load_script(n) for n in list_scripts()]
