"""Plan rewrite rules (analyzer/optimizer batches).

Reference parity: ``src/carnot/planner/compiler/analyzer/`` +
``optimizer/`` rule passes run by RuleExecutor
(``planner/rules/rule_executor.h:120``). The rules here operate on the
exec-layer Plan DAG:

- ``fuse_quantile_plucks``: pluck_float64(quantiles(x), 'p99') inside the
  aggregating fragment becomes a direct ``_quantile_p99`` UDA output, so
  the hot path never materializes JSON sketch strings (TPU-specific; the
  reference evaluates pluck per row).
- ``prune_unused_columns``: projection pushdown to sources + dropping
  dead Map/Agg outputs (reference ``prune_unused_columns_rule``).
- ``add_limit_to_result_sinks``: cap result streams (reference
  ``add_limit_to_batch_result_sink_rule``, 10k default).
- ``prune_unreachable``: drop operators not feeding any result sink
  (reference ``prune_unconnected_operators_rule``).
"""

from __future__ import annotations

from ..types.dtypes import DataType
from ..exec.plan import (
    AggExpr,
    AggOp,
    ColumnRef,
    FilterOp,
    FuncCall,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySourceOp,
    Plan,
    ResultSinkOp,
    UnionOp,
)
from ..udf.builtins.math_sketches import QUANTILE_FIELDS

_PLUCK_FUNCS = frozenset({"pluck", "pluck_float64", "pluck_int64"})
ALL = None  # "requires every column" marker


def run_rules(plan: Plan, max_output_rows: int = 10_000,
              table_stats: dict | None = None) -> Plan:
    prune_unreachable(plan)
    fold_constants(plan)
    prune_noop_filters(plan)
    fuse_quantile_plucks(plan)
    push_filters_below_maps(plan)
    merge_consecutive_filters(plan)
    push_limit_below_maps(plan)
    fuse_consecutive_maps(plan)
    drop_noop_maps(plan)
    merge_nodes(plan)
    push_agg_through_join(plan, table_stats)
    prune_unused_columns(plan)
    add_limit_to_result_sinks(plan, max_output_rows)
    return plan


def _consumers(plan: Plan) -> dict:
    out: dict[int, list] = {nid: [] for nid in plan.nodes}
    for n in plan.nodes.values():
        for i in n.inputs:
            out[i].append(n.id)
    return out


def _expr_columns(expr, acc: set):
    if isinstance(expr, ColumnRef):
        acc.add(expr.name)
    elif isinstance(expr, FuncCall):
        for a in expr.args:
            _expr_columns(a, acc)
    return acc


def _rewrite_expr(expr, fn):
    """Bottom-up expression rewrite; ``fn`` maps a node to a replacement
    (or returns it unchanged)."""
    if isinstance(expr, FuncCall):
        expr = FuncCall(expr.name, tuple(_rewrite_expr(a, fn) for a in expr.args))
    return fn(expr)


# -- quantile pluck fusion ----------------------------------------------------
def fuse_quantile_plucks(plan: Plan) -> None:
    consumers = _consumers(plan)

    def find_quantile_agg(start_nid: int, col: str):
        """Walk up a single-consumer chain to the AggOp producing ``col``
        via the 'quantiles' UDA. Returns (agg_nid, path_map_nids,
        agg_out_name) or None."""
        nid = start_nid
        path_maps = []
        while True:
            if len(consumers.get(nid, [])) > 1:
                return None  # materialization boundary: host pluck works
            node = plan.nodes[nid]
            op = node.op
            if isinstance(op, AggOp):
                for ae in op.aggs:
                    if ae.out_name == col:
                        if ae.uda_name == "quantiles":
                            return nid, path_maps, col
                        return None
                return None
            if isinstance(op, (FilterOp, LimitOp)):
                nid = node.inputs[0]
            elif isinstance(op, MapOp):
                src = next((e for n, e in op.exprs if n == col), None)
                if not isinstance(src, ColumnRef):
                    return None
                col = src.name
                path_maps.append(nid)
                nid = node.inputs[0]
            else:
                return None

    for nid in list(plan.topo_order()):
        node = plan.nodes[nid]
        op = node.op
        if not isinstance(op, (MapOp, FilterOp)):
            continue

        def rewrite(e, _node=node):
            if not (
                isinstance(e, FuncCall)
                and e.name in _PLUCK_FUNCS
                and len(e.args) == 2
                and isinstance(e.args[0], ColumnRef)
                and isinstance(e.args[1], Literal)
                and e.args[1].value in QUANTILE_FIELDS
            ):
                return e
            if not _node.inputs:
                return e
            found = find_quantile_agg(_node.inputs[0], e.args[0].name)
            if found is None:
                return e
            agg_nid, path_maps, agg_out = found
            agg_node = plan.nodes[agg_nid]
            field = e.args[1].value
            src_ae = next(
                ae for ae in agg_node.op.aggs if ae.out_name == agg_out
            )
            new_name = f"_q_{field}_{src_ae.out_name}"
            if all(ae.out_name != new_name for ae in agg_node.op.aggs):
                agg_node.op = AggOp(
                    group_cols=agg_node.op.group_cols,
                    aggs=agg_node.op.aggs
                    + (AggExpr(new_name, f"_quantile_{field}", src_ae.args),),
                    max_groups=agg_node.op.max_groups,
                )
            # Thread the new column through intermediate full projections.
            for mid in path_maps:
                mop = plan.nodes[mid].op
                if all(n != new_name for n, _ in mop.exprs):
                    plan.nodes[mid].op = MapOp(
                        exprs=mop.exprs + ((new_name, ColumnRef(new_name)),)
                    )
            return ColumnRef(new_name)

        if isinstance(op, MapOp):
            node.op = MapOp(
                exprs=tuple((n, _rewrite_expr(e, rewrite)) for n, e in op.exprs)
            )
        else:
            node.op = FilterOp(predicate=_rewrite_expr(op.predicate, rewrite))


# -- column pruning -----------------------------------------------------------
def prune_unused_columns(plan: Plan) -> None:
    """Two phases: propagate per-node column requirements from the sinks,
    then rewrite Map/Agg/Source ops to drop dead columns."""
    order = plan.topo_order()
    required: dict[int, object] = {nid: set() for nid in plan.nodes}

    def require(nid, cols):
        if cols is ALL or required[nid] is ALL:
            required[nid] = ALL
        else:
            required[nid] = required[nid] | cols

    for nid in reversed(order):
        node = plan.nodes[nid]
        op = node.op
        req = required[nid]
        if isinstance(op, ResultSinkOp):
            require(node.inputs[0], ALL)
        elif isinstance(op, (LimitOp, UnionOp)):
            for i in node.inputs:
                require(i, req)
        elif isinstance(op, FilterOp):
            pred_cols = _expr_columns(op.predicate, set())
            require(node.inputs[0], ALL if req is ALL else req | pred_cols)
        elif isinstance(op, MapOp):
            kept = _kept_map_exprs(op, req)
            needed = set()
            for _n, e in kept:
                _expr_columns(e, needed)
            require(node.inputs[0], needed)
        elif isinstance(op, AggOp):
            needed = set(op.group_cols)
            for ae in op.aggs:
                if req is ALL or ae.out_name in req:
                    for a in ae.args:
                        _expr_columns(a, needed)
            require(node.inputs[0], needed)
        elif isinstance(op, JoinOp):
            l_rel = plan.nodes[node.inputs[0]].relation
            r_rel = plan.nodes[node.inputs[1]].relation
            if req is ALL or l_rel is None or r_rel is None:
                require(node.inputs[0], ALL)
                require(node.inputs[1], ALL)
            else:
                l_req, r_req = set(op.left_on), set(op.right_on)
                taken = set(l_rel.column_names)
                for c in l_rel.column_names:
                    if c in req:
                        l_req.add(c)
                for c in r_rel.column_names:
                    if c in op.right_on:
                        continue
                    out_n = c
                    while out_n in taken:
                        out_n += op.suffix
                    taken.add(out_n)
                    if out_n in req:
                        r_req.add(c)
                require(node.inputs[0], l_req)
                require(node.inputs[1], r_req)
        elif isinstance(op, MemorySourceOp):
            pass
        else:
            for i in node.inputs:
                require(i, ALL)

    # Phase 2: rewrite.
    for nid in order:
        node = plan.nodes[nid]
        op = node.op
        req = required[nid]
        if req is ALL:
            continue
        if isinstance(op, MapOp):
            kept = _kept_map_exprs(op, req)
            if len(kept) != len(op.exprs):
                node.op = MapOp(exprs=kept)
        elif isinstance(op, AggOp):
            kept = tuple(ae for ae in op.aggs if ae.out_name in req)
            if len(kept) != len(op.aggs):
                node.op = AggOp(
                    group_cols=op.group_cols, aggs=kept,
                    max_groups=op.max_groups,
                )
        elif isinstance(op, MemorySourceOp):
            if node.relation is not None:
                cols = tuple(
                    c for c in node.relation.column_names if c in req
                )
                if len(cols) != len(node.relation.column_names):
                    node.op = MemorySourceOp(
                        table=op.table, columns=cols,
                        start_time=op.start_time, stop_time=op.stop_time,
                    )


def _kept_map_exprs(op: MapOp, req):
    """Map exprs surviving pruning (shared by both phases so requirement
    propagation matches the rewrite): at least one expr is kept to
    preserve row cardinality."""
    if req is ALL:
        return op.exprs
    kept = tuple((n, e) for n, e in op.exprs if n in req)
    if not kept and op.exprs:
        kept = op.exprs[:1]
    return kept


# -- limits -------------------------------------------------------------------
def add_limit_to_result_sinks(plan: Plan, max_rows: int) -> None:
    for nid in list(plan.nodes):
        node = plan.nodes[nid]
        if not isinstance(node.op, ResultSinkOp):
            continue
        src = node.inputs[0]
        src_op = plan.nodes[src].op
        if isinstance(src_op, LimitOp) and src_op.n <= max_rows:
            continue
        lim = plan.add(LimitOp(max_rows), [src])
        plan.nodes[lim].relation = plan.nodes[src].relation
        node.inputs[0] = lim


# -- reachability -------------------------------------------------------------
# op -> (fn, allowed arg dtypes): folding must not change type/error
# behavior — arithmetic on BOOLEAN literals or logicalAnd on INT64 would
# fold to values the unfolded expression's UDF bind would have rejected.
_FOLDABLE = {
    "add": (lambda a, b: a + b, "num"),
    "subtract": (lambda a, b: a - b, "num"),
    "multiply": (lambda a, b: a * b, "num"),
    "lessThan": (lambda a, b: a < b, "num"),
    "lessThanEqual": (lambda a, b: a <= b, "num"),
    "greaterThan": (lambda a, b: a > b, "num"),
    "greaterThanEqual": (lambda a, b: a >= b, "num"),
    "equal": (lambda a, b: a == b, "any"),
    "notEqual": (lambda a, b: a != b, "any"),
    "logicalAnd": (lambda a, b: bool(a and b), "bool"),
    "logicalOr": (lambda a, b: bool(a or b), "bool"),
}


def fold_constants(plan: Plan) -> None:
    """Evaluate literal-only scalar subtrees at compile time (the
    reference's constant-folding analyzer pass). Only pure arithmetic /
    comparison / boolean ops fold — everything else keeps its runtime
    semantics (e.g. divide's inf-on-zero stays on device)."""
    from ..types.dtypes import DataType

    def fold(e):
        if not (isinstance(e, FuncCall) and e.name in _FOLDABLE):
            return e
        if not all(isinstance(a, Literal) for a in e.args) or len(e.args) != 2:
            return e
        a, b = e.args
        fn, kinds = _FOLDABLE[e.name]
        allowed = {
            "num": (DataType.INT64, DataType.FLOAT64, DataType.TIME64NS),
            "bool": (DataType.BOOLEAN,),
            "any": (
                DataType.INT64, DataType.FLOAT64, DataType.BOOLEAN,
                DataType.TIME64NS,
            ),
        }[kinds]
        if a.dtype != b.dtype or a.dtype not in allowed:
            return e
        try:
            v = fn(a.value, b.value)
        except Exception:
            return e
        if isinstance(v, bool):
            return Literal(v, DataType.BOOLEAN)
        return Literal(v, a.dtype)

    for node in plan.nodes.values():
        op = node.op
        if isinstance(op, MapOp):
            node.op = MapOp(
                exprs=tuple((n, _rewrite_expr(e, fold)) for n, e in op.exprs)
            )
        elif isinstance(op, FilterOp):
            node.op = FilterOp(predicate=_rewrite_expr(op.predicate, fold))


def push_filters_below_maps(plan: Plan) -> None:
    """Swap Filter(Map(x)) -> Map(Filter'(x)) when every column the
    predicate references is a pure pass-through of the map (the
    reference's filter-pushdown pass). Within one fused fragment the win
    is evaluation-order freedom for XLA; across a materialization
    boundary it prunes rows before the map computes."""
    consumers = _consumers(plan)
    for nid in list(plan.topo_order()):
        node = plan.nodes[nid]
        if not isinstance(node.op, FilterOp) or not node.inputs:
            continue
        up_id = node.inputs[0]
        up = plan.nodes[up_id]
        if not isinstance(up.op, MapOp) or len(consumers.get(up_id, [])) != 1:
            continue
        # Predicate columns must map 1:1 onto upstream columns.
        pred_cols = _expr_columns(node.op.predicate, set())
        renames = {
            n: e.name
            for n, e in up.op.exprs
            if isinstance(e, ColumnRef)
        }
        if not pred_cols <= set(renames):
            continue

        def rename(e):
            if isinstance(e, ColumnRef):
                return ColumnRef(renames[e.name])
            return e

        new_pred = _rewrite_expr(node.op.predicate, rename)
        # Rewire in place, keeping ids stable for downstream consumers:
        # nid (what consumers point at) becomes the Map; up_id becomes
        # the renamed Filter over the map's old input.
        x_inputs = list(up.inputs)
        map_op, map_rel = up.op, up.relation
        up.op = FilterOp(predicate=new_pred)
        up.inputs = x_inputs
        up.relation = (
            plan.nodes[x_inputs[0]].relation if x_inputs else None
        )
        node.op = map_op
        node.inputs = [up_id]
        node.relation = map_rel



# -- eager aggregation through joins ------------------------------------------
_PAJ_DECOMPOSABLE = frozenset({"count", "sum", "min", "max"})


def _source_key_ndv(plan: Plan, nid: int, cols, table_stats):
    """Estimated NDV product of ``cols`` at node ``nid`` from ingest
    sketches (walking renames/filters down to a MemorySourceOp), or
    None when the subtree computes the keys or stats are missing."""
    if not table_stats:
        return None
    mapping = {c: c for c in cols}
    while True:
        node = plan.nodes.get(nid)
        if node is None:
            return None
        op = node.op
        if isinstance(op, MemorySourceOp):
            st = table_stats.get(op.table)
            if not st:
                return None
            prod = 1
            for c in mapping.values():
                v = (st.get("ndv") or {}).get(c)
                if v is None:
                    return None
                prod *= max(int(v), 1)
            rows = st.get("rows")
            return min(prod, int(rows)) if rows else prod
        if isinstance(op, (FilterOp, LimitOp)) and node.inputs:
            nid = node.inputs[0]
        elif isinstance(op, MapOp) and node.inputs:
            from ..exec.plan import trace_map_renames

            mapping = trace_map_renames(op, mapping)
            if mapping is None:
                return None
            nid = node.inputs[0]
        else:
            return None


def push_agg_through_join(plan: Plan, table_stats: dict | None = None) -> None:
    """Eager aggregation (Yan & Larson): rewrite GroupBy(Join(L, R)) so
    the build side pre-aggregates below the join.

    When every group key comes from the probe (left) side and every
    aggregate decomposes, the N:M join never materializes: R partial-aggs
    by its join keys (adding a ``__paj_cnt`` multiplicity), the join
    becomes N:1 — which the engine executes as a fused in-fragment device
    lookup — and the top aggregate reweights:

        count(x)        -> sum(__paj_cnt)
        sum(r_col)      -> sum(__paj_s_<col>)
        min/max(r_col)  -> min/max(__paj_m*_<col>)
        min/max(l_col)  -> min/max(l_col)   (fan-out can't change extremes)

    The reference's optimizer has no analog (Carnot always hash-joins,
    ``src/carnot/exec/equijoin_node.cc``); on TPU this turns the worst
    exec-node shape (host hash join) into two dense scatter aggregates.
    Inner joins only: outer variants change null/row semantics.
    """
    consumers = _consumers(plan)
    for nid in list(plan.nodes):
        node = plan.nodes.get(nid)
        if node is None or not isinstance(node.op, AggOp):
            continue
        agg: AggOp = node.op
        if agg.mode != "full" or not node.inputs:
            continue
        if any(ae.out_name.startswith("__paj_") for ae in agg.aggs):
            continue  # already rewritten
        jid = node.inputs[0]
        jnode = plan.nodes.get(jid)
        if jnode is None or not isinstance(jnode.op, JoinOp):
            continue
        join: JoinOp = jnode.op
        if join.how != "inner" or consumers.get(jid, []) != [nid]:
            continue
        if len(jnode.inputs) != 2:
            continue
        left_id, right_id = jnode.inputs
        lrel = plan.nodes[left_id].relation
        rrel = plan.nodes[right_id].relation
        if lrel is None or rrel is None:
            continue
        # Already N:1? A build side grouped by exactly the join keys is
        # unique on them — pre-aggregating again would just stack a
        # pointless blocking agg (and the engine's fused lookup join
        # consumes the grouped state directly).
        rid = right_id
        while isinstance(plan.nodes[rid].op, (MapOp, FilterOp)) and plan.nodes[rid].inputs:
            rid = plan.nodes[rid].inputs[0]
        rop = plan.nodes[rid].op
        if isinstance(rop, AggOp) and set(rop.group_cols) >= set(join.right_on):
            continue
        lcols = set(lrel.column_names)
        # Join-output name -> (side, source column), mirroring the
        # engine's _join_out_schema (left names win; right value columns
        # take the suffix on collision).
        src_of: dict = {c: ("l", c) for c in lrel.column_names}
        for c in rrel.column_names:
            if c in join.right_on:
                continue
            out = c + join.suffix if c in lcols else c
            src_of.setdefault(out, ("r", c))
        if not all(
            c in src_of and src_of[c][0] == "l" for c in agg.group_cols
        ):
            continue

        # Every aggregate must be a decomposable UDA over one column.
        plan_ok = True
        right_needs: dict = {}  # right col -> set of partial kinds
        rewritten: list = []  # (tmp_name, final AggExpr builder data)
        for ae in agg.aggs:
            if (
                ae.uda_name not in _PAJ_DECOMPOSABLE
                or len(ae.args) != 1
                or not isinstance(ae.args[0], ColumnRef)
                or ae.args[0].name not in src_of
            ):
                plan_ok = False
                break
            side, src = src_of[ae.args[0].name]
            if ae.uda_name == "count":
                rewritten.append((ae, "sum", "__paj_cnt"))
            elif side == "r":
                kind = {"sum": "s", "min": "mn", "max": "mx"}[ae.uda_name]
                right_needs.setdefault(src, set()).add(kind)
                rewritten.append((ae, ae.uda_name, f"__paj_{kind}_{src}"))
            elif ae.uda_name in ("min", "max"):
                rewritten.append((ae, ae.uda_name, ae.args[0].name))
            else:
                plan_ok = False  # sum/mean over a left column: needs
                break  # cnt-weighted reweighting (not yet)
        if not plan_ok:
            continue
        # The partial count needs a castable (non-string) column on R.
        cnt_src = next(
            (
                c
                for c in rrel.column_names
                if rrel.col_type(c)
                in (DataType.INT64, DataType.FLOAT64, DataType.TIME64NS,
                    DataType.BOOLEAN)
            ),
            None,
        )
        if cnt_src is None:
            continue

        from ..types.relation import Relation

        partial_aggs = [AggExpr("__paj_cnt", "count", (ColumnRef(cnt_src),))]
        partial_items = [(rc, rrel.col_type(rc)) for rc in join.right_on]
        partial_items.append(("__paj_cnt", DataType.INT64))
        for src, kinds in sorted(right_needs.items()):
            for kind in sorted(kinds):
                uda = {"s": "sum", "mn": "min", "mx": "max"}[kind]
                partial_aggs.append(
                    AggExpr(f"__paj_{kind}_{src}", uda, (ColumnRef(src),))
                )
                partial_items.append(
                    (f"__paj_{kind}_{src}", rrel.col_type(src))
                )
        # Partial-agg group capacity: the join key's sketched NDV (x1.25
        # slack for HLL error, rounded to a power of two) instead of a
        # blind 64K default — a mis-sized capacity climbs the overflow-
        # doubling ladder at run time, one jit recompile per rung.
        # Clamped to the rebucket ceiling: sketch NDV is table-LIFETIME
        # (expiry never decrements), and under-sizing self-corrects at
        # run time while a stale over-size pre-allocates real memory.
        from ..config import get_flag

        groups = max(agg.max_groups, 1 << 16)
        ndv = _source_key_ndv(
            plan, right_id, list(join.right_on), table_stats
        )
        if ndv:
            want = int(ndv * 1.25) + 1
            groups = max(
                agg.max_groups,
                min(1 << (want - 1).bit_length(),
                    int(get_flag("max_groups_limit"))),
            )
        # Telemetry feedback floor: a past run of THIS script observed
        # its largest aggregate's true output cardinality (the partial
        # agg is itself a fragment, so the max covers it). A drifted
        # sketch NDV can under-size the capacity and pay the overflow-
        # doubling ladder at run time — floor at reality instead;
        # over-size is the cheaper error (see join_capacity_safety).
        observed = (table_stats or {}).get("__observed_self__") or {}
        ogroups = int(observed.get("agg_groups", 0) or 0)
        if ogroups:
            owant = int(ogroups * 1.25) + 1
            groups = max(
                groups,
                min(1 << (owant - 1).bit_length(),
                    int(get_flag("max_groups_limit"))),
            )
        partial_id = plan.add(
            AggOp(
                group_cols=tuple(join.right_on),
                aggs=tuple(partial_aggs),
                max_groups=groups,
            ),
            inputs=[right_id],
            relation=Relation(partial_items),
        )

        # The join (id kept) now probes the aggregated build side: N:1.
        jnode.op = JoinOp(
            left_on=join.left_on, right_on=join.right_on, how="inner",
            suffix=join.suffix,
        )
        jnode.inputs = [left_id, partial_id]
        jnode.relation = Relation(
            list(lrel.items())
            + [(n, t) for n, t in partial_items if n not in join.right_on]
        )

        # Final aggregate under a projection that restores the original
        # output names/order (node id kept so consumers stay valid).
        final_aggs = tuple(
            AggExpr(f"__paj_o_{ae.out_name}", uda, (ColumnRef(src),))
            for ae, uda, src in rewritten
        )
        final_items = [(c, lrel.col_type(c)) for c in agg.group_cols] + [
            (f"__paj_o_{ae.out_name}", _paj_out_type(ae, uda, src, lrel, dict(partial_items)))
            for ae, uda, src in rewritten
        ]
        final_id = plan.add(
            AggOp(
                group_cols=agg.group_cols, aggs=final_aggs,
                max_groups=agg.max_groups,
            ),
            inputs=[jid],
            relation=Relation(final_items),
        )
        node.op = MapOp(
            exprs=tuple((c, ColumnRef(c)) for c in agg.group_cols)
            + tuple(
                (ae.out_name, ColumnRef(f"__paj_o_{ae.out_name}"))
                for ae, _uda, _src in rewritten
            )
        )
        node.inputs = [final_id]
        consumers = _consumers(plan)


def _paj_out_type(ae, uda, src, lrel, partial_types):
    if ae.uda_name == "count":
        return DataType.INT64
    if src in partial_types:
        return partial_types[src]
    return lrel.col_type(src)


# -- common-subplan dedup -----------------------------------------------------
def merge_nodes(plan: Plan) -> None:
    """Unify structurally identical subplans so shared work executes
    once (reference ``optimizer/merge_nodes_rule.h``).

    Bottom-up over the topo order: a node whose (op, canonical inputs)
    pair was already seen redirects its consumers to the first
    occurrence. The engine materializes any fan-out node once, so a
    multi-output script whose branches re-state the same filter/map
    prefix computes it one time. Sinks never merge (each display/export
    is its own effect).
    """
    from ..exec.plan import (
        BridgeSinkOp,
        BridgeSourceOp,
        OTelExportSinkOp,
        TableSinkOp,
        UDTFSourceOp,
    )

    never = (
        ResultSinkOp, TableSinkOp, OTelExportSinkOp, BridgeSinkOp,
        BridgeSourceOp,
        # UDTFs may be stateful/impure (cluster introspection snapshots).
        UDTFSourceOp,
    )
    canon: dict = {}
    remap: dict = {}
    for nid in plan.topo_order():
        node = plan.nodes[nid]
        node.inputs = [remap.get(i, i) for i in node.inputs]
        if isinstance(node.op, never):
            continue
        try:
            key = (node.op, tuple(node.inputs))
            hash(key)
        except TypeError:
            continue
        if key in canon:
            remap[nid] = canon[key]
        else:
            canon[key] = nid
    for nid in remap:
        del plan.nodes[nid]


# -- plan-level simplifications ----------------------------------------------
def prune_noop_filters(plan: Plan) -> None:
    """Drop FilterOps whose predicate folded to literal True."""
    for nid in list(plan.nodes):
        node = plan.nodes.get(nid)
        if node is None or not isinstance(node.op, FilterOp):
            continue
        p = node.op.predicate
        if isinstance(p, Literal) and p.value is True and node.inputs:
            src = node.inputs[0]
            for m in plan.nodes.values():
                m.inputs = [src if i == nid else i for i in m.inputs]
            del plan.nodes[nid]


def merge_consecutive_filters(plan: Plan) -> None:
    """Filter(Filter(x)) -> one Filter over ``logicalAnd(inner, outer)``
    when the inner filter has a single consumer (reference
    ``analyzer/combine_consecutive_filters``-style pass). Row masks
    conjoin exactly, and one FilterOp keeps the fused fragment's op
    chain (and fold_constants' view of the predicate) whole."""
    from .pattern import Pat, match, single_consumer

    changed = True
    while changed:
        changed = False
        consumers = _consumers(plan)
        for nid in list(plan.nodes):
            m = match(
                plan, nid,
                Pat(FilterOp, inputs=[Pat(FilterOp, name="inner")]),
            )
            if m is None or not single_consumer(
                plan, m["inner"].id, consumers
            ):
                continue
            node, inner = m[0], m["inner"]
            node.op = FilterOp(
                predicate=FuncCall(
                    "logicalAnd",
                    (inner.op.predicate, node.op.predicate),
                )
            )
            node.inputs = list(inner.inputs)
            del plan.nodes[inner.id]
            consumers = _consumers(plan)
            changed = True


def push_limit_below_maps(plan: Plan) -> None:
    """Limit(Map(x)) -> Map(Limit(x)) when the map has a single consumer
    (reference analyzer limit-pushdown). Maps are row-wise and order-
    preserving, so projecting the first n input rows equals taking the
    first n projected rows — and the limit's early source abort now
    fires before the projection computes."""
    from .pattern import Pat, match, single_consumer

    changed = True
    while changed:
        changed = False
        consumers = _consumers(plan)
        for nid in list(plan.topo_order()):
            m = match(
                plan, nid,
                Pat(LimitOp, inputs=[Pat(MapOp, name="map")]),
            )
            if m is None or not single_consumer(plan, m["map"].id, consumers):
                continue
            node, up = m[0], m["map"]
            # Id-stable swap (consumers keep pointing at nid): nid
            # becomes the Map, the map's node becomes the Limit over x.
            x_inputs = list(up.inputs)
            map_op, map_rel = up.op, up.relation
            up.op = node.op
            up.inputs = x_inputs
            up.relation = (
                plan.nodes[x_inputs[0]].relation if x_inputs else None
            )
            node.op = map_op
            node.inputs = [up.id]
            node.relation = map_rel
            changed = True


def drop_noop_maps(plan: Plan) -> None:
    """Remove MapOps that are identity projections of their input — the
    reference's ``analyzer/drop_noop_rule``-class cleanup. A map is a
    no-op when every output is ``name = col(name)`` and the output
    column set equals the input relation's, so dropping it cannot
    change schema or values."""
    from .pattern import Pat, match

    def identity(node) -> bool:
        if any(
            not isinstance(e, ColumnRef) or e.name != n
            for n, e in node.op.exprs
        ):
            return False
        if not node.inputs:
            return False
        src = plan.nodes[node.inputs[0]].relation
        return src is not None and (
            [n for n, _ in node.op.exprs] == list(src.column_names)
        )

    for nid in list(plan.nodes):
        m = match(plan, nid, Pat(MapOp, where=identity))
        if m is None:
            continue
        src = m[0].inputs[0]
        for n in plan.nodes.values():
            n.inputs = [src if i == nid else i for i in n.inputs]
        del plan.nodes[nid]


def fuse_consecutive_maps(plan: Plan) -> None:
    """Inline Map(Map(x)) into one projection when the inner map has a
    single consumer (reference ``combine_consecutive_maps_rule``): the
    outer expressions substitute the inner's column definitions."""
    consumers = _consumers(plan)
    changed = True
    while changed:
        changed = False
        for nid in list(plan.nodes):
            node = plan.nodes.get(nid)
            if node is None or not isinstance(node.op, MapOp):
                continue
            if not node.inputs:
                continue
            inner = plan.nodes.get(node.inputs[0])
            if (
                inner is None
                or not isinstance(inner.op, MapOp)
                or consumers.get(inner.id, []) != [nid]
            ):
                continue
            defs = dict(inner.op.exprs)
            # Inlining duplicates an inner expression once per outer
            # reference; only pass-through/literal defs may be inlined
            # into multiple sites (the reference rule's copyability
            # guard) — an expensive expr referenced twice must not run
            # twice in the fused fragment.
            # Count reference SITES, not referencing expressions: a
            # single outer expr using an inner column twice (a*a) still
            # inlines the definition twice.
            refs: dict = {}

            def count_sites(e):
                if isinstance(e, ColumnRef):
                    refs[e.name] = refs.get(e.name, 0) + 1
                elif isinstance(e, FuncCall):
                    for a in e.args:
                        count_sites(a)

            for _n, e in node.op.exprs:
                count_sites(e)
            if any(
                refs.get(name, 0) > 1
                and not isinstance(e, (ColumnRef, Literal))
                for name, e in defs.items()
            ):
                continue

            def subst(e):
                if isinstance(e, ColumnRef) and e.name in defs:
                    return defs[e.name]
                return e

            node.op = MapOp(exprs=tuple(
                (n, _rewrite_expr(e, subst)) for n, e in node.op.exprs
            ))
            node.inputs = list(inner.inputs)
            del plan.nodes[inner.id]
            consumers = _consumers(plan)
            changed = True


def prune_unreachable(plan: Plan) -> None:
    from ..exec.plan import OTelExportSinkOp, TableSinkOp

    sink_ids = [
        nid
        for nid, n in plan.nodes.items()
        if isinstance(n.op, (ResultSinkOp, OTelExportSinkOp, TableSinkOp))
    ]
    if not sink_ids:
        return
    seen: set = set()

    def visit(nid):
        if nid in seen:
            return
        seen.add(nid)
        for i in plan.nodes[nid].inputs:
            visit(i)

    for s in sink_ids:
        visit(s)
    for nid in list(plan.nodes):
        if nid not in seen:
            del plan.nodes[nid]
