"""Typed pattern matching over plan DAGs.

Reference parity: ``src/carnot/planner/compiler/analyzer`` rules are
written against a pattern-matcher over the typed IR
(``planner/ir/pattern_match.h`` — ``Match(ir_node, Filter(Map()))``
style predicates). Plan ops double as the IR here, so the matcher works
directly on :class:`~pixie_tpu.exec.plan.PlanNode` chains: a pattern is
an op type plus optional guards and input sub-patterns, and a match
binds each pattern's node so rewrites read like the reference's rules::

    m = match(plan, nid, Pat(FilterOp, inputs=[Pat(MapOp, name="map")]))
    if m and single_consumer(plan, m["map"].id):
        ...rewrite using m["map"], m[0]...

``m`` maps pattern names (and positional index of the root = 0) to
PlanNodes. Guards (``where``) run on the candidate node before inputs
recurse, so expensive checks stay local.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass(frozen=True)
class Pat:
    """One node pattern: op type(s) + optional guard + input patterns.

    ``op``: a plan Op class or tuple of classes (isinstance check).
    ``inputs``: sub-patterns matched positionally against the node's
    inputs (fewer patterns than inputs is fine — extras are ignored;
    more is a non-match). ``where``: guard on the candidate PlanNode.
    ``name``: binding key in the match result.
    """

    op: object
    inputs: tuple = field(default=())
    where: Optional[Callable] = None
    name: Optional[str] = None

    def __init__(self, op, inputs=(), where=None, name=None):
        object.__setattr__(self, "op", op)
        object.__setattr__(self, "inputs", tuple(inputs))
        object.__setattr__(self, "where", where)
        object.__setattr__(self, "name", name)


def match(plan, nid: int, pat: Pat) -> Optional[dict]:
    """Match ``pat`` rooted at node ``nid``; returns {name_or_index:
    PlanNode} bindings (root at key 0) or None. Shared sub-DAGs are
    fine — the matcher only walks edges, it never mutates."""
    out: dict = {}

    def walk(node_id, p, idx):
        node = plan.nodes.get(node_id)
        if node is None or not isinstance(node.op, p.op):
            return False
        if p.where is not None and not p.where(node):
            return False
        if len(p.inputs) > len(node.inputs):
            return False
        out[p.name if p.name is not None else idx] = node
        return all(
            walk(node.inputs[i], sp, f"{idx}.{i}")
            for i, sp in enumerate(p.inputs)
        )

    return out if walk(nid, pat, 0) else None


def single_consumer(plan, nid: int, consumers: Optional[dict] = None) -> bool:
    """True when exactly one node consumes ``nid`` exactly once (the
    precondition for every fuse/inline rewrite). Pass a prebuilt
    ``consumers`` map (``rules._consumers(plan)``) inside sweep loops —
    the fallback walks every node per call."""
    if consumers is not None:
        return len(consumers.get(nid, ())) == 1
    count = 0
    for n in plan.nodes.values():
        count += sum(1 for i in n.inputs if i == nid)
        if count > 1:
            return False
    return count == 1
