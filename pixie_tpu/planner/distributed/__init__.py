"""Distributed planner: logical plan -> per-agent fragments + collectives.

Reference parity: ``src/carnot/planner/distributed/`` — Splitter cuts the
plan at blocking operators, the partial-op manager splits aggregates and
limits into prepare/merge halves, the Coordinator assigns fragments to
live agents (pruning sources no agent can serve), and the Stitcher wires
the cross-fragment bridges. In the TPU build the PEM tier is the device
mesh's ``agents`` axis and every GRPC bridge becomes an XLA collective
over ICI, chosen by pattern (partial-agg state merge, row gather).
"""

from .coordinator import Coordinator, DistributedPlan, prune_unavailable_sources
from .distributed_state import AgentInfo, DistributedState
from .planner import DistributedPlanner
from .splitter import BlockingSplitPlan, BridgeSpec, Splitter

__all__ = [
    "AgentInfo",
    "BlockingSplitPlan",
    "BridgeSpec",
    "Coordinator",
    "DistributedPlan",
    "DistributedPlanner",
    "DistributedState",
    "Splitter",
    "prune_unavailable_sources",
]
