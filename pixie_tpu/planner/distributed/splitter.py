"""Splitter: cut a logical plan at blocking operators.

Reference parity: ``planner/distributed/splitter/splitter.h:75`` — the
plan is partitioned into ``before_blocking`` (runs on every data agent,
ends in bridge sinks, contains no blocking nodes) and ``after_blocking``
(runs on the merge tier, fed by bridge sources, holds the blocking nodes
and everything downstream). The partial-op manager
(``splitter/partial_op_mgr/partial_op_mgr.h``) splits aggregates into a
prepare (partial, mergeable-carry) half and a merge (finalize) half, and
limits into local + global caps.

TPU mapping: each bridge records the collective that implements it —
``agg_state_merge`` (per-device UDA carries folded over the mesh axis;
the reference's UDA Serialize/DeSerialize path, ``udf.h:99-100``) or
``row_gather`` (all_gather of surviving rows; the reference's plain
GRPCSink row stream).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ...exec.plan import (
    AggOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    JoinOp,
    LimitOp,
    MemorySourceOp,
    Op,
    Plan,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)

AGG_STATE_MERGE = "agg_state_merge"
ROW_GATHER = "row_gather"


@dataclass
class BridgeSpec:
    """One PEM->Kelvin bridge (GRPCSink/Source pair analog)."""

    bridge_id: int
    kind: str  # AGG_STATE_MERGE | ROW_GATHER
    # Filled by the stitcher (distributed_stitcher_rules.h analog):
    # mesh axes the implementing collective reduces/gathers over.
    axes: tuple = ()


@dataclass
class BlockingSplitPlan:
    """splitter.h BlockingSplitPlan analog."""

    before_blocking: Plan
    after_blocking: Plan
    bridges: list = field(default_factory=list)  # list[BridgeSpec]
    # Which agents run the data fragment: "pem" (data agents only) or
    # "all_agents" (Kelvins too — an ALL_AGENTS UDTF is present). The
    # splitter decides once; the coordinator only reads it.
    data_tier: str = "pem"

    def bridge(self, bridge_id: int) -> BridgeSpec:
        return next(b for b in self.bridges if b.bridge_id == bridge_id)


def _is_blocking(op: Op) -> bool:
    """Blocking = cannot run shard-local without a cross-agent exchange."""
    from ...exec.plan import OTelExportSinkOp

    return isinstance(
        op, (AggOp, JoinOp, UnionOp, LimitOp, ResultSinkOp, OTelExportSinkOp)
    )


def _pushdown_unions(plan: Plan) -> set:
    """UnionOp node ids safe to keep on the PEM side (pushdown_union_agg).

    A union is blocking in general — its output interleaves rows from
    every agent. But when (1) every transitive input is shard-local and
    row-wise (MemorySource/EmptySource leaves through Map/Filter only)
    and (2) its sole consumer chain through row-wise ops ends at a full
    AggOp, the union can run per-agent: the downstream agg then splits
    into a partial half on the PEM side and its AGG_STATE_MERGE bridge
    ships sketch-sized mergeable carries (HLL registers, t-digest
    centroids) instead of the union's pre-agg rows over one ROW_GATHER
    bridge per branch. Order-insensitivity of the agg's update/merge is
    what makes the per-agent interleaving unobservable.
    """
    from ...config import get_flag

    if not get_flag("pushdown_union_agg"):
        return set()
    consumers: dict[int, list[int]] = {}
    for nid, node in plan.nodes.items():
        for i in node.inputs:
            consumers.setdefault(i, []).append(nid)
    safe: set = set()
    for nid, node in plan.nodes.items():
        if not isinstance(node.op, UnionOp):
            continue
        # (1) every transitive input is PEM-resident and non-blocking.
        stack, ok = list(node.inputs), True
        while stack and ok:
            i = stack.pop()
            iop = plan.nodes[i].op
            if isinstance(iop, (MemorySourceOp, EmptySourceOp)):
                continue
            if _is_blocking(iop) or isinstance(iop, UDTFSourceOp):
                ok = False
            else:  # Map/Filter: row-wise, keep walking up
                stack.extend(plan.nodes[i].inputs)
        if not ok:
            continue
        # (2) sole consumer chain through row-wise ops ends at a full agg.
        cur = nid
        while ok:
            outs = consumers.get(cur, [])
            if len(outs) != 1:
                ok = False
                break
            cop = plan.nodes[outs[0]].op
            if isinstance(cop, AggOp):
                break  # the splitter walk will make this a partial agg
            if _is_blocking(cop) or isinstance(cop, UDTFSourceOp):
                ok = False
                break
            cur = outs[0]
        if ok:
            safe.add(nid)
    return safe


class Splitter:
    """Splits one logical plan; ``registry`` resolves UDTF executor
    classes (udtf.h UDTFSourceExecutor -> which tier runs the source)."""

    def __init__(self, registry=None):
        self.registry = registry

    def _udtf_executor(self, op: UDTFSourceOp):
        from ...udf.udtf import UDTFExecutor

        if self.registry is None or not self.registry.has_udtf(op.name):
            return UDTFExecutor.ONE_KELVIN  # default: one merge instance
        return self.registry.get_udtf(op.name).executor

    def split(self, plan: Plan) -> BlockingSplitPlan:
        before, after = Plan(), Plan()
        bridges: list[BridgeSpec] = []
        data_tier = "pem"
        pushdown = _pushdown_unions(plan)
        # logical node id -> ('pem', new_id) | ('kelvin', new_id)
        placed: dict[int, tuple[str, int]] = {}

        def to_kelvin(nid: int) -> int:
            """Id of nid's output within after_blocking, bridging if the
            producer ran on the PEM side."""
            side, new_id = placed[nid]
            if side == "kelvin":
                return new_id
            bid = len(bridges)
            node = plan.nodes[nid]
            before.add(BridgeSinkOp(bid), [new_id])
            src = after.add(BridgeSourceOp(bid))
            if isinstance(node.op, AggOp):
                # Partial-op manager (AggOperatorMgr): the PEM half is a
                # partial agg, the bridge ships mergeable carries, and an
                # explicit finalize agg runs on the merge side.
                bridges.append(BridgeSpec(bid, AGG_STATE_MERGE))
                src = after.add(replace(node.op, mode="finalize"), [src])
            else:
                bridges.append(BridgeSpec(bid, ROW_GATHER))
            placed[nid] = ("kelvin", src)
            return src

        for nid in plan.topo_order():
            node = plan.nodes[nid]
            op = node.op
            inputs_kelvin = any(placed[i][0] == "kelvin" for i in node.inputs)
            if isinstance(op, (MemorySourceOp, EmptySourceOp)):
                placed[nid] = ("pem", before.add(op))
            elif isinstance(op, UDTFSourceOp):
                from ...udf.udtf import UDTFExecutor

                ex = self._udtf_executor(op)
                if ex in (UDTFExecutor.ALL_AGENTS, UDTFExecutor.ALL_PEM):
                    placed[nid] = ("pem", before.add(op))
                    if ex == UDTFExecutor.ALL_AGENTS:
                        data_tier = "all_agents"
                else:
                    placed[nid] = ("kelvin", after.add(op))
            elif isinstance(op, AggOp) and not inputs_kelvin:
                # Split: prepare (partial) stays on the PEM side; when the
                # result is consumed downstream it bridges as a carry
                # merge and the consumer reads finalized output.
                new_id = before.add(replace(op, mode="partial"), [
                    placed[i][1] for i in node.inputs
                ])
                placed[nid] = ("pem", new_id)
                to_kelvin(nid)  # aggs always bridge (their output is global)
            elif (isinstance(op, UnionOp) and nid in pushdown
                  and not inputs_kelvin):
                # Push-down: a PEM-safe union stays on the data tier so
                # the downstream agg takes the partial-split branch and
                # its bridge ships merge state, not the union's rows.
                placed[nid] = ("pem", before.add(
                    op, [placed[i][1] for i in node.inputs]
                ))
            elif isinstance(op, LimitOp) and not inputs_kelvin:
                # LimitOperatorMgr: local cap on each agent, global cap
                # after the gather.
                local = before.add(op, [placed[i][1] for i in node.inputs])
                placed[nid] = ("pem", local)
                src = to_kelvin(nid)
                placed[nid] = ("kelvin", after.add(op, [src]))
            elif _is_blocking(op) or inputs_kelvin:
                placed[nid] = (
                    "kelvin",
                    after.add(op, [to_kelvin(i) for i in node.inputs]),
                )
            else:  # Map/Filter fed only by PEM-side nodes
                placed[nid] = (
                    "pem",
                    before.add(op, [placed[i][1] for i in node.inputs]),
                )
        return BlockingSplitPlan(before, after, bridges, data_tier=data_tier)
