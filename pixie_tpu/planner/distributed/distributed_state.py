"""Live-cluster state consumed by the distributed planner.

Reference parity: ``distributedpb::DistributedState`` and ``CarnotInfo``
(``src/carnot/planner/distributedpb/distributed_plan.proto:48,102``) —
one entry per live agent, carrying its role (PEM processes data and has
local tables; Kelvin accepts remote data and runs merge fragments) and
table availability. The planner replans against this on every query
(elasticity: ``query_executor.go:415`` pulls it fresh per script).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class AgentInfo:
    """CarnotInfo analog for one agent."""

    agent_id: str
    processes_data: bool = True  # PEM: runs source fragments
    accepts_remote_sources: bool = False  # Kelvin: runs merge fragments
    # Tables this agent holds locally. None = unknown -> assume all
    # (the reference's schema-less default before metadata sync).
    tables: frozenset[str] | None = None
    asid: int = 0

    def has_table(self, name: str) -> bool:
        return self.tables is None or name in self.tables


@dataclass
class DistributedState:
    agents: list[AgentInfo] = field(default_factory=list)
    # Live agents excluded from planning by the tracker's flap
    # quarantine (services/tracker.py): visible for statusz/debugging,
    # never scheduled.
    quarantined: list[str] = field(default_factory=list)

    @property
    def pems(self) -> list[AgentInfo]:
        return [a for a in self.agents if a.processes_data]

    @property
    def kelvins(self) -> list[AgentInfo]:
        return [a for a in self.agents if a.accepts_remote_sources]

    def pems_with_table(self, table: str) -> list[AgentInfo]:
        return [a for a in self.pems if a.has_table(table)]

    @classmethod
    def homogeneous(cls, n_pems: int, n_kelvins: int = 1) -> "DistributedState":
        """Synthetic state for tests/benchmarks (the reference test idiom:
        fake CarnotInfos, no processes — distributed_planner_test.cc)."""
        agents = [AgentInfo(agent_id=f"pem-{i}", asid=i + 1) for i in range(n_pems)]
        agents += [
            AgentInfo(
                agent_id=f"kelvin-{i}",
                processes_data=False,
                accepts_remote_sources=True,
                asid=1000 + i,
            )
            for i in range(n_kelvins)
        ]
        return cls(agents=agents)
