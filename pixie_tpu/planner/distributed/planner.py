"""DistributedPlanner: split -> coordinate -> stitch.

Reference parity: ``planner/distributed/distributed_planner.h:66``
(DistributedPlanner::Plan) and the stitcher rules
(``distributed_stitcher_rules.h``) that wire each GRPCSink's destination
address to its GRPCSource. Here stitching assigns each bridge the mesh
axes its collective runs over: the ``agents`` axis within a slice (ICI),
plus the ``kelvin`` axis when a second reduction tier exists.
"""

from __future__ import annotations

from ...exec.plan import Plan
from ...parallel.mesh import AGENTS, KELVIN
from .coordinator import Coordinator, DistributedPlan
from .distributed_state import DistributedState
from .splitter import Splitter


class DistributedPlanner:
    """Combines splitter + coordinator + stitcher (logical_planner.h:40
    drives this from the query broker's compile path)."""

    def __init__(self, registry=None):
        self.splitter = Splitter(registry)
        self.coordinator = Coordinator()

    def plan(
        self, logical_plan: Plan, state: DistributedState, mesh=None,
        schemas=None, table_stats=None,
    ) -> DistributedPlan:
        split = self.splitter.split(logical_plan)
        dplan = self.coordinator.assign(split, state)
        self.stitch(dplan, state, mesh=mesh)
        # Always-on structural verification (pixie_tpu/analysis): bridge
        # sink/source/spec pairing, no blocking ops in the data
        # fragment, agg bridges feeding their finalize half — a bad
        # split fails HERE, not as a hung merge or a device error on an
        # agent. (The schema walk already ran on the logical plan in
        # compile_pxl; the broker re-checks dispatch sets per query.)
        from ...analysis.verifier import check_distributed_plan

        check_distributed_plan(dplan)
        # Resource-bound pass over the split (pxbound): per-agent data
        # fragment bounds + merge bounds with bridge rows seeded from
        # the data side x agent count + total wire bound. Attached as
        # dplan.resource_report; the broker folds it into the
        # predicted_cost its admission control schedules on. Optional:
        # callers without schemas (tests building raw splits) skip it.
        if schemas is not None:
            from ...analysis.bounds import distributed_bounds

            distributed_bounds(
                dplan, schemas, self.splitter.registry, table_stats,
                n_agents=max(len(dplan.data_agent_ids), 1),
            )
        return dplan

    def stitch(self, dplan: DistributedPlan, state: DistributedState, mesh=None) -> None:
        """Wire bridges to the mesh axes implementing them.

        When the executing ``mesh`` is known it is authoritative (a bridge
        folds over exactly the axes the mesh has, size>1); without one
        (planning-only use) axes are derived from the agent state.
        """
        if mesh is not None:
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            axes = (AGENTS,) + ((KELVIN,) if sizes.get(KELVIN, 1) > 1 else ())
        else:
            axes = (AGENTS,) + ((KELVIN,) if len(state.kelvins) > 1 else ())
        for b in dplan.split.bridges:
            b.axes = axes
