"""Coordinator: assign split fragments to live agents.

Reference parity: ``planner/distributed/coordinator/coordinator.h`` —
decides which agents run the data fragment (pruning sources an agent
cannot serve: ``prune_unavailable_sources_rule.h``) and which run the
merge fragment, deduplicating identical per-agent plans into clusters
(``plan_clusters.h``). On TPU a cluster maps to one SPMD program over
the mesh's ``agents`` axis — agents in one cluster are shards of a
single compiled executable, which is the XLA-native form of the
reference's plan-cluster dedup.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ...exec.plan import MemorySourceOp, Plan
from .distributed_state import AgentInfo, DistributedState
from .splitter import BlockingSplitPlan


class PlanningError(Exception):
    pass


def source_tables(plan: Plan) -> set[str]:
    return {
        n.op.table for n in plan.nodes.values() if isinstance(n.op, MemorySourceOp)
    }


def prune_unavailable_sources(
    plan: Plan, agent: AgentInfo
) -> tuple[bool, set[str]]:
    """(can_run_entire_fragment, missing_tables) for one agent.

    Reference: ``prune_unavailable_sources_rule.h`` removes sources (and
    dependent subtrees) an agent cannot serve. Fixed-shape SPMD wants
    identical programs per shard, so instead of rewriting per-agent plans
    we exclude the agent from the cluster: its shard simply isn't in the
    mesh (degraded mesh = reference's pruned plan).
    """
    missing = {t for t in source_tables(plan) if not agent.has_table(t)}
    return (not missing, missing)


@dataclass
class PlanCluster:
    """Agents sharing one SPMD data-fragment program (plan_clusters.h)."""

    agent_ids: tuple
    plan: Plan


@dataclass
class DistributedPlan:
    """Per-query physical assignment (distributedpb::DistributedPlan)."""

    split: BlockingSplitPlan
    clusters: list = field(default_factory=list)  # list[PlanCluster]
    kelvin_agent_ids: tuple = ()
    pruned_agent_ids: tuple = ()

    @property
    def merge_plan(self) -> Plan:
        return self.split.after_blocking

    @property
    def data_agent_ids(self) -> tuple:
        return tuple(a for c in self.clusters for a in c.agent_ids)

    @property
    def n_data_shards(self) -> int:
        return len(self.data_agent_ids)


class Coordinator:
    def assign(
        self, split: BlockingSplitPlan, state: DistributedState
    ) -> DistributedPlan:
        needed = source_tables(split.before_blocking)
        # The splitter already resolved the data tier (udtf.h executor
        # semantics: ALL_AGENTS fragments run on Kelvins too).
        candidates = (
            state.agents if split.data_tier == "all_agents" else state.pems
        )
        eligible, pruned = [], []
        for a in candidates:
            missing = {t for t in needed if not a.has_table(t)}
            (eligible if not missing else pruned).append(a.agent_id)
        if not eligible and needed:
            raise PlanningError(f"no live agent can serve {sorted(needed)}")
        kelvins = tuple(a.agent_id for a in state.kelvins)
        if not kelvins and len(split.after_blocking.nodes) > 0:
            # Degrade: a data agent doubles as the merge tier (the
            # reference runs Kelvin-less in standalone mode).
            kelvins = tuple(eligible[:1])
        clusters = (
            [PlanCluster(tuple(eligible), split.before_blocking)]
            if eligible and split.before_blocking.nodes
            else []
        )
        return DistributedPlan(
            split=split,
            clusters=clusters,
            kelvin_agent_ids=kelvins,
            pruned_agent_ids=tuple(pruned),
        )
