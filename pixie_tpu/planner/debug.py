"""Plan debugger: human-readable rendering of compiled plans + stats.

Reference parity: the planner's plan debugger / ``px debug plan`` dump
(``/root/reference/src/carnot/planner/compiler/...`` graphviz export and
``src/pixie_cli`` plan rendering). TPU-first difference: fragments are
whole jitted programs, so the rendering annotates which linear chains
fuse into one XLA program and, when analyze stats are attached, the
per-fragment stage wall times.
"""

from __future__ import annotations

from ..exec.plan import (
    AggOp,
    BridgeSinkOp,
    BridgeSourceOp,
    EmptySourceOp,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySourceOp,
    OTelExportSinkOp,
    Plan,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)


def _op_label(op) -> str:
    if isinstance(op, MemorySourceOp):
        cols = "*" if op.columns is None else ",".join(op.columns)
        rng = ""
        if op.start_time is not None or op.stop_time is not None:
            rng = f" time=[{op.start_time}, {op.stop_time})"
        return f"MemorySource table={op.table!r} cols={cols}{rng}"
    if isinstance(op, MapOp):
        exprs = ", ".join(f"{n}={e!r}" for n, e in op.exprs)
        return f"Map {exprs}"
    if isinstance(op, FilterOp):
        return f"Filter {op.predicate!r}"
    if isinstance(op, AggOp):
        aggs = ", ".join(
            f"{a.out_name}={a.uda_name}({', '.join(map(repr, a.args))})"
            for a in op.aggs
        )
        by = ",".join(op.group_cols) or "<global>"
        mode = "" if op.mode == "full" else f" mode={op.mode}"
        return f"Agg by=[{by}] {aggs} max_groups={op.max_groups}{mode}"
    if isinstance(op, JoinOp):
        return (
            f"Join how={op.how} left_on={list(op.left_on)} "
            f"right_on={list(op.right_on)}"
        )
    if isinstance(op, LimitOp):
        return f"Limit n={op.n}"
    if isinstance(op, UnionOp):
        return "Union (time-ordered)"
    if isinstance(op, UDTFSourceOp):
        args = ", ".join(f"{k}={v!r}" for k, v in op.args)
        return f"UDTFSource {op.name}({args})"
    if isinstance(op, EmptySourceOp):
        return f"EmptySource {[n for n, _ in op.relation_items]}"
    if isinstance(op, BridgeSinkOp):
        return f"BridgeSink id={op.bridge_id}"
    if isinstance(op, BridgeSourceOp):
        return f"BridgeSource id={op.bridge_id}"
    if isinstance(op, OTelExportSinkOp):
        return "OTelExportSink"
    if isinstance(op, ResultSinkOp):
        return f"ResultSink {op.name!r}"
    return type(op).__name__


def _fragment_breaks(plan: Plan) -> set:
    """Node ids that START a new fragment (sources, joins, unions, and
    any op consumed by >1 node — everything the engine materializes)."""
    consumers: dict[int, int] = {}
    for n in plan.nodes.values():
        for i in n.inputs:
            consumers[i] = consumers.get(i, 0) + 1
    breaks = set()
    for nid, node in plan.nodes.items():
        op = node.op
        if not isinstance(op, (MapOp, FilterOp, AggOp, LimitOp, ResultSinkOp)):
            breaks.add(nid)
        elif node.inputs and consumers.get(node.inputs[0], 0) > 1:
            breaks.add(nid)
    return breaks


def explain_plan(plan: Plan, stats=None) -> str:
    """Text tree of the plan, sinks last, annotated with fragment fusion.

    ``stats`` is an optional ``exec.analyze.QueryStats`` (from
    ``execute_plan(analyze=True)``) — per-fragment stage seconds are
    appended when given.
    """
    lines = []
    breaks = _fragment_breaks(plan)
    frag_stats = list(getattr(stats, "fragments", []) or [])
    fi = 0
    for nid in plan.topo_order():
        node = plan.nodes[nid]
        fused = nid not in breaks and node.inputs
        prefix = "  | " if fused else "  "  # "| " = fused into the chain above
        rel = ""
        if node.relation is not None:
            rel = f"  :: {node.relation}"
        lines.append(f"{prefix}[{nid}] {_op_label(node.op)}{rel}")
        if stats is not None and isinstance(node.op, AggOp) and fi < len(frag_stats):
            fs = frag_stats[fi]
            fi += 1
            stages = ", ".join(
                f"{k}={v.seconds * 1e3:.1f}ms"
                for k, v in sorted(fs.stages.items())
            )
            lines.append(
                f"  |    stats: windows={fs.windows} rows_in={fs.rows_in} "
                f"rows_out={fs.rows_out} {stages}"
            )
    header = f"Plan: {len(plan.nodes)} ops, sinks={plan.sinks()}"
    return "\n".join([header] + lines)


def explain_pxl(query: str, schemas: dict, registry=None) -> str:
    """Compile a PxL script and render its physical plan (px explain)."""
    from ..udf.registry import default_registry
    from .compiler import CompilerState, compile_pxl

    state = CompilerState(
        schemas=schemas, registry=registry or default_registry()
    )
    compiled = compile_pxl(query, state)
    return explain_plan(compiled.plan)
