"""The ``px`` namespace exposed to PxL scripts.

Reference parity: ``src/carnot/planner/objects/pixie_module.h:33``
(PixieModule: DataFrame, display/debug, now/time helpers, DurationNanos
and the other semantic-type constructors, uint128, and every registered
UDF/UDA surfaced as ``px.<name>``).
"""

from __future__ import annotations

import re

from ..types.dtypes import DataType
from .objects import (
    AggFuncMarker,
    ColumnExpr,
    DataFrameObj,
    Literal,
    PlanBuilder,
    PxLError,
    ScalarFuncMarker,
    as_expr,
)

_REL_TIME = re.compile(r"^\s*(-?\d+(?:\.\d+)?)\s*(ns|us|ms|s|m|h|d)\s*$")
_UNIT_NS = {
    "ns": 1,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60_000_000_000,
    "h": 3_600_000_000_000,
    "d": 86_400_000_000_000,
}


def parse_time(value, now_ns: int, lineno=None):
    """Resolve a PxL time argument to absolute nanoseconds.

    Strings are relative to now ('-30s', '-5m'); ints are absolute ns.
    Reference: the compiler's time-conversion analyzer rules
    (``compiler/analyzer/resolve_time_rule``-family).
    """
    if value is None:
        return None
    if isinstance(value, str):
        m = _REL_TIME.match(value)
        if not m:
            raise PxLError(
                f"cannot parse time {value!r} (want e.g. '-30s', '-5m')", lineno
            )
        return now_ns + int(float(m.group(1)) * _UNIT_NS[m.group(2)])
    if isinstance(value, (int, float)):
        return int(value)
    raise PxLError(f"invalid time argument {value!r}", lineno)


def _scale(ns_per_unit: int):
    def f(n):
        if isinstance(n, ColumnExpr):
            return n * ns_per_unit
        return int(n * ns_per_unit)

    return f


def _semantic_cast(name: str, dtype: DataType | None = None):
    """Semantic-type constructor: identity on values, annotation-only.

    Reference semantic types (``px.DurationNanos`` etc.) affect UI
    formatting, not computation; the engine relation keeps base dtypes.
    """

    def f(x=None):
        if x is None:
            raise PxLError(f"px.{name}() requires a value")
        return x

    f.__name__ = name
    return f


# Aggregate-capable names; True = also usable as a scalar in map context
# when the registry has a matching scalar overload.
_AGG_NAMES = {
    "count": False,
    "sum": False,
    "mean": False,
    "max": False,
    "min": False,
    "quantiles": False,
    "any": False,
    "count_distinct": False,
    "stddev": False,
    "variance": False,
}


class PxModule:
    """``import px`` — attribute access resolves helpers, semantic types,
    and registered UDF/UDA names."""

    def __init__(self, builder: PlanBuilder, now_ns: int):
        self._builder = builder
        self._now_ns = now_ns

    # -- dataframe lifecycle -------------------------------------------------
    def DataFrame(self, table=None, select=None, start_time=None,
                  end_time=None, **kwargs) -> DataFrameObj:
        if kwargs:
            raise PxLError(f"px.DataFrame: unknown arguments {sorted(kwargs)}")
        if not isinstance(table, str):
            raise PxLError("px.DataFrame requires table='name'")
        return self._builder.source(
            table,
            select=select,
            start_time=parse_time(start_time, self._now_ns),
            stop_time=parse_time(end_time, self._now_ns),
        )

    def display(self, df, name: str = "output"):
        self._builder.display(df, name)

    def to_table(self, df, name: str):
        """Persist a DataFrame's rows into the table store under ``name``
        (the MemorySink write-back; later queries can read the table)."""
        self._builder.to_table(df, name)

    def export(self, df, spec):
        """px.export(df, px.otel.Data(...)) — OTel exporter surface
        (``planner/objects/exporter.h``)."""
        self._builder.export_otel(df, spec)

    @property
    def otel(self):
        from .otel_module import OTelModule

        return OTelModule()

    def debug(self, df, name: str = "debug"):
        self._builder.display(df, "_" + name)

    # -- time helpers --------------------------------------------------------
    def now(self) -> int:
        return self._now_ns

    seconds = staticmethod(_scale(1_000_000_000))
    minutes = staticmethod(_scale(60_000_000_000))
    hours = staticmethod(_scale(3_600_000_000_000))
    days = staticmethod(_scale(86_400_000_000_000))
    millis = staticmethod(_scale(1_000_000))
    microseconds = staticmethod(_scale(1_000))

    def strptime(self, s: str, fmt: str) -> int:
        import datetime

        dt = datetime.datetime.strptime(s, fmt)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        return int(dt.timestamp() * 1_000_000_000)

    # -- misc constructors ---------------------------------------------------
    def uint128(self, s: str):
        import uuid

        return Literal(int(uuid.UUID(s)), DataType.UINT128)

    def equals_any(self, col, values):
        """col == values[0] or col == values[1] or ... (reference
        ``pixie_module.cc`` EqualsAny)."""
        if not values:
            raise PxLError("px.equals_any requires at least one value")
        out = None
        for v in values:
            term = col == v
            out = term if out is None else (out | term)
        return out

    def select(self, cond, if_true, if_false):
        return ScalarFuncMarker("select")(cond, if_true, if_false)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        # Semantic-type constructors (capitalized).
        if name in _SEMANTIC_TYPES:
            return _semantic_cast(name)
        reg = self._builder.registry
        if reg.has_udtf(name):
            return lambda **kw: self._builder.udtf_source(name, **kw)
        if name in _AGG_NAMES and reg.has_uda(name):
            return AggFuncMarker(name, has_scalar=reg.has_scalar(name))
        if reg.has_scalar(name):
            return ScalarFuncMarker(name)
        if reg.has_uda(name):
            return AggFuncMarker(name)
        raise PxLError(
            f"px has no attribute {name!r} (not a registered function)"
        )


_SEMANTIC_TYPES = frozenset({
    "DurationNanos", "Percent", "Bytes", "Time", "Duration",
    "Service", "Pod", "Node", "Namespace", "Container", "UPID",
    "Port", "IPAddress", "Status",
})
