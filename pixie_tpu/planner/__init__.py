"""PxL frontend: compile PxL (a Pythonic pandas-like DSL) to exec Plans.

Reference parity: ``src/carnot/planner/`` — parser (libpypa there, CPython
``ast`` here), ASTVisitor + QLObject model (``compiler/ast_visitor.h:75``,
``objects/dataframe.h:40``), typed IR with analyzer/optimizer rule batches
(``compiler/analyzer/``, ``compiler/optimizer/``), and the logical planner
facade (``logical_planner.h:40``).

TPU-first contrast: the reference compiles PxL to a protobuf plan shipped
to C++ exec nodes; here the compiler emits the exec-layer ``Plan`` DAG
directly, and the fragment compiler turns maximal linear chains of it into
single jitted XLA programs.
"""

from .compiler import CompiledScript, CompilerState, compile_mutations, compile_pxl
from .objects import PxLError

__all__ = ["CompiledScript", "CompilerState", "compile_mutations", "compile_pxl", "PxLError"]
