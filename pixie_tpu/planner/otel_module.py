"""The ``px.otel`` namespace: export configuration objects.

Reference parity: ``src/carnot/planner/objects/otel.h:35`` (OTelModule:
``px.otel.Data``, ``px.otel.metric.Gauge/Summary``,
``px.otel.trace.Span``, ``px.otel.Endpoint``) consumed by
``px.export(df, ...)`` (``exporter.h``).
"""

from __future__ import annotations

from ..exec.otel import (
    OTelDataSpec,
    OTelEndpointConfig,
    OTelMetricGauge,
    OTelMetricSummary,
    OTelSpan,
)
from ..exec.plan import ColumnRef
from .objects import ColumnExpr, PxLError


def _colname(v, what: str):
    if isinstance(v, ColumnExpr) and isinstance(v.expr, ColumnRef):
        return v.expr.name
    if isinstance(v, str):
        return v
    raise PxLError(
        f"{what} must be a plain dataframe column (df.col), got {v!r}"
    )


def _attr_pairs(attributes, what: str):
    return tuple(
        (k, _colname(v, f"{what} attribute {k!r}"))
        for k, v in (attributes or {}).items()
    )


class _MetricNamespace:
    def Gauge(self, name, value, attributes=None, unit="", description=""):
        return OTelMetricGauge(
            name=name,
            value_column=_colname(value, "Gauge value"),
            attributes=_attr_pairs(attributes, "Gauge"),
            unit=unit,
            description=description,
        )

    def Summary(
        self,
        name,
        count,
        quantile_values=None,
        attributes=None,
        unit="",
        description="",
    ):
        return OTelMetricSummary(
            name=name,
            count_column=_colname(count, "Summary count"),
            quantile_columns=tuple(
                (float(q), _colname(c, f"Summary quantile {q}"))
                for q, c in (quantile_values or {}).items()
            ),
            attributes=_attr_pairs(attributes, "Summary"),
            unit=unit,
            description=description,
        )


class _TraceNamespace:
    def Span(self, name, start_time, end_time, attributes=None):
        name_is_col = isinstance(name, ColumnExpr)
        return OTelSpan(
            name=_colname(name, "Span name") if name_is_col else str(name),
            start_time_column=_colname(start_time, "Span start_time"),
            end_time_column=_colname(end_time, "Span end_time"),
            attributes=_attr_pairs(attributes, "Span"),
            name_is_column=name_is_col,
        )


class OTelModule:
    def __init__(self):
        self.metric = _MetricNamespace()
        self.trace = _TraceNamespace()

    def Endpoint(self, url="", headers=None, insecure=False):
        return OTelEndpointConfig(
            url=url,
            headers=tuple(sorted((headers or {}).items())),
            insecure=insecure,
        )

    def Data(self, endpoint=None, resource=None, data=None):
        res = []
        for k, v in (resource or {}).items():
            if isinstance(v, ColumnExpr):
                res.append((k, ("column", _colname(v, f"resource {k!r}"))))
            else:
                res.append((k, str(v)))
        return OTelDataSpec(
            endpoint=endpoint or OTelEndpointConfig(),
            resource=tuple(res),
            data=tuple(data or ()),
        )
