"""Compiler driver: PxL source -> analyzed exec Plan.

Reference parity: ``src/carnot/planner/compiler/compiler.h:39``
(Compiler::CompileToIR: parse -> ASTVisitor -> IR -> Analyze -> Optimize)
plus the LogicalPlanner facade (``planner/logical_planner.h:40``). The
distributed step (per-agent plan splitting) is the DistributedEngine's
shard_map compilation; see ``pixie_tpu.parallel``.
"""

from __future__ import annotations

import ast
import time
from dataclasses import dataclass, field

from ..exec.plan import Plan
from .ast_visitor import ASTVisitor
from .objects import PlanBuilder, PxLError
from .px_module import PxModule
from .rules import run_rules


@dataclass
class CompilerState:
    """Per-query compile inputs (reference:
    ``planner/compiler_state/compiler_state.h`` — schemas, time, max
    output rows, registry info)."""

    schemas: dict  # table name -> Relation
    registry: object
    now_ns: int = 0
    max_output_rows: int = 10_000
    max_groups: int = 4096
    # Ingest-sketch statistics per table (``table_store/sketches.py``):
    # {table: {"rows": int, "ndv": {col: estimated distinct values}}}.
    # Optimizer rules consult them (e.g. eager aggregation sizes its
    # partial agg's group capacity from the join key's NDV instead of a
    # blind default that climbs the overflow-doubling ladder at run
    # time). Estimates only — never correctness-bearing.
    table_stats: dict = field(default_factory=dict)

    def __post_init__(self):
        if not self.now_ns:
            self.now_ns = time.time_ns()


@dataclass
class CompiledScript:
    plan: Plan
    outputs: list  # sink names in display order
    funcs: dict = field(default_factory=dict)  # module-level PxL functions
    # Tracepoint deployments/deletes from pxtrace (mutation scripts;
    # planner CompileMutations analog). A script may carry both mutations
    # and a query plan — the broker deploys, waits for readiness, then
    # runs the plan (mutation_executor.go:84).
    mutations: list = field(default_factory=list)
    # Export sinks (px.export) have no named output; callers must not
    # treat outputs == [] as "nothing to execute" when this is non-zero.
    n_exports: int = 0


def parse_pxl(query: str) -> ast.Module:
    """Parse PxL source (reference wraps libpypa, ``parser/parser.h:45``;
    PxL is Python-shaped so CPython's ast is the natural parser here)."""
    try:
        return ast.parse(query)
    except SyntaxError as e:
        raise PxLError(f"syntax error: {e.msg}", e.lineno)


def compile_mutations(query: str, state: CompilerState) -> list:
    """Extract pxtrace mutations without requiring the query phase to
    compile (planner CompileMutations / cgo PlannerCompileMutations
    analog): a mutation script may query the very table its tracepoint
    creates, which only exists after deployment — so extraction is
    best-effort past the mutation statements."""
    tree = parse_pxl(query)
    builder = PlanBuilder(
        plan=Plan(),
        schemas=dict(state.schemas),
        registry=state.registry,
        max_groups=state.max_groups,
    )
    px = PxModule(builder, state.now_ns)
    visitor = ASTVisitor(px)
    # Statement-at-a-time: query-phase statements may fail (their tables
    # deploy only after the mutations run) without hiding mutation
    # statements that follow them.
    for stmt in tree.body:
        try:
            visitor.exec_stmt(stmt, visitor.module_scope)
        except Exception:
            continue
    return list(visitor._pxtrace.mutations) if visitor._pxtrace else []


def compile_pxl(query: str, state: CompilerState) -> CompiledScript:
    # Telemetry feedback resolution (services/telemetry.py): the engine
    # exposes OBSERVED per-script cardinalities from past runs under
    # table_stats["__observed__"] keyed by script hash; resolve THIS
    # script's entry so optimizer rules can consult it without knowing
    # the script (arXiv:2102.02440 — observed stats over estimates).
    observed = state.table_stats.get("__observed__")
    if observed:
        import hashlib

        ent = observed.get(
            hashlib.sha256(query.encode()).hexdigest()[:12]
        )
        if ent:
            state.table_stats = {
                **state.table_stats, "__observed_self__": dict(ent),
            }
    tree = parse_pxl(query)
    builder = PlanBuilder(
        plan=Plan(),
        schemas=dict(state.schemas),
        registry=state.registry,
        max_groups=state.max_groups,
    )
    px = PxModule(builder, state.now_ns)
    visitor = ASTVisitor(px)
    visitor.run(tree)
    mutations = list(visitor._pxtrace.mutations) if visitor._pxtrace else []
    if (not builder.sinks and not builder.n_exports
            and not builder.n_table_sinks and not mutations):
        raise PxLError(
            "script produced no output tables; call px.display(df), "
            "px.to_table(df, name), or "
            "px.export(df, ...) (or the script only defines functions — "
            "call one and display its result)"
        )
    run_rules(builder.plan, state.max_output_rows,
              table_stats=state.table_stats)
    # Always-on static verification (see pixie_tpu/analysis): schema
    # propagation + column/dtype binding + topology invariants over the
    # rewritten plan, so a bad plan fails HERE with node provenance
    # instead of as a device-side shape error mid-query. Raises
    # PlanCheckError (a PxLError) on any error-severity finding; clean
    # verifications memoize on (script, schemas, registry) — repeat
    # compiles of one script re-verify for free.
    from ..analysis.verifier import check_script_plan

    check_script_plan(
        builder.plan, query, builder.schemas, state.registry,
        plan_params=(state.max_output_rows, state.max_groups),
    )
    # Resource-bound pass (pixie_tpu/analysis/bounds.py, pxbound):
    # abstract interpretation of per-node row/byte/group bounds seeded
    # from the ingest sketches in state.table_stats. Enforces the
    # (default-off) compile-time budgets, pre-sizes aggregate group
    # capacity to the NDV bound, and attaches the PlanResourceReport to
    # the plan — the engine pre-sizes join buffers from it and the
    # broker schedules admission on its predicted_cost. Raises
    # PlanCheckError (a PxLError) when a budget flag is on and the
    # prediction exceeds it; sketch-less plans are never rejected.
    from ..analysis.bounds import apply_plan_bounds

    apply_plan_bounds(
        builder.plan, builder.schemas, state.registry, state.table_stats,
        script=query,
        plan_params=(state.max_output_rows, state.max_groups),
    )
    return CompiledScript(
        plan=builder.plan, outputs=list(builder.sinks), funcs=visitor.funcs,
        mutations=mutations, n_exports=builder.n_exports,
    )
