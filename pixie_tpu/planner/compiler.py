"""Compiler driver: PxL source -> analyzed exec Plan.

Reference parity: ``src/carnot/planner/compiler/compiler.h:39``
(Compiler::CompileToIR: parse -> ASTVisitor -> IR -> Analyze -> Optimize)
plus the LogicalPlanner facade (``planner/logical_planner.h:40``). The
distributed step (per-agent plan splitting) is the DistributedEngine's
shard_map compilation; see ``pixie_tpu.parallel``.
"""

from __future__ import annotations

import ast
import time
from dataclasses import dataclass, field

from ..exec.plan import Plan
from .ast_visitor import ASTVisitor
from .objects import PlanBuilder, PxLError
from .px_module import PxModule
from .rules import run_rules


@dataclass
class CompilerState:
    """Per-query compile inputs (reference:
    ``planner/compiler_state/compiler_state.h`` — schemas, time, max
    output rows, registry info)."""

    schemas: dict  # table name -> Relation
    registry: object
    now_ns: int = 0
    max_output_rows: int = 10_000
    max_groups: int = 4096

    def __post_init__(self):
        if not self.now_ns:
            self.now_ns = time.time_ns()


@dataclass
class CompiledScript:
    plan: Plan
    outputs: list  # sink names in display order
    funcs: dict = field(default_factory=dict)  # module-level PxL functions


def parse_pxl(query: str) -> ast.Module:
    """Parse PxL source (reference wraps libpypa, ``parser/parser.h:45``;
    PxL is Python-shaped so CPython's ast is the natural parser here)."""
    try:
        return ast.parse(query)
    except SyntaxError as e:
        raise PxLError(f"syntax error: {e.msg}", e.lineno)


def compile_pxl(query: str, state: CompilerState) -> CompiledScript:
    tree = parse_pxl(query)
    builder = PlanBuilder(
        plan=Plan(),
        schemas=dict(state.schemas),
        registry=state.registry,
        max_groups=state.max_groups,
    )
    px = PxModule(builder, state.now_ns)
    visitor = ASTVisitor(px)
    visitor.run(tree)
    if not builder.sinks and not builder.n_exports:
        raise PxLError(
            "script produced no output tables; call px.display(df) or "
            "px.export(df, ...) (or the script only defines functions — "
            "call one and display its result)"
        )
    run_rules(builder.plan, state.max_output_rows)
    return CompiledScript(
        plan=builder.plan, outputs=list(builder.sinks), funcs=visitor.funcs
    )
