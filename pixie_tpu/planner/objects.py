"""Compile-time object model (QLObjects) for the PxL frontend.

Reference parity: ``src/carnot/planner/objects/`` — ``Dataframe``
(``dataframe.h:40``: merge/groupby/agg/head/drop/append + subscript
filter/projection), column expressions, and the metadata ``ctx`` accessor
(``planner/metadata/metadata_handler.h:72``).

The AST visitor evaluates PxL statements against these objects; dataframe
methods append operators to the exec ``Plan`` under construction and track
the resolved ``Relation`` (the reference defers typing to analyzer rules;
here schemas are known at compile time, so resolution is eager).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..exec.plan import (
    AggExpr,
    AggOp,
    ColumnRef,
    Expr,
    FilterOp,
    FuncCall,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySourceOp,
    Plan,
    ResultSinkOp,
    UDTFSourceOp,
    UnionOp,
)
from ..types.dtypes import DataType
from ..types.relation import Relation
from ..udf.udf import SignatureError


class PxLError(Exception):
    """Compile error with source location when available."""

    def __init__(self, msg: str, lineno: Optional[int] = None):
        self.raw_msg = msg
        self.lineno = lineno
        super().__init__(f"line {lineno}: {msg}" if lineno else msg)


def infer_type(expr: Expr, relation: Relation, registry) -> DataType:
    """Resolve an expression's type against a relation (planner-side
    mirror of the exec binder; reference: resolver_types_rule)."""
    if isinstance(expr, ColumnRef):
        if not relation.has_column(expr.name):
            raise PxLError(f"column {expr.name!r} does not exist in {relation}")
        return relation.col_type(expr.name)
    if isinstance(expr, Literal):
        return expr.dtype
    if isinstance(expr, FuncCall):
        arg_types = [infer_type(a, relation, registry) for a in expr.args]
        try:
            return registry.get_scalar(expr.name, arg_types).return_type
        except SignatureError as e:
            raise PxLError(str(e))
    raise PxLError(f"cannot type expression {expr!r}")


def py_to_literal(value, lineno=None) -> Literal:
    if isinstance(value, Literal):
        return value
    if isinstance(value, bool):
        return Literal(value, DataType.BOOLEAN)
    if isinstance(value, int):
        return Literal(value, DataType.INT64)
    if isinstance(value, float):
        return Literal(value, DataType.FLOAT64)
    if isinstance(value, str):
        return Literal(value, DataType.STRING)
    raise PxLError(f"cannot use {type(value).__name__} value {value!r} in an "
                   "expression", lineno)


def as_expr(value) -> Expr:
    if isinstance(value, ColumnExpr):
        return value.expr
    if isinstance(value, Expr):
        return value
    return py_to_literal(value)


def _owner_df(*values):
    for v in values:
        if isinstance(v, ColumnExpr) and v.df is not None:
            return v.df
    return None


class ColumnExpr:
    """A lazily-built scalar expression over one dataframe's columns."""

    def __init__(self, expr: Expr, df: Optional["DataFrameObj"]):
        self.expr = expr
        self.df = df

    def __repr__(self):
        return f"ColumnExpr({self.expr!r})"

    def __bool__(self):
        raise PxLError(
            "a column expression has no compile-time truth value; use it in "
            "df[...] / assignments, or combine with 'and'/'or'"
        )

    def _bin(self, other, name, reverse=False):
        df = _owner_df(self, other)
        if isinstance(other, ColumnExpr) and other.df is not None and \
                self.df is not None and other.df is not self.df:
            raise PxLError(
                "cannot combine columns from two different dataframes; "
                "merge them first"
            )
        a, b = self.expr, as_expr(other)
        if reverse:
            a, b = b, a
        return ColumnExpr(FuncCall(name, (a, b)), df)

    def __add__(self, o):
        return self._bin(o, "add")

    def __radd__(self, o):
        return self._bin(o, "add", reverse=True)

    def __sub__(self, o):
        return self._bin(o, "subtract")

    def __rsub__(self, o):
        return self._bin(o, "subtract", reverse=True)

    def __mul__(self, o):
        return self._bin(o, "multiply")

    def __rmul__(self, o):
        return self._bin(o, "multiply", reverse=True)

    def __truediv__(self, o):
        return self._bin(o, "divide")

    def __rtruediv__(self, o):
        return self._bin(o, "divide", reverse=True)

    def __mod__(self, o):
        return self._bin(o, "modulo")

    def __pow__(self, o):
        return self._bin(o, "pow")

    def __eq__(self, o):  # noqa: A003 - PxL semantics, not identity
        return self._bin(o, "equal")

    def __ne__(self, o):
        return self._bin(o, "notEqual")

    def __lt__(self, o):
        return self._bin(o, "lessThan")

    def __le__(self, o):
        return self._bin(o, "lessThanEqual")

    def __gt__(self, o):
        return self._bin(o, "greaterThan")

    def __ge__(self, o):
        return self._bin(o, "greaterThanEqual")

    def __and__(self, o):
        return self._bin(o, "logicalAnd")

    def __rand__(self, o):
        return self._bin(o, "logicalAnd", reverse=True)

    def __or__(self, o):
        return self._bin(o, "logicalOr")

    def __ror__(self, o):
        return self._bin(o, "logicalOr", reverse=True)

    def __invert__(self):
        return ColumnExpr(FuncCall("logicalNot", (self.expr,)), self.df)

    def __neg__(self):
        return ColumnExpr(FuncCall("negate", (self.expr,)), self.df)

    __hash__ = None  # __eq__ builds expressions; not hashable


@dataclass(frozen=True)
class ScalarFuncMarker:
    """``px.floor``-style callable: builds a FuncCall when applied."""

    name: str

    def __call__(self, *args):
        df = _owner_df(*args)
        return ColumnExpr(FuncCall(self.name, tuple(as_expr(a) for a in args)), df)


@dataclass(frozen=True)
class AggFuncMarker:
    """``px.mean``-style marker used inside .agg(out=(col, px.mean)).

    Several names (count/mean/max/...) are also callable as scalar funcs
    in map context when the registry has a scalar overload.
    """

    name: str
    has_scalar: bool = False

    def __call__(self, *args):
        if not self.has_scalar:
            raise PxLError(
                f"px.{self.name} is an aggregate; use it inside "
                f".agg(out=('col', px.{self.name}))"
            )
        return ScalarFuncMarker(self.name)(*args)


DF_METHODS = frozenset({"groupby", "agg", "merge", "head", "drop", "append", "stream"})
DF_ATTRS = frozenset({"ctx", "columns"})


class DataFrameObj:
    """The PxL ``DataFrame`` object: lazy operator-DAG builder.

    Mutable by design: ``df.col = expr`` appends a Map operator and
    advances this object's plan node in place (matching PxL's pandas-like
    mutation semantics; reference ``objects/dataframe.cc``).
    """

    def __init__(self, builder: "PlanBuilder", node_id: int, relation: Relation):
        self.builder = builder
        self.node_id = node_id
        self.relation = relation

    # -- column access -------------------------------------------------------
    def col(self, name: str, lineno=None) -> ColumnExpr:
        if not self.relation.has_column(name):
            raise PxLError(
                f"column {name!r} does not exist; available: "
                f"{list(self.relation.column_names)}", lineno
            )
        return ColumnExpr(ColumnRef(name), self)

    def resolve_expr(self, value, what="expression", lineno=None) -> Expr:
        if isinstance(value, ColumnExpr):
            if value.df is not None and value.df is not self:
                raise PxLError(
                    f"{what} references columns of a different dataframe", lineno
                )
            return value.expr
        return as_expr(value)

    # -- operators -----------------------------------------------------------
    def _advance(self, op, relation, extra_inputs=()):
        nid = self.builder.plan.add(
            op, [self.node_id, *extra_inputs], relation=relation
        )
        return DataFrameObj(self.builder, nid, relation)

    def set_column(self, name: str, value, lineno=None):
        """df.name = value — Map keeping all columns, adding/replacing one."""
        expr = self.resolve_expr(value, what=f"assignment to {name!r}", lineno=lineno)
        dt = infer_type(expr, self.relation, self.builder.registry)
        exprs = []
        replaced = False
        for c, _t in self.relation.items():
            if c == name:
                exprs.append((name, expr))
                replaced = True
            else:
                exprs.append((c, ColumnRef(c)))
        if not replaced:
            exprs.append((name, expr))
        items = [(c, dt if c == name else self.relation.col_type(c))
                 for c, _ in exprs]
        new = self._advance(MapOp(exprs=tuple(exprs)), Relation(items))
        # In-place mutation: the variable keeps pointing at this object.
        self.node_id, self.relation = new.node_id, new.relation

    def project(self, names, lineno=None) -> "DataFrameObj":
        for n in names:
            if not isinstance(n, str):
                raise PxLError(f"projection list must contain column names, "
                               f"got {n!r}", lineno)
            if not self.relation.has_column(n):
                raise PxLError(f"column {n!r} does not exist in {self.relation}",
                               lineno)
        exprs = tuple((n, ColumnRef(n)) for n in names)
        rel = Relation([(n, self.relation.col_type(n)) for n in names])
        return self._advance(MapOp(exprs=exprs), rel)

    def filter(self, cond: ColumnExpr, lineno=None) -> "DataFrameObj":
        expr = self.resolve_expr(cond, what="filter predicate", lineno=lineno)
        dt = infer_type(expr, self.relation, self.builder.registry)
        if dt != DataType.BOOLEAN:
            raise PxLError(f"filter predicate has type {dt.name}, want BOOLEAN",
                           lineno)
        return self._advance(FilterOp(predicate=expr), self.relation)

    def head(self, n: int = 5, lineno=None) -> "DataFrameObj":
        if not isinstance(n, int) or n < 0:
            raise PxLError(f"head() expects a non-negative int, got {n!r}", lineno)
        return self._advance(LimitOp(n), self.relation)

    def drop(self, columns, lineno=None) -> "DataFrameObj":
        if isinstance(columns, str):
            columns = [columns]
        for c in columns:
            if not self.relation.has_column(c):
                raise PxLError(f"cannot drop missing column {c!r}", lineno)
        keep = [c for c in self.relation.column_names if c not in set(columns)]
        return self.project(keep, lineno)

    def groupby(self, by, lineno=None) -> "GroupbyObj":
        cols = [by] if isinstance(by, str) else list(by)
        for c in cols:
            if not isinstance(c, str) or not self.relation.has_column(c):
                raise PxLError(f"groupby column {c!r} does not exist", lineno)
        return GroupbyObj(self, tuple(cols))

    def agg(self, lineno=None, **kwargs) -> "DataFrameObj":
        return GroupbyObj(self, ()).agg(lineno=lineno, **kwargs)

    def merge(self, right, how="inner", left_on=None, right_on=None,
              suffixes=("", "_x"), lineno=None) -> "DataFrameObj":
        if not isinstance(right, DataFrameObj):
            raise PxLError("merge() right side must be a DataFrame", lineno)
        if right.builder is not self.builder:
            raise PxLError("cannot merge dataframes from different scripts", lineno)
        if left_on is None or right_on is None:
            raise PxLError("merge() requires left_on= and right_on=", lineno)
        lo = [left_on] if isinstance(left_on, str) else list(left_on)
        ro = [right_on] if isinstance(right_on, str) else list(right_on)
        if len(lo) != len(ro):
            raise PxLError("merge() left_on/right_on length mismatch", lineno)
        for c in lo:
            if not self.relation.has_column(c):
                raise PxLError(f"merge left_on column {c!r} missing", lineno)
        for c in ro:
            if not right.relation.has_column(c):
                raise PxLError(f"merge right_on column {c!r} missing", lineno)
        if how not in ("inner", "left", "right", "outer"):
            raise PxLError(
                f"merge how={how!r} unsupported "
                "(inner/left/right/outer)", lineno)
        suffixes = tuple(suffixes)
        if suffixes and suffixes[0] != "":
            raise PxLError("merge suffixes must keep the left side unsuffixed "
                           "(['', '_x'])", lineno)
        suffix = suffixes[1] if len(suffixes) > 1 else "_x"
        out_rel = self.relation.merge(
            right.relation.select(
                [c for c in right.relation.column_names if c not in set(ro)]
            ),
            suffix=suffix,
        )
        op = JoinOp(left_on=tuple(lo), right_on=tuple(ro), how=how, suffix=suffix)
        return self._advance(op, out_rel, extra_inputs=(right.node_id,))

    def append(self, other, lineno=None) -> "DataFrameObj":
        if not isinstance(other, DataFrameObj):
            raise PxLError("append() expects a DataFrame", lineno)
        if tuple(other.relation.column_names) != tuple(self.relation.column_names):
            raise PxLError(
                f"append() schema mismatch: {list(self.relation.column_names)} "
                f"vs {list(other.relation.column_names)}", lineno)
        return self._advance(UnionOp(), self.relation,
                             extra_inputs=(other.node_id,))

    def stream(self, lineno=None) -> "DataFrameObj":
        # Streaming is the engine's execution mode, not a plan property.
        return self

    @property
    def ctx(self) -> "CtxAccessor":
        return CtxAccessor(self)

    @property
    def columns(self):
        return list(self.relation.column_names)

    def __repr__(self):
        return f"DataFrame(node={self.node_id}, {self.relation})"


@dataclass
class GroupbyObj:
    df: DataFrameObj
    by: tuple

    def agg(self, lineno=None, **kwargs) -> DataFrameObj:
        if not kwargs:
            raise PxLError("agg() requires at least one out=('col', px.fn)",
                           lineno)
        aggs = []
        registry = self.df.builder.registry
        for out_name, spec in kwargs.items():
            if not (isinstance(spec, tuple) and len(spec) >= 2):
                raise PxLError(
                    f"agg {out_name}= must be a ('column', px.fn[, args...]) "
                    "tuple", lineno)
            col, fn, *extra = spec
            if isinstance(fn, ScalarFuncMarker):
                fn = AggFuncMarker(fn.name)
            if not isinstance(fn, AggFuncMarker):
                raise PxLError(
                    f"agg {out_name}=: second element must be a px aggregate "
                    f"function, got {fn!r}", lineno)
            if isinstance(col, str):
                arg = self.df.col(col, lineno).expr
            else:
                arg = self.df.resolve_expr(col, what=f"agg {out_name}", lineno=lineno)
            # Extra positional args for multi-arg UDAs, e.g.
            # out=('lat', px.kmeans, 2) (ml_ops.h KMeansUDA's k).
            args = [arg] + [
                self.df.resolve_expr(e, what=f"agg {out_name}", lineno=lineno)
                for e in extra
            ]
            arg_ts = [
                infer_type(a, self.df.relation, registry) for a in args
            ]
            try:
                uda = registry.get_uda(fn.name, arg_ts)
            except SignatureError as e:
                raise PxLError(str(e), lineno)
            aggs.append((AggExpr(out_name, fn.name, tuple(args)), uda.return_type))

        items = [(c, self.df.relation.col_type(c)) for c in self.by]
        items += [(ae.out_name, rt) for ae, rt in aggs]
        op = AggOp(
            group_cols=self.by,
            aggs=tuple(ae for ae, _ in aggs),
            max_groups=self.df.builder.max_groups,
        )
        return self.df._advance(op, Relation(items))


class CtxAccessor:
    """``df.ctx['service']`` — resolve k8s metadata to UDF calls.

    Reference: ``planner/metadata/metadata_handler.h:72`` maps metadata
    properties to ``upid_to_*`` UDFs keyed on the ``upid`` column.
    """

    def __init__(self, df: DataFrameObj):
        self.df = df

    def __getitem__(self, key: str) -> ColumnExpr:
        from ..metadata.resolver import resolve_ctx  # cycle-free at call time

        return resolve_ctx(self.df, key)


@dataclass
class PlanBuilder:
    """Shared compile state: the plan under construction + schemas."""

    plan: Plan
    schemas: dict  # table name -> Relation
    registry: object
    max_groups: int = 4096
    sinks: list = field(default_factory=list)  # output names in display order
    n_exports: int = 0  # OTel export sinks (outputs without a name)
    n_table_sinks: int = 0  # table write-backs (px.to_table)

    def source(self, table: str, select=None, start_time=None, stop_time=None,
               lineno=None) -> DataFrameObj:
        if table not in self.schemas:
            raise PxLError(
                f"table {table!r} does not exist; available: "
                f"{sorted(self.schemas)}", lineno)
        rel = self.schemas[table]
        op = MemorySourceOp(table=table, columns=None,
                            start_time=start_time, stop_time=stop_time)
        nid = self.plan.add(op, [], relation=rel)
        df = DataFrameObj(self, nid, rel)
        if select is not None:
            df = df.project(list(select), lineno)
        return df

    def udtf_source(self, name: str, lineno=None, **kwargs) -> DataFrameObj:
        """px.<UDTFName>(...) -> DataFrame (udtf.h source surface)."""
        from ..types.relation import Relation as _Relation

        udtf = self.registry.get_udtf(name)
        declared = {e[0] for e in udtf.init_args}
        unknown = set(kwargs) - declared
        if unknown:
            raise PxLError(
                f"px.{name}: unknown arguments {sorted(unknown)}; "
                f"declared: {sorted(declared)}", lineno)
        # Required-arg + type check at compile time (udtf.h checks init
        # args during planning, not at the remote source node). Required-
        # ness comes from the declaration — (name, type) is required,
        # (name, type, default) optional — never from fn introspection.
        for entry in udtf.init_args:
            arg_name, arg_type = entry[0], entry[1]
            if udtf.arg_required(arg_name) and arg_name not in kwargs:
                raise PxLError(
                    f"px.{name}: missing required argument {arg_name!r}", lineno
                )
            if arg_name in kwargs:
                v = kwargs[arg_name]
                ok = (
                    isinstance(v, bool)
                    if arg_type == DataType.BOOLEAN
                    else isinstance(v, int) and not isinstance(v, bool)
                    if arg_type in (DataType.INT64, DataType.TIME64NS)
                    else isinstance(v, (int, float)) and not isinstance(v, bool)
                    if arg_type == DataType.FLOAT64
                    else isinstance(v, str)
                    if arg_type == DataType.STRING
                    else True
                )
                if not ok:
                    raise PxLError(
                        f"px.{name}: argument {arg_name!r} must be "
                        f"{arg_type.name}, got {type(v).__name__}", lineno)
        rel = _Relation(list(udtf.relation))
        op = UDTFSourceOp(name=name, args=tuple(sorted(kwargs.items())))
        nid = self.plan.add(op, [], relation=rel)
        return DataFrameObj(self, nid, rel)

    def display(self, df: DataFrameObj, name: str = "output", lineno=None):
        if not isinstance(df, DataFrameObj):
            raise PxLError("px.display() expects a DataFrame", lineno)
        if name in self.sinks:
            raise PxLError(f"duplicate output table name {name!r}", lineno)
        self.plan.add(ResultSinkOp(name), [df.node_id])
        self.sinks.append(name)

    def to_table(self, df: DataFrameObj, name: str, lineno=None):
        """Write df back into the table store (MemorySink write-back)."""
        from ..exec.plan import TableSinkOp

        if not isinstance(df, DataFrameObj):
            raise PxLError("px.to_table() expects a DataFrame", lineno)
        if not isinstance(name, str) or not name:
            raise PxLError("px.to_table() needs a table name", lineno)
        self.plan.add(TableSinkOp(name), [df.node_id])
        self.n_table_sinks += 1

    def export_otel(self, df: DataFrameObj, spec, lineno=None):
        from ..exec.plan import OTelExportSinkOp

        if not isinstance(df, DataFrameObj):
            raise PxLError("px.export() expects a DataFrame", lineno)
        missing = {
            c
            for c in spec.referenced_columns()
            if not df.relation.has_column(c)
        }
        if missing:
            raise PxLError(
                f"px.export: columns {sorted(missing)} not in dataframe "
                f"{df.relation}", lineno)
        self.plan.add(OTelExportSinkOp(spec), [df.node_id])
        self.n_exports += 1
