"""PxL AST evaluator.

Reference parity: ``src/carnot/planner/compiler/ast_visitor.h:75``
(ASTVisitorImpl::ProcessModuleNode) — walks the Python AST and evaluates
module-level dataflow into QLObjects, never executing user code with the
host interpreter's semantics. PxL is Python-shaped but restricted: the
statement/expression whitelist below IS the language definition.

Scripts manipulate two kinds of values:
- host values (ints, strings, lists, ...) evaluated at compile time —
  loop bounds, window sizes, flags;
- deferred values (ColumnExpr, DataFrameObj) that build the operator DAG.
"""

from __future__ import annotations

import ast
import operator
from dataclasses import dataclass

from .objects import (
    ColumnExpr,
    DataFrameObj,
    DF_METHODS,
    PxLError,
    ScalarFuncMarker,
)
from .px_module import PxModule


class _ReturnSignal(Exception):
    def __init__(self, value):
        self.value = value


class Scope:
    """Lexical scope chain (VarTable analog, ``objects/var_table.h``)."""

    def __init__(self, parent=None):
        self.vars: dict = {}
        self.parent = parent

    def lookup(self, name: str):
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        raise KeyError(name)

    def assign(self, name: str, value):
        self.vars[name] = value


@dataclass
class _DFMethod:
    """A dataframe/groupby method reference awaiting its call (the Call
    handler injects the source line number)."""

    df: object
    name: str


class PxFunc:
    """A PxL-defined function (vis-spec entry points are these)."""

    def __init__(self, name, args_ast, body, closure, visitor, doc=""):
        self.name = name
        self.args_ast = args_ast
        self.body = body
        self.closure = closure
        self.visitor = visitor
        self.doc = doc

    @property
    def arg_names(self):
        return [a.arg for a in self.args_ast.args]

    def __call__(self, *args, **kwargs):
        v = self.visitor
        scope = Scope(parent=self.closure)
        names = self.arg_names
        defaults = self.args_ast.defaults
        # rightmost defaults align with rightmost args
        default_map = {
            names[len(names) - len(defaults) + i]: v.eval(d, self.closure)
            for i, d in enumerate(defaults)
        }
        if len(args) > len(names):
            raise PxLError(f"{self.name}() takes {len(names)} arguments, "
                           f"{len(args)} given")
        bound = dict(zip(names, args))
        for k, val in kwargs.items():
            if k not in names:
                raise PxLError(f"{self.name}() got unexpected argument {k!r}")
            if k in bound:
                raise PxLError(f"{self.name}() got duplicate argument {k!r}")
            bound[k] = val
        for n in names:
            if n not in bound:
                if n not in default_map:
                    raise PxLError(f"{self.name}() missing argument {n!r}")
                bound[n] = default_map[n]
        scope.vars.update(bound)
        try:
            for stmt in self.body:
                v.exec_stmt(stmt, scope)
        except _ReturnSignal as r:
            return r.value
        return None


_BINOPS = {
    ast.Add: operator.add,
    ast.Sub: operator.sub,
    ast.Mult: operator.mul,
    ast.Div: operator.truediv,
    ast.FloorDiv: operator.floordiv,
    ast.Mod: operator.mod,
    ast.Pow: operator.pow,
    # pandas-style boolean combinators on columns (host ints get Python's
    # bitwise semantics, same operators).
    ast.BitAnd: operator.and_,
    ast.BitOr: operator.or_,
    ast.BitXor: operator.xor,
}

_CMPOPS = {
    ast.Eq: operator.eq,
    ast.NotEq: operator.ne,
    ast.Lt: operator.lt,
    ast.LtE: operator.le,
    ast.Gt: operator.gt,
    ast.GtE: operator.ge,
}

_SAFE_BUILTINS = {
    "len": len, "range": range, "int": int, "float": float, "str": str,
    "bool": bool, "abs": abs, "min": min, "max": max, "round": round,
    "list": list, "dict": dict, "sorted": sorted, "enumerate": enumerate,
    "zip": zip, "sum": sum, "True": True, "False": False, "None": None,
}


class ASTVisitor:
    """Evaluates a PxL module against a PlanBuilder-backed ``px`` module."""

    def __init__(self, px: PxModule, pxtrace=None):
        self.px = px
        # Lazily-built pxtrace module (probes DSL); importing it marks the
        # script as a mutation candidate (probes.h MutationsIR).
        self._pxtrace = pxtrace
        self.module_scope = Scope()
        self.funcs: dict[str, PxFunc] = {}

    @property
    def pxtrace(self):
        if self._pxtrace is None:
            from .pxtrace_module import TraceModule

            self._pxtrace = TraceModule()
        return self._pxtrace

    # -- driver --------------------------------------------------------------
    def run(self, tree: ast.Module):
        for stmt in tree.body:
            self.exec_stmt(stmt, self.module_scope)

    # -- statements ----------------------------------------------------------
    def exec_stmt(self, node, scope: Scope):
        try:
            method = getattr(self, f"_stmt_{type(node).__name__}", None)
            if method is None:
                raise PxLError(
                    f"PxL does not support {type(node).__name__} statements",
                    node.lineno,
                )
            method(node, scope)
        except PxLError:
            raise
        except _ReturnSignal:
            raise
        except Exception as e:  # surface evaluation errors with location
            raise PxLError(f"{type(e).__name__}: {e}", getattr(node, "lineno", None))

    def _stmt_Import(self, node, scope):
        for alias in node.names:
            if alias.name == "px":
                scope.assign(alias.asname or "px", self.px)
            elif alias.name == "pxtrace":
                scope.assign(alias.asname or "pxtrace", self.pxtrace)
            else:
                raise PxLError(
                    f"cannot import {alias.name!r}; only 'px' and 'pxtrace' "
                    "are available",
                    node.lineno,
                )

    def _stmt_ImportFrom(self, node, scope):
        raise PxLError("'from ... import' is not supported; use 'import px'",
                       node.lineno)

    def _stmt_Expr(self, node, scope):
        if isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
            return  # docstring
        self.eval(node.value, scope)

    def _stmt_Pass(self, node, scope):
        pass

    def _stmt_Assign(self, node, scope):
        value = self.eval(node.value, scope)
        for target in node.targets:
            self._assign_target(target, value, scope)

    def _stmt_AnnAssign(self, node, scope):
        if node.value is None:
            return
        self._assign_target(node.target, self.eval(node.value, scope), scope)

    def _stmt_AugAssign(self, node, scope):
        cur = self.eval(_as_load(node.target), scope)
        op = _BINOPS.get(type(node.op))
        if op is None:
            raise PxLError(f"unsupported augmented assignment", node.lineno)
        self._assign_target(node.target, self._binop(op, cur,
                                                     self.eval(node.value, scope),
                                                     node.lineno), scope)

    def _assign_target(self, target, value, scope):
        if isinstance(target, ast.Name):
            scope.assign(target.id, value)
        elif isinstance(target, ast.Attribute):
            obj = self.eval(target.value, scope)
            if not isinstance(obj, DataFrameObj):
                raise PxLError("attribute assignment is only valid on "
                               "dataframes (df.col = expr)", target.lineno)
            obj.set_column(target.attr, value, target.lineno)
        elif isinstance(target, ast.Subscript):
            obj = self.eval(target.value, scope)
            key = self.eval(target.slice, scope)
            if isinstance(obj, DataFrameObj):
                if not isinstance(key, str):
                    raise PxLError("df[...] = expr requires a string column "
                                   "name", target.lineno)
                obj.set_column(key, value, target.lineno)
            else:
                obj[key] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            vals = list(value)
            if len(vals) != len(target.elts):
                raise PxLError("unpacking length mismatch", target.lineno)
            for t, v in zip(target.elts, vals):
                self._assign_target(t, v, scope)
        else:
            raise PxLError(
                f"unsupported assignment target {type(target).__name__}",
                target.lineno,
            )

    def _stmt_FunctionDef(self, node, scope):
        doc = ast.get_docstring(node) or ""
        fn = PxFunc(node.name, node.args, node.body, scope, self, doc)
        for dec in reversed(node.decorator_list):
            wrapper = self.eval(dec, scope)
            if not callable(wrapper):
                raise PxLError(
                    f"decorator on {node.name!r} is not callable", node.lineno
                )
            fn = wrapper(fn)
        scope.assign(node.name, fn)
        if scope is self.module_scope and isinstance(fn, PxFunc):
            self.funcs[node.name] = fn

    def _stmt_Return(self, node, scope):
        raise _ReturnSignal(self.eval(node.value, scope) if node.value else None)

    def _stmt_If(self, node, scope):
        cond = self.eval(node.test, scope)
        body = node.body if _truthy(cond, node.lineno) else node.orelse
        for stmt in body:
            self.exec_stmt(stmt, scope)

    def _stmt_For(self, node, scope):
        it = self.eval(node.iter, scope)
        if isinstance(it, (ColumnExpr, DataFrameObj)):
            raise PxLError("cannot iterate over deferred column/dataframe "
                           "values; loops run at compile time", node.lineno)
        for item in it:
            self._assign_target(node.target, item, scope)
            for stmt in node.body:
                self.exec_stmt(stmt, scope)
        for stmt in node.orelse:
            self.exec_stmt(stmt, scope)

    # -- expressions ---------------------------------------------------------
    def eval(self, node, scope: Scope):
        method = getattr(self, f"_expr_{type(node).__name__}", None)
        if method is None:
            raise PxLError(
                f"PxL does not support {type(node).__name__} expressions",
                getattr(node, "lineno", None),
            )
        return method(node, scope)

    def _expr_Constant(self, node, scope):
        return node.value

    def _expr_Name(self, node, scope):
        try:
            return scope.lookup(node.id)
        except KeyError:
            if node.id in _SAFE_BUILTINS:
                return _SAFE_BUILTINS[node.id]
            raise PxLError(f"name {node.id!r} is not defined", node.lineno)

    def _expr_Attribute(self, node, scope):
        obj = self.eval(node.value, scope)
        attr = node.attr
        if isinstance(obj, DataFrameObj):
            if attr in DF_METHODS:
                return _DFMethod(obj, attr)
            if attr == "ctx":
                return obj.ctx
            if attr == "columns":
                return obj.columns
            return obj.col(attr, node.lineno)
        from .objects import GroupbyObj

        if isinstance(obj, GroupbyObj) and attr == "agg":
            return _DFMethod(obj, "agg")
        if isinstance(obj, PxModule):
            try:
                return getattr(obj, attr)
            except PxLError as e:
                raise PxLError(e.raw_msg, node.lineno)
        from .otel_module import OTelModule, _MetricNamespace, _TraceNamespace
        from .pxtrace_module import TraceModule

        if isinstance(
            obj, (OTelModule, _MetricNamespace, _TraceNamespace, TraceModule)
        ) and not attr.startswith("_"):
            try:
                return getattr(obj, attr)
            except AttributeError:
                raise PxLError(
                    f"{type(obj).__name__} has no attribute {attr!r}",
                    node.lineno,
                ) from None
        raise PxLError(
            f"cannot access attribute {attr!r} on {type(obj).__name__}",
            node.lineno,
        )

    def _expr_Subscript(self, node, scope):
        obj = self.eval(node.value, scope)
        if isinstance(node.slice, ast.Slice):
            if isinstance(obj, (DataFrameObj, ColumnExpr)):
                raise PxLError("slicing is not supported on dataframes; use "
                               "head(n)", node.lineno)
            lo = self.eval(node.slice.lower, scope) if node.slice.lower else None
            hi = self.eval(node.slice.upper, scope) if node.slice.upper else None
            st = self.eval(node.slice.step, scope) if node.slice.step else None
            return obj[slice(lo, hi, st)]
        key = self.eval(node.slice, scope)
        if isinstance(obj, DataFrameObj):
            if isinstance(key, str):
                return obj.col(key, node.lineno)
            if isinstance(key, (list, tuple)):
                return obj.project(list(key), node.lineno)
            if isinstance(key, ColumnExpr):
                return obj.filter(key, node.lineno)
            raise PxLError(
                f"df[...] expects a column name, a list of names, or a "
                f"boolean expression; got {type(key).__name__}", node.lineno)
        try:
            return obj[key]
        except PxLError as e:
            raise PxLError(e.raw_msg, node.lineno)

    def _expr_Call(self, node, scope):
        fn = self.eval(node.func, scope)
        args = [self.eval(a, scope) for a in node.args]
        kwargs = {}
        for kw in node.keywords:
            if kw.arg is None:
                raise PxLError("**kwargs expansion is not supported",
                               node.lineno)
            kwargs[kw.arg] = self.eval(kw.value, scope)
        try:
            if isinstance(fn, _DFMethod):
                return getattr(fn.df, fn.name)(*args, lineno=node.lineno,
                                               **kwargs)
            return fn(*args, **kwargs)
        except PxLError as e:
            if e.lineno is None:
                raise PxLError(e.raw_msg, node.lineno)
            raise
        except _ReturnSignal:
            raise
        except Exception as e:
            raise PxLError(f"{type(e).__name__}: {e}", node.lineno)

    def _binop(self, op, left, right, lineno):
        try:
            return op(left, right)
        except PxLError as e:
            raise PxLError(e.raw_msg, lineno)
        except TypeError as e:
            raise PxLError(str(e), lineno)

    def _expr_BinOp(self, node, scope):
        op = _BINOPS.get(type(node.op))
        if op is None:
            raise PxLError(
                f"unsupported operator {type(node.op).__name__}", node.lineno)
        left = self.eval(node.left, scope)
        right = self.eval(node.right, scope)
        if op is operator.floordiv and (
            isinstance(left, ColumnExpr) or isinstance(right, ColumnExpr)
        ):
            # a // b on columns: floor(divide(a, b))
            div = self._binop(operator.truediv, left, right, node.lineno)
            return ScalarFuncMarker("floor")(div)
        return self._binop(op, left, right, node.lineno)

    def _expr_Compare(self, node, scope):
        left = self.eval(node.left, scope)
        result = None
        for opnode, rnode in zip(node.ops, node.comparators):
            right = self.eval(rnode, scope)
            op = _CMPOPS.get(type(opnode))
            if op is None:
                raise PxLError(
                    f"unsupported comparison {type(opnode).__name__}",
                    node.lineno)
            term = self._binop(op, left, right, node.lineno)
            result = term if result is None else self._combine_bool(
                "logicalAnd", result, term, node.lineno)
            left = right
        return result

    def _combine_bool(self, name, a, b, lineno):
        if isinstance(a, ColumnExpr) or isinstance(b, ColumnExpr):
            return ScalarFuncMarker(name)(a, b)
        return (a and b) if name == "logicalAnd" else (a or b)

    def _expr_BoolOp(self, node, scope):
        is_and = isinstance(node.op, ast.And)
        result = None
        for v in node.values:
            val = self.eval(v, scope)
            if result is None:
                result = val
            else:
                result = self._combine_bool(
                    "logicalAnd" if is_and else "logicalOr", result, val,
                    node.lineno)
            # host short-circuit once the folded value is decided
            if not isinstance(result, ColumnExpr):
                if is_and and not _truthy(result, node.lineno):
                    return result
                if not is_and and _truthy(result, node.lineno):
                    return result
        return result

    def _expr_UnaryOp(self, node, scope):
        val = self.eval(node.operand, scope)
        if isinstance(node.op, ast.Not):
            if isinstance(val, ColumnExpr):
                return ~val
            return not val
        if isinstance(node.op, ast.USub):
            return -val
        if isinstance(node.op, ast.UAdd):
            return +val if not isinstance(val, ColumnExpr) else val
        if isinstance(node.op, ast.Invert):
            return ~val
        raise PxLError("unsupported unary operator", node.lineno)

    def _expr_IfExp(self, node, scope):
        cond = self.eval(node.test, scope)
        if isinstance(cond, ColumnExpr):
            return ScalarFuncMarker("select")(
                cond, self.eval(node.body, scope), self.eval(node.orelse, scope)
            )
        return (self.eval(node.body, scope) if _truthy(cond, node.lineno)
                else self.eval(node.orelse, scope))

    def _expr_List(self, node, scope):
        return [self.eval(e, scope) for e in node.elts]

    def _expr_Tuple(self, node, scope):
        return tuple(self.eval(e, scope) for e in node.elts)

    def _expr_Dict(self, node, scope):
        out = {}
        for k, v in zip(node.keys, node.values):
            if k is None:
                raise PxLError("**dict expansion is not supported", node.lineno)
            out[self.eval(k, scope)] = self.eval(v, scope)
        return out

    def _expr_JoinedStr(self, node, scope):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:  # FormattedValue
                val = self.eval(v.value, scope)
                if isinstance(val, (ColumnExpr, DataFrameObj)):
                    raise PxLError(
                        "f-strings cannot embed column expressions; use "
                        "string UDFs", node.lineno)
                parts.append(format(val, v.format_spec and
                                    self.eval(v.format_spec, scope) or ""))
        return "".join(parts)

    def _expr_ListComp(self, node, scope):
        if len(node.generators) != 1:
            raise PxLError("nested comprehensions are not supported",
                           node.lineno)
        gen = node.generators[0]
        it = self.eval(gen.iter, scope)
        out = []
        child = Scope(parent=scope)
        for item in it:
            self._assign_target(gen.target, item, child)
            if all(_truthy(self.eval(c, child), node.lineno)
                   for c in gen.ifs):
                out.append(self.eval(node.elt, child))
        return out

    def _expr_Lambda(self, node, scope):
        raise PxLError(
            "lambdas are not supported; use px.<func> expressions", node.lineno)


def _truthy(value, lineno) -> bool:
    if isinstance(value, ColumnExpr):
        raise PxLError(
            "column expressions have no compile-time truth value", lineno)
    if isinstance(value, DataFrameObj):
        raise PxLError("dataframes have no compile-time truth value", lineno)
    return bool(value)


def _as_load(node):
    import copy

    n = copy.copy(node)
    n.ctx = ast.Load()
    return n
