"""The ``pxtrace`` PxL module: probe definitions -> tracepoint mutations.

Reference parity: ``src/carnot/planner/probes/probes.h`` (``MutationsIR``)
and the ``pxtrace`` QLObject module — scripts decorate a probe function
with ``@pxtrace.probe(symbol)``, return a list of ``{column: expr}``
dicts, and register it with ``pxtrace.UpsertTracepoint``. Compiling such
a script yields *mutations* instead of (or alongside) a query plan; the
broker's mutation executor deploys them and waits for table readiness
(``mutation_executor.go:84``).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..trace.spec import (
    ProbeDef,
    TraceExpr,
    TracepointDelete,
    TracepointDeployment,
    parse_ttl,
)
from .objects import PxLError

_TYPE_NAMES = {
    "int64": "INT64",
    "float64": "FLOAT64",
    "string": "STRING",
    "boolean": "BOOLEAN",
    "time64ns": "TIME64NS",
}


def _dtype(type_name):
    from ..types.dtypes import DataType

    if type_name is None:
        return DataType.INT64
    key = str(type_name).lower()
    if key not in _TYPE_NAMES:
        raise PxLError(
            f"unknown trace type {type_name!r}; one of {sorted(_TYPE_NAMES)}"
        )
    return DataType[_TYPE_NAMES[key]]


@dataclass
class _ProbeMarker:
    """A @pxtrace.probe-decorated PxL function awaiting UpsertTracepoint."""

    target: str
    fn: object  # PxFunc


class TraceModule:
    """Bound as ``pxtrace`` in script scope; collects mutations."""

    def __init__(self):
        self.mutations: list = []  # TracepointDeployment | TracepointDelete

    # -- decorators / expression constructors ------------------------------
    def probe(self, target: str):
        if not isinstance(target, str) or not target:
            raise PxLError("pxtrace.probe() expects a symbol string")

        def deco(fn):
            return _ProbeMarker(target=target, fn=fn)

        return deco

    def ArgExpr(self, expr: str, type=None) -> TraceExpr:  # noqa: N802
        return TraceExpr("arg", str(expr), _dtype(type))

    def RetExpr(self, expr: str = "", type=None) -> TraceExpr:  # noqa: N802
        return TraceExpr("ret", str(expr), _dtype(type))

    def FunctionLatency(self) -> TraceExpr:  # noqa: N802
        from ..types.dtypes import DataType

        return TraceExpr("latency", "", DataType.INT64)

    # -- mutations ----------------------------------------------------------
    def UpsertTracepoint(self, name, table_name, probe_fn,  # noqa: N802
                         target=None, ttl="10m"):
        if not isinstance(probe_fn, _ProbeMarker):
            raise PxLError(
                "UpsertTracepoint() expects a @pxtrace.probe-decorated "
                "function"
            )
        rows = probe_fn.fn()
        if (
            not isinstance(rows, list)
            or len(rows) != 1
            or not isinstance(rows[0], dict)
        ):
            raise PxLError(
                "a probe function must return a single-element list of "
                "{column: pxtrace expression} (probes.h output spec)"
            )
        outputs = []
        for col, te in rows[0].items():
            if not isinstance(te, TraceExpr):
                raise PxLError(
                    f"probe output {col!r} is not a pxtrace expression"
                )
            outputs.append((str(col), te))
        dep = TracepointDeployment(
            name=str(name),
            table_name=str(table_name),
            probe=ProbeDef(target=probe_fn.target, outputs=tuple(outputs)),
            ttl_s=parse_ttl(ttl),
        )
        self.mutations.append(dep)
        return dep

    def DeleteTracepoint(self, name):  # noqa: N802
        d = TracepointDelete(name=str(name))
        self.mutations.append(d)
        return d
